"""Chaos soak: SLO-asserted compound-fault long-run (ROADMAP item 5).

The crash sweeps prove point-in-time recovery; the cluster harness proves
scale under clean churn.  Nothing before this module proved *steady-state
SLOs while faults compound* — an apiserver flap during a kubelet restart
during a WAL compaction is the production scenario the north star
implies, and this soak is its hermetic reproduction:

- **time compression**: the run is scheduled in *simulated* seconds
  (``compression`` sim-seconds per wall second, default 60×), so a
  two-minute wall run covers hours of simulated churn and every budget
  in the SLO (claim-stuck T, leak grace, recovery windows) is expressed
  in sim time;
- **seeded fault scheduler**: one thread draws faults from a seeded RNG
  and composes the repo's existing injectors —

  ===================  ====================================================
  kind                 what it does
  ===================  ====================================================
  apiserver_latency    ``FakeKube.set_latency`` spike for a sim window
                       (stays active while OTHER faults run: compounding)
  watch_close          ``FakeKube.close_watches`` — every informer stream
                       gets the in-band 410 and must relist (with the
                       shared full-jitter backoff)
  kubelet_restart      a node's kubelet loses its memory mid-flight:
                       re-prepare of a live claim must be idempotent, and
                       a claim whose API object vanished while kubelet was
                       down must be reclaimed by the stale-claim GC
  plugin_crash         ``checkpoint.armed_crash`` raises SimulatedCrash at
                       a random checkpoint boundary (the crash sweeps' six
                       points incl. post-journal-append / mid-compaction),
                       the driver is abandoned (``crash_stop``, no
                       shutdown compaction) and rebuilt over the same dirs
                       through the REAL recovery path
  torn_wal             plugin_crash at post-journal-append plus garbage
                       appended to ``checkpoint.wal`` before restart
                       (power-cut-mid-append recovery, loudly truncated)
  clock_skew           ±10 min wall steps on the shared GC clock while
                       stale-claim GC passes run — the monotonic staleness
                       discipline (tpudra/clock.py) must hold in both
                       directions
  cd_wave              a gang slice reservation (controller/gang.py) is
                       issued through real CD plugin drivers WHILE the
                       other fault windows are live — a bound gang must be
                       all-bound, a failed one must roll back to
                       none-bound, and teardown must converge to zero
                       bound members within the recovery budget; the
                       monitor's quiet-window gang-atomicity invariant
                       holds the residue to "never partial"
  partition_fault      the fractional-chip lifecycle breaks on one node
                       (docs/partitioning.md): partition create fails
                       mid-bind (retryable error, clean retry), the MP
                       control daemon — a REAL process — is SIGKILLed
                       mid-ATTACH, or the destroy leg fails composed with
                       a SIGKILL so only the restarted plugin's recovery
                       sweep can reap the orphan; the node must converge
                       to zero live partitions and zero records, and the
                       monitor's partition-leak invariant holds the
                       record ⟷ hardware bijection in quiet windows
  apiserver_outage     an error plan (``FakeKube.set_error_plan``) makes
                       the apiserver REFUSE — sustained 429-with-
                       Retry-After shedding, 500/503 storms, a fail-once
                       blip, or a full outage window with every watch
                       stream force-closed — composed with whatever
                       latency/disk windows are open; recovery asserts
                       every informer back on a live watch and a fresh
                       bind granted, with every retry routed through the
                       shared backoff honoring the Retry-After floor
  controller_failover  the LEADING controller dies mid-gang-reserve
                       (armed crash + checkpoint abandon + lease elector
                       crash), a standby replica waits out lease expiry
                       and acquires with a strictly larger fencing term,
                       a fresh gang manager converges the gang
                       all-or-nothing under the new term, and a
                       deliberately-REVIVED stale leader's commit must be
                       refused at the checkpoint layer (StaleLeader,
                       counted in the report)
  disk_fault           a storage fault plan (tpudra/storage.py) is
                       installed against ONE node's checkpoint + CDI dirs
                       — ENOSPC on writes, EIO on fsync (fsyncgate),
                       EROFS everywhere (read-only remount), slow-I/O
                       stalls, or a fail-once blip — optionally composed
                       with a SIGKILL mid-fault and a restart storm
                       against the broken dir; the node must enter
                       degraded mode (typed retryable shed errors,
                       storage-degraded slice annotation) with reads and
                       publication alive, every ACKNOWLEDGED mutation
                       must survive the composed crash, and after heal
                       the node must converge back to healthy (probe +
                       compaction rewrite, annotation cleared) within
                       the recovery budget
  ===================  ====================================================

- **continuous invariant monitor**: a thread asserts, every few hundred
  sim-seconds, that no claim sits in a non-terminal phase longer than T,
  that no CDI spec or per-uid flock file outlives its checkpoint record,
  and that published ResourceSlice content reconverges to checkpoint
  truth after every fault window; at finalize the lock-witness log (when
  armed) is merged against the static model — no cycles, no model gaps.
  Every check lands in ``tpudra_soak_invariant_checks_total``.

- **machine-readable SLO report**: JSON with per-fault-window bind
  latency histograms, invariant check/violation counts, and recovery
  times — consumed by ``tools/soak_report.py --assert-slo`` (the ``make
  soak`` exit gate).  Every violation carries the seed and the fault
  timeline up to that instant, and ``--replay <report.json>`` re-executes
  that recorded timeline instead of drawing a fresh one.

Concurrency discipline: the soak's own locks (``chaos.*``) are never held
across a call into driver or kube code — worker threads take them only
for pure bookkeeping (node picking, sample append, window tagging), so
the lock witness sees no soak→driver edges and the static model stays
closed under ``make lockgraph``.
"""

from __future__ import annotations

import argparse
import contextlib
import errno
import json
import logging
import os
import random
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Optional

from tpudra import TPU_DRIVER_NAME, lockwitness, metrics, racewitness, storage, trace
from tpudra.clock import MonotonicAger, SkewedClock
from tpudra.kube import gvr
from tpudra.kube.deadline import api_deadline
from tpudra.kube.errors import ApiError, NotFound
from tpudra.plugin import checkpoint as checkpoint_mod
from tpudra.plugin.checkpoint import PREPARE_STARTED, SimulatedCrash
from tpudra.plugin.resourceslice import SLICE_STORAGE_DEGRADED_ANNOTATION
from tpudra.sim.cluster import (
    ClusterScaleConfig,
    ClusterScaleSim,
    latency_summary,
    make_claim,
)

logger = logging.getLogger(__name__)

#: The checkpoint boundaries the crash injector may arm — the same six
#: points the subprocess crash sweeps kill at (tests/crashharness.POINTS;
#: redeclared here because tpudra must not import from tests/).
CRASH_POINTS = (
    "post-prepare-started",
    "post-mutate",
    "post-cdi",
    "post-completed",
    "post-journal-append",
    "mid-compaction",
)

FAULT_KINDS = (
    "apiserver_latency",
    "watch_close",
    "kubelet_restart",
    "plugin_crash",
    "torn_wal",
    "clock_skew",
    "cd_wave",
    "chip_fault",
    "daemon_crash",
    "disk_fault",
    "partition_fault",
    "apiserver_outage",
    "controller_failover",
)

#: apiserver_outage variants — how the apiserver REFUSES (docs/ha.md):
#: sustained 429-with-Retry-After load shedding, 500 storms, 503 fronting
#: failures, a fail-once 429 blip, or a full outage window (every verb
#: 503 plus forced watch closes).
APISERVER_OUTAGE_VARIANTS = (
    "storm_429",
    "storm_500",
    "storm_503",
    "fail_once_429",
    "full_outage",
)

#: Failover-stack lease timings, in WALL seconds: the lease layer runs in
#: real time (its expiry judgment is the candidates' own monotonic
#: clocks), so these are NOT sim-scaled — at the default 60x they read
#: as 90/18 sim-seconds, comfortably inside the recovery budget.
FAILOVER_LEASE_WALL_S = 1.5
FAILOVER_RENEW_WALL_S = 0.3

#: partition_fault variants — where the fractional-chip lifecycle breaks
#: (docs/partitioning.md): hardware create fails mid-bind, the MP control
#: daemon dies mid-ATTACH, or the destroy leg fails and a SIGKILL lands
#: before anything can repair it (the recovery sweep must).
PARTITION_FAULT_VARIANTS = (
    "create_fail",
    "daemon_crash_mid_attach",
    "destroy_fail_crash",
)

#: disk_fault variants — what the misbehaving disk does (storage.FaultPlan
#: rules scoped to one node's checkpoint + CDI dirs).
DISK_FAULT_VARIANTS = (
    "enospc_write",  # every write fails ENOSPC until heal
    "eio_fsync",     # every fsync fails EIO until heal (fsyncgate)
    "erofs",         # the whole write surface fails EROFS (ro remount)
    "slow_io",       # fsyncs stall; nothing fails
    "enospc_once",   # one write fails ENOSPC mid-append, then recovers
)

#: Invariant label values (METRICS-HYGIENE: one spelling, shared with the
#: metrics docstring and soak_report).
INV_CLAIM_STUCK = "claim-stuck"
INV_CDI_LEAK = "cdi-leak"
INV_FLOCK_LEAK = "flock-leak"
INV_SLICE_CONVERGENCE = "slice-convergence"
INV_LOCK_WITNESS = "lock-witness"
#: Finalize-time merge of the vector-clock race witness log against the
#: static thread/race model (tpudra-racegraph): a witnessed unordered
#: cross-thread write pair or a model gap fails the soak.
INV_RACE_WITNESS = "race-witness"
INV_FAULT_RECOVERY = "fault-recovery"
INV_GANG_ATOMICITY = "gang-atomicity"
#: No quiet-window ResourceSlice may advertise silicon its driver holds
#: unhealthy (the health loop's withhold must actually reach the API).
INV_SLICE_HEALTH = "slice-health"
#: No gang may sit in the degraded/remediating phases past the recovery
#: budget — remediation must converge or release, not linger.
INV_GANG_DEGRADED = "gang-degraded"
#: No bound gang grant may live on a node with faulted silicon after its
#: remediation completed (and none in any quiet window).
INV_GRANT_HEALTH = "grant-health"
#: Every mutate that returned success is present after crash+recovery —
#: disk faults notwithstanding.  Checked with an "anchor" claim bound and
#: acknowledged BEFORE each crash-shaped fault (plugin_crash, torn_wal,
#: disk_fault's composed SIGKILL) and asserted present in the recovered
#: checkpoint afterwards.
INV_ACK_DURABILITY = "acknowledged-mutation-durability"
#: No node may sit in storage-degraded mode past the recovery budget once
#: no disk fault is active — heal detection + the convergent compaction
#: rewrite must bring it back.
INV_STORAGE_DEGRADED = "storage-degraded-convergence"
#: The fractional-chip bijection (docs/partitioning.md): no live partition
#: without a checkpoint explanation (Live record or completed claim
#: grant), and no Live-phase record without its live partition — aged by
#: the leak grace so in-flight create/destroy windows never false-fire.
INV_PARTITION_LEAK = "partition-leak"
#: No two leadership terms may interleave gang WAL commits: the journaled
#: fence record's term history must be strictly increasing (a superseded
#: term committing after its successor is split-brain the checkpoint
#: layer failed to refuse).
INV_SINGLE_WRITER = "single-writer"
#: While the apiserver is up (no outage/latency window open), SOME
#: controller must hold a live, renewing lease within the recovery budget
#: — leader election must never deadlock the control plane.
INV_LEADERSHIP = "leadership-liveness"
INVARIANTS = (
    INV_CLAIM_STUCK,
    INV_CDI_LEAK,
    INV_FLOCK_LEAK,
    INV_SLICE_CONVERGENCE,
    INV_LOCK_WITNESS,
    INV_RACE_WITNESS,
    INV_FAULT_RECOVERY,
    INV_GANG_ATOMICITY,
    INV_SLICE_HEALTH,
    INV_GANG_DEGRADED,
    INV_GRANT_HEALTH,
    INV_ACK_DURABILITY,
    INV_STORAGE_DEGRADED,
    INV_PARTITION_LEAK,
    INV_SINGLE_WRITER,
    INV_LEADERSHIP,
)


@dataclass
class SLOBudget:
    """The soak's pass/fail budgets.  Latency budgets are wall-clock (the
    bind path runs in real time); lifecycle budgets are sim-clock (they
    scale with the compressed schedule)."""

    bind_p99_ms: float = 2000.0
    #: T: max time a claim may sit in a non-terminal phase (sim seconds).
    max_claim_stuck_sim_s: float = 600.0
    #: A CDI spec / flock file with no checkpoint record may exist at most
    #: this long (sim seconds) — covers the in-flight windows.
    leak_grace_sim_s: float = 300.0
    #: Slice content must reconverge to checkpoint truth within this many
    #: sim seconds after the last fault window closes.
    convergence_sim_s: float = 300.0
    #: A crashed node must serve a correct re-prepare within this (sim).
    recovery_sim_s: float = 900.0


@dataclass
class ChaosConfig:
    nodes: int = 4
    chips_per_node: int = 4
    seed: int = 0
    #: Wall-clock run length and the sim-seconds-per-wall-second factor:
    #: 75 s × 60 = 4500 sim seconds = 1.25 simulated hours.
    wall_s: float = 75.0
    compression: float = 60.0
    #: Mean gap between scheduled faults (sim seconds, exponential draw).
    fault_mean_gap_sim_s: float = 180.0
    churn_workers: int = 2
    #: Harness cadences in SIM seconds, so the monitor's sampling rate and
    #: the GC's reclaim latency scale with compression the same way the
    #: budgets they police do (a wall-anchored GC cadence at high
    #: compression would let every orphan blow the sim-time claim-stuck
    #: budget before its first reclaim pass).
    monitor_interval_sim_s: float = 30.0
    gc_interval_sim_s: float = 60.0
    #: Latency-spike RTTs in SIM seconds for the same reason: a
    #: wall-anchored 400 ms RTT is 24 sim-seconds at 60x but 160 at 400x,
    #: which silently re-scales the fault severity against every sim
    #: budget.  3/9/24 sim-s ≙ 50/150/400 ms at the default 60x.
    latency_rtt_sim_choices: tuple = (3.0, 9.0, 24.0)
    fault_kinds: tuple = FAULT_KINDS
    budget: SLOBudget = field(default_factory=SLOBudget)
    #: Arm the lock witness for the run (subprocess/make-soak mode; the
    #: in-process unit tests leave it off so they don't flip the
    #: process-wide witness env).
    witness: bool = False
    report_path: str = "/tmp/tpudra_soak.json"
    #: Replay mode: execute this recorded fault timeline (list of fault
    #: spec dicts) instead of drawing from the RNG.
    replay_timeline: Optional[list] = None


@dataclass
class FaultRecord:
    kind: str
    t_sim_start: float
    t_sim_end: Optional[float] = None
    node: Optional[int] = None
    point: Optional[str] = None
    params: dict = field(default_factory=dict)
    recovered_sim_s: Optional[float] = None

    def spec(self) -> dict:
        """The replayable part: what to inject, not what happened."""
        return {
            "kind": self.kind,
            "t_sim": round(self.t_sim_start, 1),
            "node": self.node,
            "point": self.point,
            "params": self.params,
        }


class _PongServer:
    """Stand-in for the host-0 workload's jax coordinator: accepts on
    loopback and answers ``pong`` — the registered upstream the daemon
    proxy must keep forwarding to across daemon_crash faults."""

    def __init__(self):
        import socket as socket_mod

        self._sock = socket_mod.socket(
            socket_mod.AF_INET, socket_mod.SOCK_STREAM
        )
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._serve, daemon=True, name="soak-pong"
        )
        self._thread.start()

    def _serve(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                conn.sendall(b"pong\n")
            except OSError:
                ...
            finally:
                try:
                    conn.close()
                except OSError:
                    ...

    def stop(self) -> None:
        import socket as socket_mod

        self._stopped.set()
        # shutdown() before close(): close alone does not wake a Linux
        # thread blocked in accept() (the CoordinatorProxy.stop bug this
        # same module's daemon_crash fault surfaced) — without it the
        # soak-pong thread leaks parked in accept() every run.
        try:
            self._sock.shutdown(socket_mod.SHUT_RDWR)
        except OSError:
            ...
        try:
            self._sock.close()
        except OSError:
            ...


class SimClock:
    """Wall → simulated time: ``now_sim() = elapsed_wall × compression``."""

    def __init__(self, compression: float):
        self.compression = compression
        self._t0 = time.monotonic()

    def now_sim(self) -> float:
        return (time.monotonic() - self._t0) * self.compression

    def wall_of(self, sim_seconds: float) -> float:
        return sim_seconds / self.compression


class ChaosSoak:
    """One soak run over a ClusterScaleSim.  ``run()`` blocks for
    ``config.wall_s`` and returns the report dict."""

    def __init__(self, config: ChaosConfig):
        self.config = config
        self.budget = config.budget
        self._rng = random.Random(config.seed)
        self._gc_clock = SkewedClock()
        if config.witness:
            os.environ[lockwitness.ENV_WITNESS] = "1"
            os.environ.setdefault(
                lockwitness.ENV_WITNESS_LOG,
                os.path.join(
                    os.path.dirname(config.report_path) or ".",
                    "soak-lock-witness.jsonl",
                ),
            )
            lockwitness.reset_for_tests()
            # The race witness rides along: with the lock witness armed the
            # sampled locksets are real, so the finalize merge can tell a
            # guarded access from a racing one.
            os.environ[racewitness.ENV_WITNESS] = "1"
            os.environ.setdefault(
                racewitness.ENV_WITNESS_LOG,
                os.path.join(
                    os.path.dirname(config.report_path) or ".",
                    "soak-race-witness.jsonl",
                ),
            )
            racewitness.reset_for_tests()
            # The finalize merges assert THIS run's schedule against the
            # model at THIS commit; the logs are O_APPEND (crash-safe for
            # the sweeps' multi-process harnesses, which get a fresh tmp
            # dir per test), so a leftover from a prior soak would replay
            # stale lock ids into the gap check.  Start clean.
            for stale in (lockwitness.log_path(), racewitness.log_path()):
                try:
                    os.remove(stale)
                except FileNotFoundError:
                    pass
        # The soak runs with the fractional-chip gates ON (partition_fault
        # needs dynamic partitions + multi-process sharing) over a
        # partitionable generation — the gates COMPOSE by design
        # (featuregates.validate, docs/partitioning.md).  Process-global:
        # `make soak` is its own process; the in-process unit tests reset
        # gates per test (conftest's autouse fixture).
        from tpudra import featuregates

        featuregates.feature_gates().set_from_map(
            {
                featuregates.DYNAMIC_PARTITIONING: True,
                featuregates.MULTI_PROCESS_SHARING: True,
            }
        )
        self.sim = ClusterScaleSim(
            ClusterScaleConfig(
                nodes=config.nodes,
                chips_per_node=config.chips_per_node,
                generation="v5p",  # partitionable (v5e's fused core is not)
                seed=config.seed,
                workers=max(4, config.churn_workers * 2),
                compute_domains=2,
                gc_clock=self._gc_clock,
            )
        )
        self.simclock = SimClock(config.compression)
        self._stop = threading.Event()

        # -- shared soak state.  The condition serializes node picking /
        # quarantine / in-flight accounting; the plain locks guard the
        # sample and record sinks.  NONE of them is ever held across a
        # call into driver or kube code (module docstring).
        self._churn_cond = lockwitness.make_condition("chaos.churn_cond")
        self._quarantine: set[int] = set()
        self._inflight: dict[int, int] = {i: 0 for i in range(config.nodes)}
        self._churn_gate_open = True
        self._samples_lock = lockwitness.make_lock("chaos.samples_lock")
        self._bind_samples: list[tuple[float, float, str]] = []  # (t_sim, ms, tag)
        self._bind_errors: list[tuple[float, str, str]] = []  # (t_sim, tag, err)
        self._records_lock = lockwitness.make_lock("chaos.records_lock")
        self._timeline: list[FaultRecord] = []
        self._active: dict[str, FaultRecord] = {}
        self._latency_end_sim: Optional[float] = None
        self._latency_record: Optional[FaultRecord] = None
        self._violations: list[dict] = []
        self._violated_keys: set = set()
        self._checks: dict[str, dict[str, int]] = {
            inv: {"ok": 0, "violation": 0} for inv in INVARIANTS
        }
        self._stuck_ager = MonotonicAger()
        self._leak_ager = MonotonicAger()
        # Partition-leak aging is separate from the file-leak ager: the
        # two checks prune independently, and a shared table would drop
        # each other's keys every pass (resetting every age to zero).
        self._partition_ager = MonotonicAger()
        # First pass through the kinds is a seeded shuffle of ALL of them:
        # a short run must still exercise every enabled injector at least
        # once (soak_report asserts it), and a plain choice() leaves that
        # to luck.  Draws after the cycle are uniform.
        self._kind_cycle: list[str] = list(config.fault_kinds)
        self._rng.shuffle(self._kind_cycle)
        self._max_stuck_sim = 0.0
        self._recovery_samples: list[float] = []
        self._fault_counter = 0
        self._anomalies: list[str] = []
        # -- cd_wave stack: per-node CD plugin drivers + one gang manager,
        # built lazily by the FAULT THREAD on the first cd_wave (node
        # construction is kube/syscall work — never under a soak lock).
        # The monitor thread only reads the references (atomic in Python).
        self._cd_drivers: dict[str, object] = {}
        self._gang_mgr = None
        self._gang_cp = None
        self._cd_wave_seq = 0
        self._cd_wave_inflight = 0  # guarded by _records_lock
        # Degraded-gang age tracking for INV_GANG_DEGRADED.
        self._degraded_ager = MonotonicAger()
        # Storage-degraded age tracking for INV_STORAGE_DEGRADED: a node
        # only ages while NO disk fault is active (while one is, being
        # degraded is the correct state).
        self._storage_ager = MonotonicAger()
        # -- daemon stack (chip_fault's sibling blast radius): a supervised
        # dummy slice daemon under the REAL ProcessManager watchdog (full-
        # jitter restart backoff) plus a REAL CoordinatorProxy forwarding
        # to a registered upstream — daemon_crash SIGKILLs the child /
        # bounces the proxy while other fault windows stay open.  Fault
        # thread only.
        self._daemon_pm = None
        self._daemon_stop: Optional[threading.Event] = None
        self._daemon_proxy = None
        self._daemon_upstream: Optional[object] = None
        self._daemon_dir: Optional[str] = None
        # -- controller failover stack (docs/ha.md): one lease elector per
        # "controller replica" identity over the shared kube, the ACTIVE
        # one supplying the gang manager's fencing term.  Built with the
        # cd stack (fault thread only; the monitor reads the references
        # atomically and tolerates mid-swap windows).
        self._elector = None
        self._elector_seq = 0
        self._elector_stop: Optional[threading.Event] = None
        self._gang_term: Optional[int] = None
        self._stale_rejections = 0  # guarded by _records_lock
        self._stale_probes_run = 0  # guarded by _records_lock
        self._failover_samples_sim: list[float] = []  # time-to-new-leader
        self._lease_ager = MonotonicAger()

    # ------------------------------------------------------------- plumbing

    def _now(self) -> float:
        return self.simclock.now_sim()

    def _current_tag(self) -> str:
        with self._records_lock:
            active = sorted(self._active)
        return "+".join(active) if active else "quiet"

    def _record_fault(self, record: FaultRecord) -> None:
        metrics.SOAK_FAULTS_INJECTED_TOTAL.labels(record.kind).inc()
        with self._records_lock:
            self._timeline.append(record)
            self._active[record.kind] = record

    def _end_fault(self, record: FaultRecord) -> None:
        record.t_sim_end = self._now()
        with self._records_lock:
            if self._active.get(record.kind) is record:
                del self._active[record.kind]

    def _check(self, invariant: str, ok: bool, key=None, detail: str = "") -> None:
        """Count one invariant evaluation; a violation (deduped per key)
        dumps the seed + fault timeline needed to replay it."""
        result = "ok" if ok else "violation"
        metrics.SOAK_INVARIANT_CHECKS_TOTAL.labels(invariant, result).inc()
        with self._records_lock:
            self._checks[invariant][result] += 1
            if ok or (invariant, key) in self._violated_keys:
                return
            self._violated_keys.add((invariant, key))
            self._violations.append(
                {
                    "invariant": invariant,
                    "key": repr(key),
                    "t_sim": round(self._now(), 1),
                    "detail": detail,
                    "replay": {
                        "seed": self.config.seed,
                        "timeline": [r.spec() for r in self._timeline],
                    },
                    # The flight recorder: what the system was DOING when
                    # the invariant broke — recent spans (newest first,
                    # tpudra/trace.py ring) next to the seed + timeline
                    # that replay it.  [] when the soak ran untraced.
                    "spans": trace.recent_spans(200),
                }
            )
        logger.error("SOAK INVARIANT VIOLATION [%s] %r: %s", invariant, key, detail)

    def _check_or_interrupted(
        self, invariant: str, ok: bool, key, detail: str, what: str
    ) -> None:
        """A fault-tail assertion the run's END can interrupt (recovery
        waits, heal convergence): a bad outcome with ``_stop`` set means
        the contract is unfinished, not broken — reported as an anomaly,
        never a violation.  Every injector tail goes through here so the
        guard cannot drift per fault kind."""
        if not ok and self._stop.is_set():
            self._anomaly(f"{what} interrupted by run end")
            return
        self._check(invariant, ok, key=key, detail=detail)

    def _pass_check(self, invariant: str) -> None:
        """Count one 'ok' evaluation for a completed scan pass: candidate
        objects count individually on top, but a pass that found nothing
        to examine still asserted the invariant over the whole cluster —
        'checks' in the report must reflect continuous evaluation, not
        just how many suspicious objects happened to exist."""
        metrics.SOAK_INVARIANT_CHECKS_TOTAL.labels(invariant, "ok").inc()
        with self._records_lock:
            self._checks[invariant]["ok"] += 1

    def _anomaly(self, msg: str) -> None:
        """Something off-script that is not an invariant violation (e.g. a
        crash arm that never fired) — reported, not failed."""
        logger.warning("soak anomaly: %s", msg)
        with self._records_lock:
            self._anomalies.append(msg)

    # ---------------------------------------------- node reservation (churn)

    def _acquire_node(self, rng: random.Random) -> Optional[int]:
        with self._churn_cond:
            candidates = [
                i
                for i in range(self.config.nodes)
                if i not in self._quarantine and self._churn_gate_open
            ]
            if not candidates:
                return None
            node = rng.choice(candidates)
            self._inflight[node] += 1
            return node

    def _release_node(self, node: int) -> None:
        with self._churn_cond:
            self._inflight[node] -= 1
            self._churn_cond.notify_all()

    def _quarantine_node(self, node: int, timeout: float = 30.0) -> None:
        """Reserve a node for the fault thread: churn skips it and any
        in-flight op drains first — which also guarantees the fault thread
        leads its own group commits on that node's checkpoint (an armed
        in-process crashpoint must fire on the armed thread)."""
        deadline = time.monotonic() + timeout
        with self._churn_cond:
            self._quarantine.add(node)
            while self._inflight[node] > 0 and time.monotonic() < deadline:
                self._churn_cond.wait(0.1)

    def _unquarantine_node(self, node: int) -> None:
        with self._churn_cond:
            self._quarantine.discard(node)
            self._churn_cond.notify_all()

    def _close_churn_gate(self, timeout: float = 30.0) -> bool:
        """Stop new churn and wait for in-flight ops to drain; True when
        fully drained.  Generous timeout: one op under a compounding
        latency window can span several stacked 5 s api_deadline phases."""
        deadline = time.monotonic() + timeout
        with self._churn_cond:
            self._churn_gate_open = False
            while (
                any(self._inflight[i] > 0 for i in range(self.config.nodes))
                and time.monotonic() < deadline
            ):
                self._churn_cond.wait(0.1)
            return not any(
                self._inflight[i] > 0 for i in range(self.config.nodes)
            )

    def _open_churn_gate(self) -> None:
        with self._churn_cond:
            self._churn_gate_open = True
            self._churn_cond.notify_all()

    # ----------------------------------------------------------------- churn

    def _churn_loop(self, worker: int) -> None:
        """One sustained-churn worker: create → resolve → prepare →
        unprepare → delete, forever, on chips 1..N-1 (chip 0 of every node
        is the fault injectors' reserved slot).  Workers partition the
        chip space so they never contend on silicon; every apiserver step
        runs under a deadline so a latency spike degrades to typed,
        retryable errors instead of wedged threads."""
        rng = random.Random((self.config.seed << 8) ^ worker)
        chips = [
            c
            for c in range(1, self.config.chips_per_node)
            if (c - 1) % self.config.churn_workers == worker
        ]
        if not chips:
            return
        seq = 0
        while not self._stop.is_set():
            node = self._acquire_node(rng)
            if node is None:
                self._stop.wait(0.05)
                continue
            try:
                chip = rng.choice(chips)
                uid = f"soak-{worker}-{seq}"
                seq += 1
                self._one_bind(node, chip, uid)
            finally:
                self._release_node(node)

    def _one_bind(self, node: int, chip: int, uid: str) -> None:
        driver = self.sim.drivers[node]
        node_name = self.sim.node_names[node]
        claim = make_claim(uid, node_name, [f"tpu-{chip}"], name=uid)
        tag = self._current_tag()
        t_sim = self._now()
        t0 = time.perf_counter()
        prepared = False
        created = False
        try:
            with api_deadline(5.0):
                self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                created = True
                resolved = driver.sockets.resolve_claim("default", uid, uid)
                resp = driver.prepare_resource_claims([resolved])
            err = resp["claims"][uid].get("error")
            if err:
                raise ApiError(f"prepare: {err}")
            prepared = True
            dt_ms = (time.perf_counter() - t0) * 1000.0
            with api_deadline(5.0):
                resp = driver.unprepare_resource_claims([{"uid": uid}])
            err = resp["claims"][uid].get("error")
            if err:
                raise ApiError(f"unprepare: {err}")
            prepared = False
            with self._samples_lock:
                self._bind_samples.append((t_sim, dt_ms, tag))
        except ApiError as e:
            # Expected under fault windows (deadline 504s, latency-failed
            # verbs): recorded, cleaned up, and — when cleanup itself is
            # beaten by the fault — left for the stale-claim GC, which the
            # invariant monitor then holds to its budget.
            with self._samples_lock:
                self._bind_errors.append((t_sim, tag, str(e)[:120]))
            if prepared:
                self._best_effort_unprepare(driver, uid)
        except Exception as e:  # noqa: BLE001 — a worker death would end churn
            logger.exception("soak churn op %s failed unexpectedly", uid)
            self._anomaly(f"churn op {uid}: {e}")
            with self._samples_lock:
                self._bind_errors.append((t_sim, tag, f"unexpected: {e}"[:120]))
            if prepared:
                self._best_effort_unprepare(driver, uid)
        finally:
            if created:
                try:
                    with api_deadline(5.0):
                        self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
                except (NotFound, ApiError):
                    ...  # GC reclaims the record; cascade covers the object

    def _best_effort_unprepare(self, driver, uid: str) -> None:
        try:
            with api_deadline(5.0):
                driver.unprepare_resource_claims([{"uid": uid}])
        except Exception:  # noqa: BLE001 — the GC is the backstop
            logger.info("soak: best-effort unprepare of %s failed", uid)

    # ------------------------------------------------------ fault injectors

    def _fault_loop(self) -> None:
        """Draw (or replay) faults until the run ends; between faults, run
        stale-claim GC passes round-robin so the GC path is continuously
        live (the clock_skew fault then steps the clock under it)."""
        replay_mode = self.config.replay_timeline is not None
        replay = list(self.config.replay_timeline or [])
        gc_interval_wall = self.simclock.wall_of(self.config.gc_interval_sim_s)
        next_gc_wall = time.monotonic()
        gc_node = 0
        while not self._stop.is_set():
            if replay_mode:
                if not replay:
                    # Timeline replayed to the end: no fresh draws — idle
                    # (GC cadence only) so the run reproduces, not extends.
                    spec = None
                    gap_sim = 60.0
                else:
                    spec = replay.pop(0)
                    gap_sim = max(0.0, spec["t_sim"] - self._now())
            else:
                spec = None
                gap_sim = self._rng.expovariate(
                    1.0 / self.config.fault_mean_gap_sim_s
                )
            deadline = time.monotonic() + self.simclock.wall_of(gap_sim)
            while time.monotonic() < deadline and not self._stop.is_set():
                self._maybe_clear_latency()
                if time.monotonic() >= next_gc_wall:
                    next_gc_wall = time.monotonic() + gc_interval_wall
                    gc_node = (gc_node + 1) % self.config.nodes
                    self._gc_pass(gc_node)
                self._stop.wait(min(0.1, max(0.01, gc_interval_wall / 2)))
            if self._stop.is_set():
                break
            if replay_mode and spec is None:
                continue
            try:
                self._inject(spec)
            except Exception as e:  # noqa: BLE001 — one fault must not end the soak
                logger.exception("fault injection failed")
                self._anomaly(f"fault injection raised: {e}")
        self._maybe_clear_latency(force=True)

    def _inject(self, spec: Optional[dict]) -> None:
        if spec is None:
            if self._kind_cycle:
                kind = self._kind_cycle.pop(0)
            else:
                kind = self._rng.choice(list(self.config.fault_kinds))
            node = self._rng.randrange(self.config.nodes)
            point = self._rng.choice(CRASH_POINTS)
            params: dict = {}
            if kind == "apiserver_latency":
                params = {
                    "rtt_sim_s": self._rng.choice(
                        list(self.config.latency_rtt_sim_choices)
                    ),
                    "window_sim_s": self._rng.uniform(60, 300),
                }
            elif kind == "clock_skew":
                params = {"skew_s": self._rng.choice([-600.0, 600.0])}
            elif kind == "cd_wave":
                params = {
                    "nodes": sorted(
                        self._rng.sample(
                            range(self.config.nodes),
                            min(2, self.config.nodes),
                        )
                    )
                }
            elif kind == "daemon_crash":
                params = {
                    "target": self._rng.choice(["slicewatchd", "coordproxy"])
                }
            elif kind == "partition_fault":
                params = {
                    "variant": self._rng.choice(
                        list(PARTITION_FAULT_VARIANTS)
                    )
                }
            elif kind == "apiserver_outage":
                variant = self._rng.choice(list(APISERVER_OUTAGE_VARIANTS))
                params = {
                    "variant": variant,
                    # Sustained storms stay open for a sim window (short
                    # enough that a composed full outage undershoots the
                    # failover stack's lease grace at default compression);
                    # fail-once keeps a short window so churn can consume
                    # the per-verb blips before heal clears them.
                    "window_sim_s": (
                        self._rng.uniform(10.0, 20.0)
                        if variant == "fail_once_429"
                        else self._rng.uniform(30.0, 60.0)
                    ),
                    "retry_after_sim_s": self._rng.choice([1.0, 3.0, 6.0]),
                }
            elif kind == "disk_fault":
                variant = self._rng.choice(list(DISK_FAULT_VARIANTS))
                params = {
                    "variant": variant,
                    # Only the fail-until-healed variants compose a SIGKILL
                    # mid-fault / a restart storm against the broken dir.
                    "compose_crash": variant
                    in ("enospc_write", "eio_fsync", "erofs")
                    and self._rng.random() < 0.6,
                    "restart_storm": self._rng.random() < 0.5,
                    "window_sim_s": self._rng.uniform(60, 180),
                }
        else:
            kind = spec["kind"]
            node = spec.get("node") or 0
            point = spec.get("point") or "post-journal-append"
            params = dict(spec.get("params") or {})
        self._fault_counter += 1
        logger.info(
            "soak fault #%d: %s node=%s point=%s params=%s (t_sim=%.0f)",
            self._fault_counter, kind, node, point, params, self._now(),
        )
        if kind == "apiserver_latency":
            self._inject_latency(params)
        elif kind == "watch_close":
            self._inject_watch_close()
        elif kind == "kubelet_restart":
            self._inject_kubelet_restart(node)
        elif kind == "plugin_crash":
            self._inject_crash(node, point, torn=False)
        elif kind == "torn_wal":
            self._inject_crash(node, "post-journal-append", torn=True)
        elif kind == "clock_skew":
            self._inject_clock_skew(params)
        elif kind == "cd_wave":
            self._inject_cd_wave(params)
        elif kind == "chip_fault":
            self._inject_chip_fault(node)
        elif kind == "daemon_crash":
            self._inject_daemon_crash(params)
        elif kind == "disk_fault":
            self._inject_disk_fault(node, params)
        elif kind == "partition_fault":
            self._inject_partition_fault(node, params)
        elif kind == "apiserver_outage":
            self._inject_apiserver_outage(node, params)
        elif kind == "controller_failover":
            self._inject_controller_failover(params)
        else:
            self._anomaly(f"unknown fault kind {kind!r}")

    def _inject_latency(self, params: dict) -> None:
        record = FaultRecord(
            kind="apiserver_latency", t_sim_start=self._now(), params=params
        )
        # Overlapping spikes are routine (windows up to 300 sim-s, mean
        # gap 180): the new spike supersedes the old WINDOW, so close the
        # displaced record first — a forever-open record would make every
        # later quiet-window computation see an active fault and silently
        # disable the slice-convergence checks.
        with self._records_lock:
            prev = (
                self._latency_record
                if self._latency_end_sim is not None
                else None
            )
        if prev is not None:
            self._end_fault(prev)
        self._record_fault(record)
        rtt_wall = self.simclock.wall_of(params["rtt_sim_s"])
        record.params["rtt_wall_ms"] = round(rtt_wall * 1000.0, 1)
        self.sim.kube.set_latency(rtt_wall)
        # The window stays OPEN while the scheduler moves on to the next
        # fault — this is where compounding comes from (a crash or a
        # kubelet restart lands inside the spike).
        with self._records_lock:
            self._latency_end_sim = self._now() + params["window_sim_s"]
            self._latency_record = record

    def _maybe_clear_latency(self, force: bool = False) -> None:
        with self._records_lock:
            end = self._latency_end_sim
        if end is None or (self._now() < end and not force):
            return
        self.sim.kube.set_latency(0.0)
        with self._records_lock:
            self._latency_end_sim = None
            record = getattr(self, "_latency_record", None)
        if record is not None:
            self._end_fault(record)

    def _inject_watch_close(self) -> None:
        record = FaultRecord(kind="watch_close", t_sim_start=self._now())
        self._record_fault(record)
        closed = self.sim.kube.close_watches()
        record.params["streams_closed"] = closed
        # Recovery: every node's claim informer back to a live watch.
        deadline = time.monotonic() + self.simclock.wall_of(
            self.budget.recovery_sim_s
        )
        informers = [
            d.claim_informer
            for d in self.sim.drivers
            if d.claim_informer is not None
        ]
        while time.monotonic() < deadline:
            if all(inf.watch_healthy for inf in informers):
                break
            time.sleep(0.05)
        recovered = all(inf.watch_healthy for inf in informers)
        self._end_fault(record)
        record.recovered_sim_s = (
            record.t_sim_end - record.t_sim_start if recovered else None
        )
        if recovered:
            self._recovery_samples.append(record.recovered_sim_s)
        self._check(
            INV_FAULT_RECOVERY,
            recovered,
            key=("watch_close", self._fault_counter),
            detail="an informer watch never recovered after a forced close",
        )

    def _retry_prepare(self, node: int, claim: dict, budget_sim: float) -> bool:
        """Kubelet's retry loop: re-prepare until granted or the sim
        budget runs out (faults may be compounding — each attempt runs
        under its own deadline and backs off with full jitter)."""
        from tpudra.backoff import Backoff

        driver_getter = lambda: self.sim.drivers[node]  # noqa: E731
        uid = claim["metadata"]["uid"]
        # Module-global jitter source, NOT the schedule rng: retry counts
        # vary with wall timing, and feeding them from self._rng would let
        # timing noise shift every later fault draw — the seed must pin
        # the fault sequence, not the backoff jitter.
        backoff = Backoff(0.1, 2.0)
        deadline = time.monotonic() + self.simclock.wall_of(budget_sim)
        while time.monotonic() < deadline and not self._stop.is_set():
            try:
                with api_deadline(5.0):
                    resp = driver_getter().prepare_resource_claims([claim])
                entry = resp["claims"].get(uid, {})
                if entry.get("devices"):
                    return True
                if entry.get("error") and entry.get("permanent"):
                    return False
            except ApiError:
                ...  # deadline/latency: retry below
            # The backoff sleep is wall time; cap it in SIM terms too so a
            # high-compression run's retry loop gets more than a couple of
            # attempts inside its sim-time recovery budget.
            time.sleep(
                min(
                    backoff.next_delay(),
                    0.5,
                    max(0.02, self.simclock.wall_of(30.0)),
                )
            )
        return False

    def _inject_kubelet_restart(self, node: int) -> None:
        """The kubelet-restart scenario, compressed: a kubelet that dies
        between prepare and its own bookkeeping, then restarts.  Two
        consequences must both hold (sim/kubelet.py's retry semantics):
        the restarted kubelet's blind re-prepare of a live claim is
        idempotent (same grant, no double-bind), and a claim whose API
        object was deleted while kubelet was down is reclaimed by the
        stale-claim GC — not leaked, not double-freed."""
        record = FaultRecord(
            kind="kubelet_restart", t_sim_start=self._now(), node=node
        )
        self._record_fault(record)
        self._quarantine_node(node)
        t0_sim = self._now()
        try:
            driver = self.sim.drivers[node]
            node_name = self.sim.node_names[node]
            uid = f"soak-kr-{self._fault_counter}"
            claim = make_claim(uid, node_name, ["tpu-0"], name=uid)
            with api_deadline(5.0):
                self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            ok = self._retry_prepare(node, claim, self.budget.recovery_sim_s / 2)
            # kubelet "restarts": its memory is gone; it re-prepares every
            # pod claim it rediscovers.  The grant must come back without
            # error (idempotent cached path).
            redo = self._retry_prepare(node, claim, self.budget.recovery_sim_s / 2)
            self._check_or_interrupted(
                INV_FAULT_RECOVERY,
                ok and redo,
                key=("kubelet_restart", self._fault_counter),
                detail="re-prepare after simulated kubelet restart not idempotent",
                what=f"kubelet_restart recovery on node {node}",
            )
            # The pod was force-deleted while kubelet was down: the API
            # object vanishes with no unprepare.  The stale-claim GC must
            # reclaim the checkpoint record.
            with api_deadline(5.0):
                self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
            reclaimed = 0
            deadline = time.monotonic() + self.simclock.wall_of(
                self.budget.recovery_sim_s
            )
            while time.monotonic() < deadline:
                reclaimed = self._gc_pass(node)
                if uid not in driver.state.prepared_claim_uids():
                    break
                time.sleep(0.1)
            record.params["gc_reclaimed"] = reclaimed
            self._check(
                INV_FAULT_RECOVERY,
                uid not in driver.state.prepared_claim_uids(),
                key=("kubelet_restart_gc", self._fault_counter),
                detail="orphaned claim not reclaimed by stale-claim GC",
            )
        finally:
            self._unquarantine_node(node)
            self._end_fault(record)
            record.recovered_sim_s = record.t_sim_end - t0_sim
            self._recovery_samples.append(record.recovered_sim_s)

    def _inject_crash(self, node: int, point: str, torn: bool) -> None:
        """SIGKILL-equivalent at a checkpoint boundary, then recovery
        through the real restart path — optionally with a torn WAL tail
        injected before the restart (the power-cut-mid-append shape)."""
        record = FaultRecord(
            kind="torn_wal" if torn else "plugin_crash",
            t_sim_start=self._now(),
            node=node,
            point=point,
        )
        self._record_fault(record)
        self._quarantine_node(node)
        t0_sim = self._now()
        uid = f"soak-crash-{self._fault_counter}"
        anchor: Optional[str] = None
        try:
            driver = self.sim.drivers[node]
            node_name = self.sim.node_names[node]
            # An acknowledged bind BEFORE the crash: whatever boundary the
            # armed claim dies at, this one's success was reported — it
            # must be in the recovered checkpoint (INV_ACK_DURABILITY).
            anchor = self._bind_anchor(node)
            claim = make_claim(uid, node_name, ["tpu-0"], name=uid)
            with api_deadline(5.0):
                self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            if point == "mid-compaction":
                # Force a compaction on the armed commit, the same lever
                # the subprocess sweep pulls via TPUDRA_JOURNAL_MAX_RECORDS
                # (the abandoned instance never needs the old value back).
                driver._checkpoints._journal_max_records = 1
            from tpudra.backoff import Backoff

            crashed = False
            resolve_backoff = Backoff(0.1, 1.0)
            for _ in range(5):
                try:
                    with checkpoint_mod.armed_crash(point):
                        with api_deadline(5.0):
                            resolved = driver.sockets.resolve_claim(
                                "default", uid, uid
                            )
                            driver.prepare_resource_claims([resolved])
                    break  # prepare finished without reaching the boundary
                except SimulatedCrash:
                    crashed = True
                    break
                except ApiError:
                    # Latency spike beat the resolve; jittered retry
                    # (APISERVER-RETRY: never a constant).
                    time.sleep(resolve_backoff.next_delay())
            if not crashed:
                self._anomaly(
                    f"crash arm at {point} on node {node} never fired"
                )
            if torn:
                wal = os.path.join(
                    self.sim._base, f"p{node}", "checkpoint.wal"
                )
                with open(wal, "ab") as f:
                    f.write(b"\xff\xff\x00\x00SOAK-TORN-TAIL")
            # The process "dies": abandon without the shutdown compaction,
            # then restart over the same dirs — the REAL recovery path.
            self.sim.crash_node(node)
            self.sim.restart_node(node)
            if anchor is not None:
                self._check_ack_durability(node, anchor, f"{record.kind}@{point}")
            recovered = self._retry_prepare(
                node, claim, self.budget.recovery_sim_s
            )
            self._check_or_interrupted(
                INV_FAULT_RECOVERY,
                recovered,
                key=(record.kind, self._fault_counter),
                detail=(
                    f"claim did not converge to a grant after a crash at "
                    f"{point} (torn={torn})"
                ),
                what=f"{record.kind} recovery on node {node}",
            )
            self._best_effort_unprepare(self.sim.drivers[node], uid)
        finally:
            try:
                with api_deadline(5.0):
                    self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
            except (NotFound, ApiError):
                ...
            if anchor is not None:
                self._release_anchor(node, anchor)
            self._unquarantine_node(node)
            self._end_fault(record)
            record.recovered_sim_s = record.t_sim_end - t0_sim
            self._recovery_samples.append(record.recovered_sim_s)

    # -------------------------------------------- acknowledged-bind anchors

    def _bind_anchor(self, node: int) -> Optional[str]:
        """Bind one claim that STAYS bound across the upcoming fault — the
        acknowledged mutation INV_ACK_DURABILITY tracks through
        crash+recovery.  The node is quarantined (churn drained) when this
        runs; chips 1..N-1 are tried in order because chip 0 is the fault
        injectors' working slot and a churn straggler may still hold a
        higher chip.  None when no chip binds (the check is then skipped
        for this fault, not faked)."""
        driver = self.sim.drivers[node]
        node_name = self.sim.node_names[node]
        for chip in range(1, self.config.chips_per_node):
            uid = f"soak-anchor-{self._fault_counter}-{chip}"
            claim = make_claim(uid, node_name, [f"tpu-{chip}"], name=uid)
            try:
                with api_deadline(5.0):
                    self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                    resolved = driver.sockets.resolve_claim("default", uid, uid)
                    resp = driver.prepare_resource_claims([resolved])
                if not resp["claims"][uid].get("error"):
                    return uid
            except Exception:  # noqa: BLE001 — latency/conflict: next chip
                logger.info(
                    "anchor bind on node %d chip %d failed", node, chip,
                    exc_info=True,
                )
            with contextlib.suppress(NotFound, ApiError):
                with api_deadline(5.0):
                    self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
        return None

    def _check_ack_durability(self, node: int, uid: str, when: str) -> None:
        """Assert one acknowledged claim is present in the node's RECOVERED
        checkpoint view (the real recovery path: snapshot + journal replay
        + torn-tail truncation)."""
        try:
            present = uid in self.sim.drivers[node].state.prepared_claim_uids()
        except Exception:  # noqa: BLE001 — mid-restart window: skip, don't fake
            logger.info("ack-durability probe on node %d skipped", node, exc_info=True)
            return
        self._check(
            INV_ACK_DURABILITY,
            present,
            key=(uid, when),
            detail=(
                f"acknowledged claim {uid} missing from node {node}'s "
                f"checkpoint after {when}"
            ),
        )

    def _release_anchor(self, node: int, uid: str) -> None:
        self._best_effort_unprepare(self.sim.drivers[node], uid)
        try:
            with api_deadline(5.0):
                self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
        except (NotFound, ApiError):
            ...  # GC reclaims the record; cascade covers the object

    # ----------------------------------------------------------- disk_fault

    def _disk_fault_rules(self, node: int, variant: str) -> list[dict]:
        """FaultPlan rule kwargs for one variant, scoped to the node's
        checkpoint (p{node}) and CDI (c{node}) dirs.  The trailing slash
        keeps /p1/ from matching /p12/ (and the CD stack's cdw-p1/)."""
        scopes = [f"/p{node}/", f"/c{node}/"]
        if variant == "enospc_write":
            return [
                dict(op="write", path=s, err=errno.ENOSPC, times=None)
                for s in scopes
            ]
        if variant == "eio_fsync":
            return [
                dict(op="fsync", path=s, err=errno.EIO, times=None)
                for s in scopes
            ]
        if variant == "erofs":
            erofs = errno.EROFS
            return [
                dict(op=op, path=s, err=erofs, times=None)
                for s in scopes
                for op in ("open", "write", "fsync", "fsync_dir", "replace", "truncate")
            ]
        if variant == "slow_io":
            # Stall every fsync on the node; nothing fails.  0.15 s wall
            # per fsync keeps a multi-fsync bind well inside the p99
            # budget while being very visible in the window histogram.
            return [
                dict(op="fsync", path=s, err=None, times=None, delay_s=0.15)
                for s in scopes
            ]
        # enospc_once: one real mid-append tear — a frame prefix lands,
        # then the device gives up; the journal's poison rollback (or the
        # next commit's good-frame repair) must leave a clean boundary.
        return [
            dict(
                op="write", path=f"/p{node}/",
                err=errno.ENOSPC, times=1, partial_bytes=7,
            )
        ]

    def _inject_disk_fault(self, node: int, params: dict) -> None:
        """The misbehaving-disk scenario (docs/chaos.md): a storage fault
        plan against one node's checkpoint + CDI dirs, optionally composed
        with a SIGKILL mid-fault and a restart storm against the broken
        dir.  Asserts the whole degraded-mode contract: fail-fast typed
        shedding, reads/publication alive, acknowledged-mutation
        durability across the composed crash, and heal convergence
        (degraded flag dropped, storage-degraded annotation cleared, a
        fresh bind granted) within the recovery budget."""
        variant = params.get("variant", "enospc_write")
        failing = variant in ("enospc_write", "eio_fsync", "erofs")
        record = FaultRecord(
            kind="disk_fault", t_sim_start=self._now(), node=node,
            params=dict(params),
        )
        self._record_fault(record)
        self._quarantine_node(node)
        node_name = self.sim.node_names[node]
        plan = storage.FaultPlan()
        anchor: Optional[str] = None
        heal_t_sim: Optional[float] = None
        try:
            anchor = self._bind_anchor(node)
            for kw in self._disk_fault_rules(node, variant):
                plan.add(**kw)
            storage.install_fault_plan(plan)
            if failing:
                self._drive_node_degraded(node, record)
                if params.get("compose_crash"):
                    # SIGKILL mid-fault; optionally a restart storm, every
                    # restart recovering against the STILL-BROKEN dir —
                    # reads must work (the recovery view is read-only) and
                    # the acknowledged anchor must be in it.
                    self.sim.crash_node(node)
                    if params.get("restart_storm"):
                        self.sim.restart_node(node)
                        self.sim.crash_node(node)
                    self.sim.restart_node(node)
                    if anchor is not None:
                        self._check_ack_durability(
                            node, anchor, f"disk_fault({variant})+crash"
                        )
                # Open window: churn sheds against the broken node.
                self._unquarantine_node(node)
                self._stop.wait(
                    self.simclock.wall_of(params.get("window_sim_s", 60.0))
                )
                self._quarantine_node(node)
            else:
                # Non-failing variants: binds must still SUCCEED while the
                # fault is live (a stall or a single blip is retryable,
                # not an outage).
                uid = f"soak-df-{self._fault_counter}-live"
                claim = make_claim(uid, node_name, ["tpu-0"], name=uid)
                with api_deadline(5.0):
                    self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                ok = self._retry_prepare(
                    node, claim, self.budget.recovery_sim_s / 2
                )
                self._check_or_interrupted(
                    INV_FAULT_RECOVERY,
                    ok,
                    key=("disk_fault_live", self._fault_counter),
                    detail=(
                        f"bind did not converge under non-failing disk "
                        f"fault {variant}"
                    ),
                    what=f"disk_fault live-bind probe on node {node}",
                )
                self._best_effort_unprepare(self.sim.drivers[node], uid)
                with contextlib.suppress(NotFound, ApiError):
                    with api_deadline(5.0):
                        self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
                self._unquarantine_node(node)
                self._stop.wait(
                    self.simclock.wall_of(
                        min(30.0, params.get("window_sim_s", 30.0))
                    )
                )
                self._quarantine_node(node)
        finally:
            plan.heal()
            storage.clear_fault_plan()
            heal_t_sim = self._now()
            try:
                recovered = self._await_storage_heal(node, record)
                self._check_or_interrupted(
                    INV_FAULT_RECOVERY,
                    recovered,
                    key=("disk_fault", self._fault_counter),
                    detail=(
                        f"node {node} did not converge back to healthy "
                        f"binds after disk fault {variant} healed"
                    ),
                    what=f"disk_fault heal wait on node {node}",
                )
                if anchor is not None:
                    self._check_ack_durability(
                        node, anchor, f"disk_fault({variant})+heal"
                    )
                    self._release_anchor(node, anchor)
            finally:
                self._unquarantine_node(node)
                self._end_fault(record)
                record.recovered_sim_s = record.t_sim_end - heal_t_sim
                self._recovery_samples.append(record.recovered_sim_s)

    def _drive_node_degraded(self, node: int, record: FaultRecord) -> None:
        """Push bind attempts at the faulted node until its driver flips
        into degraded mode, then sample the fail-fast shed path: the typed
        retryable error must come back without touching flock/checkpoint
        (bounded latency, recorded in the fault record)."""
        driver_of = lambda: self.sim.drivers[node]  # noqa: E731 — crash may swap it
        node_name = self.sim.node_names[node]
        # Wall floor on the sim-derived deadline: at high compression the
        # sim budget can shrink below the heal supervisor's own wall-time
        # probe cadence, which would turn compression into fault severity.
        deadline = time.monotonic() + max(
            self.simclock.wall_of(self.budget.recovery_sim_s / 2), 5.0
        )
        seq = 0
        while (
            not driver_of().storage_degraded
            and time.monotonic() < deadline
            and not self._stop.is_set()
        ):
            uid = f"soak-df-{self._fault_counter}-p{seq}"
            seq += 1
            claim = make_claim(uid, node_name, ["tpu-0"], name=uid)
            try:
                with api_deadline(5.0):
                    self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                    resolved = driver_of().sockets.resolve_claim("default", uid, uid)
                    driver_of().prepare_resource_claims([resolved])
            except ApiError:
                ...  # latency window beat the resolve; try again
            finally:
                with contextlib.suppress(NotFound, ApiError):
                    with api_deadline(5.0):
                        self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
            time.sleep(0.05)
        degraded = driver_of().storage_degraded
        record.params["degraded_observed"] = degraded
        if not degraded:
            self._anomaly(
                f"disk_fault on node {node} never flipped the driver "
                "storage-degraded"
            )
            return
        # Shed-path sample: while degraded, every batch is refused up
        # front with the typed prefix — time a few.
        shed_ms: list[float] = []
        uid = f"soak-df-{self._fault_counter}-shed"
        ref = {"metadata": {"uid": uid, "namespace": "default", "name": uid}}
        for _ in range(5):
            t0 = time.perf_counter()
            resp = driver_of().prepare_resource_claims([ref])
            shed_ms.append((time.perf_counter() - t0) * 1000.0)
            err = resp["claims"].get(uid, {}).get("error", "")
            if storage.DEGRADED_ERROR_PREFIX not in err:
                self._anomaly(
                    f"degraded node {node} shed without the typed "
                    f"storage-degraded error: {err[:120]!r}"
                )
                break
        if shed_ms:
            record.params["shed_max_ms"] = round(max(shed_ms), 3)

    def _await_storage_heal(self, node: int, record: FaultRecord) -> bool:
        """After heal: degraded flag dropped, the storage-degraded slice
        annotation cleared, and a fresh bind granted — all within the
        recovery budget."""
        # Same wall floor as _drive_node_degraded: the heal supervisor
        # probes on wall-time backoff (≤2 s), which a high-compression sim
        # budget must not undercut.
        deadline = time.monotonic() + max(
            self.simclock.wall_of(self.budget.recovery_sim_s / 2), 5.0
        )
        while time.monotonic() < deadline and not self._stop.is_set():
            try:
                if not self.sim.drivers[node].storage_degraded:
                    break
            except Exception:  # noqa: BLE001 — mid-restart window
                logger.info(
                    "degraded probe on node %d mid-restart", node, exc_info=True
                )
            time.sleep(0.1)
        else:
            return False
        node_name = self.sim.node_names[node]
        annotation_clear = False
        while time.monotonic() < deadline and not self._stop.is_set():
            if not self._node_slices_flag_degraded(node_name):
                annotation_clear = True
                break
            time.sleep(0.1)
        record.params["annotation_cleared"] = annotation_clear
        uid = f"soak-df-{self._fault_counter}-heal"
        claim = make_claim(uid, node_name, ["tpu-0"], name=uid)
        try:
            with api_deadline(5.0):
                self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
        except ApiError:
            return False
        granted = self._retry_prepare(node, claim, self.budget.recovery_sim_s / 2)
        self._best_effort_unprepare(self.sim.drivers[node], uid)
        with contextlib.suppress(NotFound, ApiError):
            with api_deadline(5.0):
                self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
        return granted and annotation_clear

    def _node_slices_flag_degraded(self, node_name: str) -> bool:
        try:
            listing = self.sim.kube.list(gvr.RESOURCE_SLICES)
        except ApiError:
            return True  # can't tell: keep waiting
        for item in listing.get("items", []):
            spec = item.get("spec", {})
            if (
                spec.get("driver") == TPU_DRIVER_NAME
                and spec.get("nodeName") == node_name
                and item.get("metadata", {})
                .get("annotations", {})
                .get(SLICE_STORAGE_DEGRADED_ANNOTATION)
                == "true"
            ):
                return True
        return False

    def _inject_clock_skew(self, params: dict) -> None:
        """Step the shared GC wall clock ±10 min and run live stale-claim
        GC passes under the skew.  With churn drained (gate closed), every
        checkpointed claim has a live API object, so ANY unprepare here is
        a premature GC — the failure the monotonic discipline forbids."""
        record = FaultRecord(
            kind="clock_skew", t_sim_start=self._now(), params=params
        )
        self._record_fault(record)
        drained = self._close_churn_gate()
        try:
            if not drained:
                # A churn op outlived the drain (compounding latency can
                # stack several deadline windows): the zero-collection
                # assertion would misattribute that op's genuine orphan to
                # the skew.  Step the clock and run the passes anyway —
                # the claim-stuck/leak monitors still police the outcome —
                # but don't assert the count.
                self._anomaly(
                    "clock_skew: churn did not drain; skew GC passes ran "
                    "unasserted"
                )
                self._gc_clock.wall_skew_s = params["skew_s"]
                for i in range(self.config.nodes):
                    self._gc_pass(i)
                return
            # Drain genuine orphans (a churn op whose cleanup a fault beat)
            # UNskewed first: with the gate closed and this thread the only
            # fault source, anything the skewed passes then collect can
            # only be skew-induced.
            for i in range(self.config.nodes):
                self._gc_pass(i)
            self._gc_clock.wall_skew_s = params["skew_s"]
            collected = sum(
                self._gc_pass(i) for i in range(self.config.nodes)
            )
            record.params["collected_under_skew"] = collected
            self._check(
                INV_FAULT_RECOVERY,
                collected == 0,
                key=("clock_skew", self._fault_counter),
                detail=(
                    f"stale-claim GC unprepared {collected} live claim(s) "
                    f"under {params['skew_s']:+.0f}s wall skew"
                ),
            )
        finally:
            self._gc_clock.wall_skew_s = 0.0
            self._open_churn_gate()
            self._end_fault(record)

    # ------------------------------------------------- apiserver error storm

    def _inject_apiserver_outage(self, node: int, params: dict) -> None:
        """The apiserver REFUSES (docs/ha.md): a per-verb error plan —
        429-with-Retry-After shedding, 500/503 storms, a fail-once blip,
        or a full outage window with every watch stream force-closed —
        composed with whatever latency/disk windows are already open.
        After heal: every informer back on a live watch and a fresh bind
        granted within the recovery budget, with no hot-spin having
        occurred (every retry routed through the shared backoff honoring
        the Retry-After floor is what the client layers are FOR)."""
        from tpudra.kube.fake import ApiErrorPlan

        variant = params.get("variant") or "storm_503"
        record = FaultRecord(
            kind="apiserver_outage", t_sim_start=self._now(),
            params=dict(params),
        )
        self._record_fault(record)
        t0_sim = self._now()
        retry_after_wall = self.simclock.wall_of(
            params.get("retry_after_sim_s", 1.0)
        )
        plan = ApiErrorPlan()
        if variant == "storm_429":
            plan.fail(verb="*", code=429, retry_after_s=retry_after_wall)
        elif variant == "storm_500":
            plan.fail(verb="*", code=500)
        elif variant == "storm_503":
            plan.fail(verb="*", code=503, retry_after_s=retry_after_wall)
        elif variant == "fail_once_429":
            for verb in ("get", "list", "create", "update", "delete"):
                plan.fail(
                    verb=verb, code=429, times=1,
                    retry_after_s=retry_after_wall,
                )
        else:  # full_outage
            plan.outage(retry_after_s=retry_after_wall)
        self.sim.kube.set_error_plan(plan)
        try:
            if variant == "full_outage":
                record.params["streams_closed"] = self.sim.kube.close_watches()
            if variant == "fail_once_429":
                # Deterministically consume one blip: without a probe, a
                # quiet-churn window could reach heal with every times=1
                # rule unconsumed — a fault counted as injected that
                # exercised nothing (the no-op the gate must not accept).
                with contextlib.suppress(ApiError):
                    with api_deadline(3.0):
                        self.sim.kube.list(gvr.RESOURCE_CLAIMS, "default")
            self._stop.wait(
                self.simclock.wall_of(params.get("window_sim_s", 0.0))
            )
        finally:
            plan.heal()
            self.sim.kube.set_error_plan(None)
            record.params["requests_refused"] = plan.injected
            if plan.injected < 1:
                self._anomaly(
                    f"apiserver_outage({variant}) refused zero requests"
                )
        # Recovery: every node informer back to a live watch...
        deadline = time.monotonic() + self.simclock.wall_of(
            self.budget.recovery_sim_s
        )
        informers = [
            d.claim_informer
            for d in self.sim.drivers
            if d.claim_informer is not None
        ]
        while time.monotonic() < deadline and not self._stop.is_set():
            if all(inf.watch_healthy for inf in informers):
                break
            time.sleep(0.05)
        watches_ok = all(inf.watch_healthy for inf in informers)
        # ... and a fresh bind granted on the drawn node.
        self._quarantine_node(node)
        try:
            uid = f"soak-outage-{self._fault_counter}"
            claim = make_claim(
                uid, self.sim.node_names[node], ["tpu-0"], name=uid
            )
            bound = False
            try:
                with api_deadline(5.0):
                    self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
                bound = self._retry_prepare(
                    node, claim, self.budget.recovery_sim_s / 2
                )
            except ApiError:
                logger.info("outage recovery probe create failed", exc_info=True)
            self._check_or_interrupted(
                INV_FAULT_RECOVERY,
                watches_ok and bound,
                key=("apiserver_outage", self._fault_counter),
                detail=(
                    f"control plane did not reconverge after {variant} "
                    f"(watches_ok={watches_ok}, bind_granted={bound})"
                ),
                what="apiserver_outage recovery",
            )
            if bound:
                self._best_effort_unprepare(self.sim.drivers[node], uid)
            with contextlib.suppress(NotFound, ApiError):
                with api_deadline(5.0):
                    self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
        finally:
            self._unquarantine_node(node)
            self._end_fault(record)
            record.recovered_sim_s = record.t_sim_end - t0_sim
            # Sample only genuine recoveries (same predicate as the
            # invariant): a run that never re-granted a bind must not
            # feed the recovery percentiles it just violated.
            if watches_ok and bound:
                self._recovery_samples.append(record.recovered_sim_s)

    # --------------------------------------------------- controller failover

    def _inject_controller_failover(self, params: dict) -> None:
        """The ISSUE 14 failover scenario end to end: SIGKILL-shaped crash
        of the LEADING controller mid-gang-reserve (durable intent, first
        member bound), a standby replica acquires the lease after expiry,
        a fresh gang-manager incarnation under the NEW term converges the
        gang all-or-nothing via recover(), and a deliberately-REVIVED
        stale leader's commit is refused at the checkpoint layer
        (single-writer's stale-leader leg + the report's
        ``tpudra_gang_stale_leader_rejections_total``)."""
        from tpudra.controller.gang import (
            GangMember,
            GangReservationManager,
            StaleLeader,
        )
        from tpudra.plugin.checkpoint import CheckpointManager
        from tpudra.sim.multihost import make_channel_claim

        self._ensure_cd_stack()
        record = FaultRecord(
            kind="controller_failover", t_sim_start=self._now(),
            params=dict(params),
        )
        self._record_fault(record)
        t0_sim = self._now()
        n_fault = self._fault_counter
        gang_id = f"soak-fo-{n_fault}"
        domain_uid = f"{gang_id}-uid"
        idxs = list(range(min(2, self.config.nodes)))
        nodes = [self.sim.node_names[i] for i in idxs]
        members = [
            GangMember(node=n, claim_uid=f"{gang_id}-m{k}")
            for k, n in enumerate(nodes)
        ]
        claims = {
            m.claim_uid: make_channel_claim(m.claim_uid, m.node, domain_uid)
            for m in members
        }
        old_term = self._gang_term
        old_elector = self._elector
        record.params["old_term"] = old_term
        gang_dir = os.path.join(self.sim._base, "cdw-gangs")
        try:
            try:
                self._create_cd_objects(gang_id, domain_uid, nodes, claims)
            except ApiError as e:
                record.params["aborted"] = str(e)[:120]
                return
            self._await_cd_ready(gang_id)
            # THE CRASH: the leader dies mid-gang-reserve — intent
            # journaled, first member durably bound, rest in flight.
            crashed = False
            try:
                with checkpoint_mod.armed_crash("mid-gang-reserve"):
                    self._gang_mgr.reserve(gang_id, members, claims)
            except SimulatedCrash:
                crashed = True
            except Exception as e:  # noqa: BLE001 — a fault window won the race
                record.params["reserve_error"] = f"{type(e).__name__}: {e}"[:120]
            if not crashed:
                self._anomaly(
                    f"controller_failover #{n_fault}: crash arm never fired"
                )
            self._gang_cp.abandon()
            if old_elector is not None:
                old_elector.crash()
            # THE FAILOVER: a fresh replica identity waits out the lease
            # expiry and acquires with a strictly larger term.
            standby = self._start_controller_elector()
            fenced = standby is not None
            if fenced:
                record.params["new_term"] = standby.term
                self._failover_samples_sim.append(self._now() - t0_sim)
                self._check(
                    INV_LEADERSHIP,
                    standby.term > (old_term or 0),
                    key=("term-advance", n_fault),
                    detail=(
                        f"standby acquired with term {standby.term}, not "
                        f"above the dead leader's {old_term}"
                    ),
                )
            # THE RECOVERY: a new manager incarnation over the same dir,
            # under the new term, converges the gang all-or-nothing.
            new_cp = CheckpointManager(gang_dir)
            new_mgr = GangReservationManager(
                new_cp, self._gang_binder, term=self._gang_term
            )
            deadline = time.monotonic() + self.simclock.wall_of(
                self.budget.recovery_sim_s
            )
            converged = False
            # Mirror Controller._leader_startup: the new leader's first
            # act claims the store, so the fence outranks the dead term
            # even when the crashed reserve never journaled (a fault
            # window winning the race leaves nothing to recover — without
            # the claim, the stale probe below would be ACCEPTED against
            # the old leader's own high-water mark and false-fail
            # single-writer with a REAL split-brain bind).
            claimed = False
            while time.monotonic() < deadline and not self._stop.is_set():
                try:
                    if not claimed:
                        new_mgr.claim_store()
                        claimed = True
                    gangs = new_mgr.gangs()
                    if gang_id not in gangs:
                        if self._bound_gang_members(members) == 0:
                            converged = True
                            break
                    elif gangs[gang_id].phase == "bound":
                        if self._bound_gang_members(members) == len(members):
                            converged = True
                            break
                        new_mgr.release(gang_id)
                    else:
                        new_mgr.recover()
                except Exception:  # noqa: BLE001 — retried under open fault windows
                    logger.info("failover recovery retry", exc_info=True)
                time.sleep(0.05)
            self._check_or_interrupted(
                INV_GANG_ATOMICITY,
                converged,
                key=("failover", n_fault),
                detail=(
                    f"gang {gang_id} not all-or-nothing after controller "
                    f"failover ({self._bound_gang_members(members)}/"
                    f"{len(members)} members bound)"
                ),
                what="controller_failover gang recovery",
            )
            # THE REVIVED STALE LEADER: an incarnation still carrying the
            # dead term (a paused process resuming) MUST be refused at the
            # WAL — the split-brain write the fence exists to stop.  Only
            # probe once the new term actually claimed the store: an
            # unclaimed store (storage faults held every commit off) makes
            # at-or-below acceptance of the old term CORRECT, not a bug.
            if not claimed:
                record.params["stale_probe_skipped"] = "store never claimed"
                self._anomaly(
                    f"controller_failover #{n_fault}: store never claimed "
                    "under the new term; stale-leader probe skipped"
                )
            if fenced and old_term is not None and claimed:
                with self._records_lock:
                    self._stale_probes_run += 1
                refused = False
                revived_cp = CheckpointManager(gang_dir)
                try:
                    revived = GangReservationManager(
                        revived_cp, self._gang_binder, term=old_term
                    )
                    revived.reserve(
                        f"{gang_id}-stale",
                        [members[0]],
                        {members[0].claim_uid: claims[members[0].claim_uid]},
                    )
                except StaleLeader:
                    refused = True
                    with self._records_lock:
                        self._stale_rejections += 1
                except Exception as e:  # noqa: BLE001 — wrong refusal shape = violation below
                    record.params["stale_probe_error"] = (
                        f"{type(e).__name__}: {e}"[:120]
                    )
                finally:
                    revived_cp.abandon()
                self._check(
                    INV_SINGLE_WRITER,
                    refused,
                    key=("stale-leader", n_fault),
                    detail=(
                        "a revived stale leader's gang commit was NOT "
                        "refused with StaleLeader at the checkpoint layer"
                    ),
                )
            # Swap the new incarnation in for every later wave.
            self._gang_cp = new_cp
            self._gang_mgr = new_mgr
            if converged:
                self._recovery_samples.append(self._now() - t0_sim)
        finally:
            self._delete_cd_objects(gang_id, claims)
            self._end_fault(record)
            record.recovered_sim_s = record.t_sim_end - t0_sim

    # ------------------------------------------------------------- cd wave

    def _ensure_cd_stack(self) -> None:
        """Build the CD plugin drivers + gang manager on first use (fault
        thread only; ROADMAP item 5's "run the CD stack inside the soak").
        The CD drivers share the soak's accounted kube and its fault
        surface — latency spikes and watch closes hit their prepares."""
        if self._gang_mgr is not None:
            return
        from tpudra.controller.gang import GangReservationManager
        from tpudra.plugin.checkpoint import CheckpointManager
        from tpudra.sim.multihost import DriverGangBinder, build_cd_stack

        base = self.sim._base
        drivers = build_cd_stack(
            self.sim.kube,
            self.sim.node_names,
            base,
            num_hosts=self.config.nodes,
            prefix="cdw",
        )

        inner = DriverGangBinder(drivers)

        class _DeadlineBinder:
            """Every member bind/unbind under its own apiserver deadline,
            so a latency spike degrades a gang to a typed, rolled-back
            failure instead of a wedged fault thread."""

            def bind(self, member, claim):
                with api_deadline(5.0):
                    inner.bind(member, claim)

            def unbind(self, member):
                with api_deadline(5.0):
                    inner.unbind(member)

        self._gang_cp = CheckpointManager(os.path.join(base, "cdw-gangs"))
        self._gang_binder = _DeadlineBinder()
        # Leadership first: the gang manager is FENCED from its first
        # commit (controller_failover later bumps the term; single-writer
        # audits the journaled history).  An elector that cannot acquire
        # inside the budget (a latency window swallowing its writes) is an
        # anomaly and the stack runs unfenced rather than wedging.
        self._start_controller_elector()
        self._gang_mgr = GangReservationManager(
            self._gang_cp, self._gang_binder, term=self._gang_term
        )
        self._cd_drivers = drivers

    def _start_controller_elector(self):
        """Start the next controller-replica elector and wait (bounded)
        for it to lead; adopts its fencing term.  Returns the elector (or
        None on timeout, reported as an anomaly)."""
        from tpudra.controller.lease import LeaseElector

        if self._elector_stop is None:
            self._elector_stop = threading.Event()
        self._elector_seq += 1
        elector = LeaseElector(
            self.sim.kube,
            identity=f"soak-ctrl-{self._elector_seq}",
            name="soak-controller",
            namespace=self.sim.config.driver_namespace,
            lease_duration_s=FAILOVER_LEASE_WALL_S,
            renew_interval_s=FAILOVER_RENEW_WALL_S,
        )
        elector.start(self._elector_stop)
        deadline = time.monotonic() + max(
            self.simclock.wall_of(self.budget.recovery_sim_s / 2), 5.0
        )
        while time.monotonic() < deadline and not self._stop.is_set():
            if elector.is_leader:
                self._elector = elector
                self._gang_term = elector.term
                return elector
            time.sleep(0.02)
        # Kill the timed-out candidate: left running it would eventually
        # acquire as an untracked ghost and starve every later failover's
        # standby out of its acquisition window.
        elector.crash()
        self._anomaly(
            f"controller elector {elector.identity} never acquired the "
            "lease; gang stack running unfenced"
        )
        return None

    def _close_cd_stack(self) -> None:
        from tpudra.sim.multihost import close_cd_stack

        if self._elector_stop is not None:
            self._elector_stop.set()
        close_cd_stack(self._cd_drivers)
        if self._gang_cp is not None:
            try:
                self._gang_cp.close()
            except Exception:  # noqa: BLE001
                logger.exception("gang checkpoint close failed")

    def _bound_gang_members(self, members) -> int:
        n = 0
        for m in members:
            d = self._cd_drivers.get(m.node)
            if d is not None and m.claim_uid in d.state.prepared_claim_uids():
                n += 1
        return n

    def _inject_cd_wave(self, params: dict) -> None:
        """One gang reservation while whatever other fault windows are
        open stay open — the compounding scenario ROADMAP item 5 names
        ("informers suffer watch flaps while CD waves are in flight").
        The wave's own contract: whatever the outcome (bound, rolled
        back, rollback needing retries), the gang converges to zero bound
        members within the recovery budget; the quiet-window monitor then
        holds the steady state to "never partial"."""
        from tpudra.controller.gang import (
            GangBindError,
            GangMember,
            GangRollbackIncomplete,
        )
        from tpudra.sim.multihost import make_channel_claim, make_compute_domain

        self._ensure_cd_stack()
        idxs = [
            i for i in (params.get("nodes") or [0]) if i < self.config.nodes
        ] or [0]
        self._cd_wave_seq += 1
        wave = self._cd_wave_seq
        gang_id = f"soak-cdw-{wave}"
        domain_uid = f"{gang_id}-uid"
        record = FaultRecord(
            kind="cd_wave", t_sim_start=self._now(), params={"nodes": idxs}
        )
        self._record_fault(record)
        with self._records_lock:
            self._cd_wave_inflight += 1
        t0_sim = self._now()
        nodes = [self.sim.node_names[i] for i in idxs]
        members = [
            GangMember(node=n, claim_uid=f"{gang_id}-m{k}")
            for k, n in enumerate(nodes)
        ]
        claims = {
            m.claim_uid: make_channel_claim(m.claim_uid, m.node, domain_uid)
            for m in members
        }
        try:
            try:
                self._create_cd_objects(gang_id, domain_uid, nodes, claims)
            except ApiError as e:
                # The wave lost to a latency window before any member
                # could bind: nothing reserved, nothing to assert.
                record.params["aborted"] = str(e)[:120]
                return
            self._await_cd_ready(gang_id)
            try:
                self._gang_mgr.reserve(gang_id, members, claims)
                record.params["outcome"] = "bound"
                n_bound = self._bound_gang_members(members)
                self._check(
                    INV_GANG_ATOMICITY,
                    n_bound == len(members),
                    key=("wave-bound", wave),
                    detail=(
                        f"gang reported bound with {n_bound}/{len(members)} "
                        "members actually bound"
                    ),
                )
            except GangBindError:
                record.params["outcome"] = "rolled-back"
                n_bound = self._bound_gang_members(members)
                self._check(
                    INV_GANG_ATOMICITY,
                    n_bound == 0,
                    key=("wave-rollback", wave),
                    detail=(
                        f"rolled-back gang left {n_bound}/{len(members)} "
                        "members bound"
                    ),
                )
            except GangRollbackIncomplete:
                # A fault beat the rollback mid-teardown; the convergence
                # loop below retries through recover().
                record.params["outcome"] = "rollback-incomplete"

            # Teardown-to-zero: whatever happened, the wave must converge
            # to no gang record and no bound members inside the budget.
            deadline = time.monotonic() + self.simclock.wall_of(
                self.budget.recovery_sim_s
            )
            converged = False
            while time.monotonic() < deadline and not self._stop.is_set():
                try:
                    gangs = self._gang_mgr.gangs()
                    if gang_id not in gangs:
                        if self._bound_gang_members(members) == 0:
                            converged = True
                            break
                    elif gangs[gang_id].phase == "bound":
                        self._gang_mgr.release(gang_id)
                    else:
                        self._gang_mgr.recover()
                except Exception:  # noqa: BLE001 — retried under faults
                    logger.info("cd_wave teardown retry", exc_info=True)
                time.sleep(0.05)
            self._check(
                INV_FAULT_RECOVERY,
                converged,
                key=("cd_wave", self._fault_counter),
                detail="gang did not converge to zero bound members",
            )
            if converged:
                self._recovery_samples.append(self._now() - t0_sim)
        finally:
            self._delete_cd_objects(gang_id, claims)
            with self._records_lock:
                self._cd_wave_inflight -= 1
            self._end_fault(record)
            record.recovered_sim_s = record.t_sim_end - t0_sim

    # ------------------------------------------- CD object lifecycle helpers

    def _create_cd_objects(
        self, gang_id: str, domain_uid: str, nodes: list[str], claims: dict
    ) -> None:
        """Create the CD + clique CR + member channel claims for one gang
        (shared by cd_wave and chip_fault).  The clique CR plays the
        per-node daemons' role; the LIVE soak controller aggregates it
        into CD Ready status — the real readiness path the channel
        prepare gates on.  Raises ApiError when a latency window wins."""
        from tpudra.sim.multihost import make_compute_domain

        with api_deadline(5.0):
            # Start hygiene: a previous gang whose label GC a fault beat
            # would fail this gang's add_node_label — sweep OUR label off
            # the member nodes first (the controller's sweep_stale_labels
            # analog; only soak domains ever set it here).
            self._sweep_cd_labels(nodes)
            self.sim.kube.create(
                gvr.COMPUTE_DOMAINS,
                # ready=False: the LIVE soak controller owns the status.
                make_compute_domain(gang_id, domain_uid, nodes, ready=False),
                "default",
            )
            self.sim.kube.create(
                gvr.COMPUTE_DOMAIN_CLIQUES,
                {
                    "apiVersion": "resource.tpu.google.com/v1beta1",
                    "kind": "ComputeDomainClique",
                    "metadata": {
                        "name": f"{gang_id}-clique",
                        "namespace": self.sim.config.driver_namespace,
                    },
                    "spec": {"computeDomainUID": domain_uid},
                    "status": {
                        "daemons": [
                            {
                                "nodeName": n,
                                "ipAddress": "127.0.0.1",
                                "cliqueID": f"{gang_id}.0",
                                "index": k,
                                "status": "Ready",
                            }
                            for k, n in enumerate(nodes)
                        ]
                    },
                },
                self.sim.config.driver_namespace,
            )
            for claim in claims.values():
                self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")

    def _await_cd_ready(self, gang_id: str) -> None:
        """Wait (bounded) for the controller's clique aggregation to mark
        the CD Ready.  A fault window outliving the wait just means the
        gang binds into the readiness gate and rolls back — atomicity is
        still asserted."""
        ready_deadline = time.monotonic() + self.simclock.wall_of(
            self.budget.recovery_sim_s / 2
        )
        while time.monotonic() < ready_deadline and not self._stop.is_set():
            try:
                with api_deadline(3.0):
                    cd = self.sim.kube.get(
                        gvr.COMPUTE_DOMAINS, gang_id, "default"
                    )
                if cd.get("status", {}).get("status") == "Ready":
                    return
            except (NotFound, ApiError):
                ...
            time.sleep(0.02)

    def _delete_cd_objects(self, gang_id: str, claims: dict) -> None:
        for claim in claims.values():
            try:
                with api_deadline(5.0):
                    self.sim.kube.delete(
                        gvr.RESOURCE_CLAIMS, claim["metadata"]["uid"], "default"
                    )
            except (NotFound, ApiError):
                ...
        try:
            with api_deadline(5.0):
                self.sim.kube.delete(
                    gvr.COMPUTE_DOMAIN_CLIQUES,
                    f"{gang_id}-clique",
                    self.sim.config.driver_namespace,
                )
        except (NotFound, ApiError):
            ...
        try:
            with api_deadline(5.0):
                self.sim.kube.delete(gvr.COMPUTE_DOMAINS, gang_id, "default")
        except (NotFound, ApiError):
            ...

    # ----------------------------------------------------------- chip fault

    # ------------------------------------------------------ partition_fault

    @staticmethod
    def _partition_claim(uid: str, node_name: str, sharing: bool) -> dict:
        """An allocated claim for TWO fractional partitions of the
        reserved chip 0 (the fault injectors' slot), with the opaque
        TpuPartitionConfig — MultiProcess-shared for the daemon variant."""
        claim = make_claim(
            uid, node_name,
            ["tpu-0-part-1c.4hbm-0-0", "tpu-0-part-1c.4hbm-1-4"],
            name=uid,
        )
        params: dict = {
            "apiVersion": "resource.tpu.google.com/v1beta1",
            "kind": "TpuPartitionConfig",
        }
        if sharing:
            params["sharing"] = {
                "strategy": "MultiProcess",
                "multiProcessConfig": {},
            }
        claim["status"]["allocation"]["devices"]["config"] = [
            {
                "source": "FromClaim",
                "requests": [],
                "opaque": {
                    "driver": TPU_DRIVER_NAME,
                    "parameters": params,
                },
            }
        ]
        return claim

    def _node_partition_state(self, node: int) -> tuple[set, dict]:
        """(live partition uuids, partition records) for one node —
        checkpoint truth read through the real recovery view."""
        from tpudra.plugin import partitions as partrec_mod

        live = {p.uuid for p in self.sim._libs[node].list_partitions()}
        records = partrec_mod.records_in(
            self.sim.drivers[node].state._cp.read_view()
        )
        return live, records

    def _inject_partition_fault(self, node: int, params: dict) -> None:
        """Break the fractional-chip lifecycle on one node
        (docs/partitioning.md) and hold it to convergence:

        - ``create_fail``: ``create_partition`` fails once mid-bind — the
          claim must come back with a RETRYABLE error, the retry must
          bind, and no partition/record may leak at any point;
        - ``daemon_crash_mid_attach``: the claim's MP control daemon (a
          REAL process via LocalDaemonRunner) is SIGKILLed while a client
          is ATTACHed — release must still converge to zero partitions
          and a dead daemon;
        - ``destroy_fail_crash``: ``delete_partition`` fails during
          unprepare AND the plugin is crash/restarted — the recovery
          sweep must destroy the orphan from checkpoint truth alone.
        """
        from tpudra.devicelib import DeviceLibError

        variant = params.get("variant") or "create_fail"
        record = FaultRecord(
            kind="partition_fault", t_sim_start=self._now(), node=node,
            params=dict(params),
        )
        self._record_fault(record)
        self._quarantine_node(node)
        t0_sim = self._now()
        n_fault = self._fault_counter
        uid = f"soak-part-{n_fault}"
        node_name = self.sim.node_names[node]
        converged = False
        live, recs = set(), {}
        try:
            driver = self.sim.drivers[node]
            lib = self.sim._libs[node]
            claim = self._partition_claim(
                uid, node_name, sharing=variant == "daemon_crash_mid_attach"
            )
            with api_deadline(5.0):
                self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")

            if variant == "create_fail":
                real_create = lib.create_partition
                armed = {"on": True}

                def flaky_create(spec):
                    if armed["on"]:
                        armed["on"] = False
                        raise DeviceLibError(
                            f"soak partition_fault #{n_fault}: injected "
                            "create failure"
                        )
                    return real_create(spec)

                lib.create_partition = flaky_create
                try:
                    with api_deadline(5.0):
                        resp = driver.prepare_resource_claims([claim])
                    entry = resp["claims"][uid]
                    self._check_or_interrupted(
                        INV_FAULT_RECOVERY,
                        "error" in entry and not entry.get("permanent"),
                        key=("partition_create_fail", n_fault),
                        detail=(
                            "failed partition create must yield a "
                            f"retryable error, got {entry!r:.120}"
                        ),
                        what=f"partition_fault create leg on node {node}",
                    )
                finally:
                    lib.create_partition = real_create
                bound = self._retry_prepare(
                    node, claim, self.budget.recovery_sim_s / 2
                )
                live, recs = self._node_partition_state(node)
                self._check_or_interrupted(
                    INV_FAULT_RECOVERY,
                    bound and len(live) == 2,
                    key=("partition_retry_bind", n_fault),
                    detail="retry after injected create failure never bound",
                    what=f"partition_fault retry on node {node}",
                )
            elif variant == "daemon_crash_mid_attach":
                self._ensure_mp_stack(node)
                bound = self._retry_prepare(
                    node, claim, self.budget.recovery_sim_s / 2
                )
                if bound:
                    from tpudra import mpdaemon

                    pipe_dir = os.path.join(
                        self.sim._base, f"mp{node}", uid
                    )
                    attached = False
                    try:
                        resp = mpdaemon.query(pipe_dir, f"ATTACH soak-{n_fault}")
                        attached = resp.startswith("OK ")
                    except OSError:
                        ...
                    self._check_or_interrupted(
                        INV_FAULT_RECOVERY,
                        attached,
                        key=("partition_mp_attach", n_fault),
                        detail="workload ATTACH through control.sock failed",
                        what=f"partition_fault attach on node {node}",
                    )
                    # THE FAULT: SIGKILL the broker mid-attach.
                    runner = driver.state._mp.runner
                    pid = runner.pid(uid, pipe_dir)
                    if pid is not None:
                        with contextlib.suppress(OSError):
                            os.kill(pid, 9)
                else:
                    self._anomaly(
                        f"partition_fault #{n_fault}: MP bind never landed"
                    )
            else:  # destroy_fail_crash
                bound = self._retry_prepare(
                    node, claim, self.budget.recovery_sim_s / 2
                )
                if bound:
                    real_delete = lib.delete_partition
                    armed = {"on": True}

                    def flaky_delete(uuid):
                        if armed["on"]:
                            armed["on"] = False
                            raise DeviceLibError(
                                f"soak partition_fault #{n_fault}: injected "
                                "destroy failure"
                            )
                        return real_delete(uuid)

                    lib.delete_partition = flaky_delete
                    try:
                        self._best_effort_unprepare(driver, uid)
                    finally:
                        lib.delete_partition = real_delete
                    # Compose the SIGKILL before anything can repair: the
                    # restarted plugin's recovery sweep is the only path
                    # allowed to reap the orphan.
                    self.sim.crash_node(node)
                    self.sim.restart_node(node)
                else:
                    self._anomaly(
                        f"partition_fault #{n_fault}: destroy leg never bound"
                    )

            # Convergence: release whatever is still bound, then hold the
            # node to ZERO live partitions and ZERO partition records.
            self._best_effort_unprepare(self.sim.drivers[node], uid)
            deadline = time.monotonic() + self.simclock.wall_of(
                self.budget.recovery_sim_s
            )
            while time.monotonic() < deadline and not self._stop.is_set():
                try:
                    live, recs = self._node_partition_state(node)
                except Exception:  # noqa: BLE001 — mid-restart window
                    live, recs = {"restarting"}, {}
                if not live and not recs:
                    converged = True
                    break
                self._best_effort_unprepare(self.sim.drivers[node], uid)
                time.sleep(0.05)
            self._check_or_interrupted(
                INV_PARTITION_LEAK,
                converged,
                key=("partition_fault", n_fault, variant),
                detail=(
                    f"node {node} still holds partitions/records after "
                    f"{variant} (live={sorted(live)}, recs={sorted(recs)})"
                ),
                what=f"partition_fault convergence on node {node}",
            )
        finally:
            try:
                with api_deadline(5.0):
                    self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
            except (NotFound, ApiError):
                ...
            self._unquarantine_node(node)
            self._end_fault(record)
            record.recovered_sim_s = record.t_sim_end - t0_sim
            if converged:
                self._recovery_samples.append(record.recovered_sim_s)

    def _ensure_mp_stack(self, node: int) -> None:
        """Lazily hand one node's driver a MultiProcessManager with the
        LocalDaemonRunner — the REAL broker process, spawned per claim
        (fault thread only; the driver reads the reference atomically)."""
        from tpudra.plugin.sharing import LocalDaemonRunner, MultiProcessManager

        driver = self.sim.drivers[node]
        if driver.state._mp is not None:
            return
        driver.state._mp = MultiProcessManager(
            self.sim.kube,
            self.sim._libs[node],
            self.sim.node_names[node],
            pipe_root=os.path.join(self.sim._base, f"mp{node}"),
            runner=LocalDaemonRunner(),
        )

    def _inject_chip_fault(self, node: int) -> None:
        """A chip dies on a node with (1) a BOUND node-local claim on the
        silicon and (2) a live gang member — the escalation + remediation
        path end to end: the health handler must withhold the chip from
        published slices AND surface the fault on the bound claim's
        status; the gang must go degraded and remediate onto a healthy
        spare (selection filtered on published slice health), leaving no
        grant on the faulted node and zero CDI leaks.  The node is then
        crash/restarted — the plugin-replacement repair, the only re-heal
        path the reference admits."""
        from tpudra.controller.gang import GangMember
        from tpudra.devicelib import HealthEvent, HealthEventKind
        from tpudra.plugin.driver import CLAIM_UNHEALTHY_CONDITION
        from tpudra.sim.multihost import make_channel_claim

        record = FaultRecord(
            kind="chip_fault", t_sim_start=self._now(), node=node
        )
        self._record_fault(record)
        self._quarantine_node(node)
        t0_sim = self._now()
        n_fault = self._fault_counter
        uid = f"soak-chip-{n_fault}"
        gang_id = f"soak-chipg-{n_fault}"
        domain_uid = f"{gang_id}-uid"
        node_name = self.sim.node_names[node]
        gang_members: list = []
        gang_claims: dict = {}
        gang_reserved = False
        withheld = False  # read by the finally's recovery-sample gate
        try:
            driver = self.sim.drivers[node]
            # (1) a bound claim on tpu-0, the fault injectors' reserved
            # slot — the claim holder the escalation exists for.
            claim = make_claim(uid, node_name, ["tpu-0"], name=uid)
            with api_deadline(5.0):
                self.sim.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            bound = self._retry_prepare(
                node, claim, self.budget.recovery_sim_s / 2
            )
            # (2) a live 2-member gang including this node, with the whole
            # cluster in the domain so healthy peers qualify as spares
            # (daemons run on spares too — that is what makes them spares).
            if bound and self.config.nodes >= 3:
                self._ensure_cd_stack()
                others = [i for i in range(self.config.nodes) if i != node]
                peer_name = self.sim.node_names[others[0]]
                gang_members = [
                    GangMember(node=node_name, claim_uid=f"{gang_id}-m0"),
                    GangMember(node=peer_name, claim_uid=f"{gang_id}-m1"),
                ]
                gang_claims = {
                    m.claim_uid: make_channel_claim(
                        m.claim_uid, m.node, domain_uid
                    )
                    for m in gang_members
                }
                try:
                    self._create_cd_objects(
                        gang_id, domain_uid, list(self.sim.node_names),
                        gang_claims,
                    )
                    self._await_cd_ready(gang_id)
                    self._gang_mgr.reserve(gang_id, gang_members, gang_claims)
                    gang_reserved = True
                except Exception as e:  # noqa: BLE001 — a fault window won
                    record.params["gang_aborted"] = str(e)[:120]
            # (3) THE FAULT — delivered through the real handler (health
            # loop body): withhold + escalate + health-stream notify.
            event = HealthEvent(
                kind=HealthEventKind.HBM_ECC_ERROR,
                chip_uuid=self.sim._libs[node].chip_by_index(0).uuid,
                detail=f"soak chip_fault #{n_fault}",
            )
            try:
                driver._handle_health_event(event)
            except Exception:  # noqa: BLE001 — latency window beat the publish
                logger.info("chip_fault handler pass deferred", exc_info=True)
            # The slice withhold must land (retrying through the latency
            # window — the health loop's republish would).
            deadline = time.monotonic() + self.simclock.wall_of(
                self.budget.recovery_sim_s
            )
            withheld = False
            while time.monotonic() < deadline and not self._stop.is_set():
                if "tpu-0" not in self._advertised_devices(node_name):
                    withheld = True
                    break
                try:
                    with api_deadline(5.0):
                        driver.publish_resources()
                except Exception:  # noqa: BLE001 — retried until the window closes
                    logger.info("chip_fault republish retrying", exc_info=True)
                time.sleep(0.05)
            self._check(
                INV_FAULT_RECOVERY,
                withheld,
                key=("chip_fault_withhold", n_fault),
                detail="faulted chip still advertised in ResourceSlices",
            )
            if bound:
                # Escalation: the bound claim must carry the condition.
                escalated = False
                try:
                    with api_deadline(5.0):
                        live = self.sim.kube.get(
                            gvr.RESOURCE_CLAIMS, uid, "default"
                        )
                    escalated = any(
                        c.get("type") == CLAIM_UNHEALTHY_CONDITION
                        and c.get("status") == "True"
                        for c in live.get("status", {}).get("conditions", [])
                    )
                except (NotFound, ApiError):
                    ...
                self._check(
                    INV_FAULT_RECOVERY,
                    escalated,
                    key=("chip_fault_escalation", n_fault),
                    detail=(
                        "bound claim on faulted silicon got no "
                        "DeviceUnhealthy status condition"
                    ),
                )
            if gang_reserved:
                self._remediate_chip_fault_gang(
                    record, gang_id, domain_uid, gang_members, gang_claims,
                    node_name, n_fault,
                )
            # Teardown: gang first (so its channel unprepare still finds
            # the CD), then the node claim, then the repair restart.
            if gang_reserved:
                try:
                    self._gang_mgr.release(gang_id)
                except Exception:  # noqa: BLE001 — recover() owns stragglers
                    logger.info("chip_fault gang release retrying", exc_info=True)
                    try:
                        self._gang_mgr.recover()
                    except Exception:  # noqa: BLE001 — next wave retries
                        logger.info(
                            "chip_fault gang recovery deferred", exc_info=True
                        )
            self._best_effort_unprepare(driver, uid)
            # The repair: replace the plugin over the same dirs — the only
            # way sick silicon re-enters advertisement (driver.go:462-502),
            # and what keeps a long soak from grinding to all-unhealthy.
            self.sim.crash_node(node)
            self.sim.restart_node(node)
        finally:
            try:
                with api_deadline(5.0):
                    self.sim.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
            except (NotFound, ApiError):
                ...
            if gang_claims:
                self._delete_cd_objects(gang_id, gang_claims)
            self._unquarantine_node(node)
            self._end_fault(record)
            record.recovered_sim_s = record.t_sim_end - t0_sim
            # Sample only genuine recoveries (the cd_wave/daemon_crash
            # convention): a leg that timed out at the full budget already
            # recorded its invariant violation — feeding the whole budget
            # into the recovery percentiles would double-count it.
            if withheld:
                self._recovery_samples.append(record.recovered_sim_s)

    def _remediate_chip_fault_gang(
        self,
        record: FaultRecord,
        gang_id: str,
        domain_uid: str,
        gang_members: list,
        gang_claims: dict,
        faulted_node: str,
        n_fault: int,
    ) -> None:
        """The degraded→remediated leg of a chip fault: mark the member on
        the faulted node degraded (the controller's condition-watch role),
        pick a spare FILTERED ON PUBLISHED SLICE HEALTH, remediate, and
        assert the post-conditions: all-bound off the faulted node, no
        grant left on it, no CDI leak from the displaced member."""
        from tpudra.controller.gang import GangMember, select_healthy_spares
        from tpudra.sim.multihost import make_channel_claim

        sick = next(m for m in gang_members if m.node == faulted_node)
        self._gang_mgr.mark_degraded(
            gang_id, [sick.claim_uid], reason="chip_fault"
        )
        gang_nodes = {m.node for m in gang_members}
        spares = select_healthy_spares(
            self.sim.kube,
            [n for n in self.sim.node_names if n not in gang_nodes],
            exclude=gang_nodes,
        )
        if not spares:
            record.params["remediation"] = "no healthy spares"
            self._anomaly(
                f"chip_fault #{n_fault}: no healthy spare for gang {gang_id}"
            )
            return
        replacement = GangMember(
            node=spares[0], claim_uid=f"{gang_id}-r0"
        )
        replacement_claim = make_channel_claim(
            replacement.claim_uid, replacement.node, domain_uid
        )
        try:
            with api_deadline(5.0):
                self.sim.kube.create(
                    gvr.RESOURCE_CLAIMS, replacement_claim, "default"
                )
        except ApiError as e:
            record.params["remediation"] = f"aborted: {e}"[:120]
            return
        gang_claims[replacement.claim_uid] = replacement_claim
        target_claims = {
            replacement.claim_uid: replacement_claim,
            **{
                m.claim_uid: gang_claims[m.claim_uid]
                for m in gang_members
                if m.claim_uid != sick.claim_uid
            },
        }
        remediated = False
        try:
            status = self._gang_mgr.remediate(
                gang_id, {sick.claim_uid: replacement}, target_claims
            )
            remediated = status.phase == "bound"
        except Exception as e:  # noqa: BLE001 — released/failed under faults
            record.params["remediation"] = f"{type(e).__name__}: {e}"[:120]
        record.params["remediated_to"] = replacement.node
        self._check(
            INV_GANG_DEGRADED,
            remediated
            or self._gang_mgr.gangs().get(gang_id) is None,
            key=("chip_fault_remediate", n_fault),
            detail=(
                "degraded gang neither remediated nor cleanly released "
                "inside its fault window"
            ),
        )
        if remediated:
            # No grant on dead silicon: the displaced member's bind and
            # CDI spec must be gone from the faulted node.
            d = self._cd_drivers.get(faulted_node)
            leaked = d is not None and (
                sick.claim_uid in d.state.prepared_claim_uids()
                or sick.claim_uid in d.state._cdi.list_claim_uids()
            )
            self._check(
                INV_GRANT_HEALTH,
                not leaked,
                key=("chip_fault_grant", n_fault),
                detail=(
                    f"remediated gang left a grant/CDI spec for "
                    f"{sick.claim_uid} on faulted node {faulted_node}"
                ),
            )
            n_bound = self._bound_gang_members(
                [replacement]
                + [m for m in gang_members if m.claim_uid != sick.claim_uid]
            )
            self._check(
                INV_GANG_ATOMICITY,
                n_bound == len(gang_members),
                key=("chip_fault_bound", n_fault),
                detail=(
                    f"remediated gang has {n_bound}/{len(gang_members)} "
                    "members bound"
                ),
            )

    def _advertised_devices(self, node_name: str) -> set:
        try:
            with api_deadline(3.0):
                listing = self.sim.kube.list(gvr.RESOURCE_SLICES)
        except ApiError:
            return {"__unknown__"}  # indeterminate: caller retries
        out: set = set()
        for item in listing.get("items", []):
            spec = item.get("spec", {})
            if (
                spec.get("driver") == TPU_DRIVER_NAME
                and spec.get("nodeName") == node_name
            ):
                for d in spec.get("devices", []):
                    out.add(d.get("name"))
        return out

    # --------------------------------------------------------- daemon crash

    def _ensure_daemon_stack(self) -> None:
        """Build the CD daemon stack the soak supervises: a dummy slice
        daemon under the REAL ProcessManager watchdog (shared full-jitter
        restart backoff, seeded rng) and a REAL CoordinatorProxy forwarding
        to a registered upstream (an in-process stand-in for the host-0
        workload's jax coordinator).  Fault thread only."""
        if self._daemon_pm is not None:
            return
        import sys

        from tpudra.cddaemon.coordproxy import CoordinatorProxy, write_registration
        from tpudra.cddaemon.process import ProcessManager

        self._daemon_dir = os.path.join(self.sim._base, "daemon-domain")
        os.makedirs(self._daemon_dir, exist_ok=True)
        self._daemon_upstream = _PongServer()
        self._daemon_upstream.start()
        write_registration(
            self._daemon_dir, "127.0.0.1", self._daemon_upstream.port
        )
        self._daemon_proxy = CoordinatorProxy(
            0, self._daemon_dir, host="127.0.0.1"
        )
        self._daemon_proxy.start()
        self._daemon_stop = threading.Event()
        self._daemon_pm = ProcessManager(
            [sys.executable, "-c", "import time; time.sleep(3600)"],
            restart_rng=random.Random(self.config.seed ^ 0xDA3),
        )
        self._daemon_pm.ensure_started()
        self._daemon_pm.start_watchdog(self._daemon_stop, tick=0.05)

    def _close_daemon_stack(self) -> None:
        if self._daemon_stop is not None:
            self._daemon_stop.set()
        if self._daemon_pm is not None:
            try:
                self._daemon_pm.stop()
            except Exception:  # noqa: BLE001
                logger.exception("daemon stack stop failed")
        if self._daemon_proxy is not None:
            self._daemon_proxy.stop()
        if self._daemon_upstream is not None:
            self._daemon_upstream.stop()

    def _probe_proxy(self, timeout: float = 5.0) -> bool:
        """One rendezvous through the proxy: connect to the coordinator
        port, expect the registered upstream's payload back."""
        import socket as socket_mod

        try:
            with socket_mod.create_connection(
                ("127.0.0.1", self._daemon_proxy.bound_port), timeout=timeout
            ) as s:
                s.settimeout(timeout)
                return s.recv(16).startswith(b"pong")
        except OSError:
            return False

    def _inject_daemon_crash(self, params: dict) -> None:
        """SIGKILL the slice daemon (watchdog must respawn it through the
        full-jitter backoff) or bounce the coordinator proxy (the restart
        must re-read the registration and forward again) — while whatever
        other fault windows are open stay open."""
        import signal as signal_mod

        target = params.get("target") or "slicewatchd"
        record = FaultRecord(
            kind="daemon_crash", t_sim_start=self._now(), params=dict(params)
        )
        self._record_fault(record)
        t0_sim = self._now()
        try:
            self._ensure_daemon_stack()
            if target == "slicewatchd":
                deadline = time.monotonic() + self.simclock.wall_of(
                    self.budget.recovery_sim_s
                )
                pm = self._daemon_pm
                # STABLE_UPTIME is 30 WALL seconds — a compressed soak's
                # child never qualifies as stable, so repeated kills would
                # accumulate the jitter window across injections until the
                # wall-of(sim) budget loses to a correctly-pacing
                # watchdog.  Each injection tests "the watchdog respawns
                # through the backoff", not cumulative pacing (the pacing
                # law itself is unit-tested), so reset per injection.
                pm._restart_backoff.reset()
                pid_before = pm.pid
                restarts_before = pm.restarts
                pm.send_signal(signal_mod.SIGKILL)
                recovered = False
                while time.monotonic() < deadline and not self._stop.is_set():
                    if (
                        pm.running
                        and pm.pid != pid_before
                        and pm.restarts > restarts_before
                    ):
                        recovered = True
                        break
                    time.sleep(0.02)
                record.params["restarts"] = pm.restarts
                self._check(
                    INV_FAULT_RECOVERY,
                    recovered,
                    key=("daemon_crash", self._fault_counter),
                    detail=(
                        "watchdog did not respawn the slice daemon inside "
                        "the recovery budget"
                    ),
                )
            else:
                from tpudra.cddaemon.coordproxy import CoordinatorProxy

                self._daemon_proxy.stop()
                self._daemon_proxy = CoordinatorProxy(
                    0, self._daemon_dir, host="127.0.0.1"
                )
                self._daemon_proxy.start()
                # The recovery clock starts at the restart, like the
                # watchdog variant's (the crash itself has no budget).
                deadline = time.monotonic() + self.simclock.wall_of(
                    self.budget.recovery_sim_s
                )
                recovered = False
                while time.monotonic() < deadline and not self._stop.is_set():
                    if self._probe_proxy(timeout=1.0):
                        recovered = True
                        break
                    time.sleep(0.02)
                self._check(
                    INV_FAULT_RECOVERY,
                    recovered,
                    key=("daemon_crash_proxy", self._fault_counter),
                    detail=(
                        "restarted coordinator proxy never forwarded to "
                        "the registered endpoint again"
                    ),
                )
            if recovered:
                self._recovery_samples.append(self._now() - t0_sim)
        finally:
            self._end_fault(record)
            record.recovered_sim_s = record.t_sim_end - t0_sim

    def _sweep_cd_labels(self, nodes: list[str]) -> None:
        from tpudra.api.computedomain import COMPUTE_DOMAIN_NODE_LABEL

        for name in nodes:
            try:
                node = self.sim.kube.get(gvr.NODES, name)
            except (NotFound, ApiError):
                continue
            label = node.get("metadata", {}).get("labels", {}).get(
                COMPUTE_DOMAIN_NODE_LABEL
            )
            if label and label.startswith("soak-"):
                try:
                    self.sim.kube.patch(
                        gvr.NODES,
                        name,
                        {
                            "metadata": {
                                "labels": {COMPUTE_DOMAIN_NODE_LABEL: None}
                            }
                        },
                    )
                except ApiError:
                    ...  # next wave sweeps again

    def _gc_pass(self, node: int) -> int:
        try:
            with api_deadline(3.0):
                return self.sim.drivers[node].cleanup.cleanup_once()
        except Exception:  # noqa: BLE001 — GC races churn/crashes by design
            logger.info("soak GC pass on node %d failed", node, exc_info=True)
            return 0

    # ------------------------------------------------------------- monitor

    def _monitor_loop(self) -> None:
        interval_wall = max(
            0.05, self.simclock.wall_of(self.config.monitor_interval_sim_s)
        )
        while not self._stop.wait(interval_wall):
            try:
                self._monitor_once()
            except Exception:  # noqa: BLE001 — the monitor must outlive faults
                logger.exception("invariant monitor pass failed")
        self._monitor_once()  # final pass after churn stops

    def _monitor_once(self) -> None:
        self._check_claim_stuck()
        self._check_leaks()
        self._check_partition_leak()
        self._check_slice_convergence()
        self._check_gang_atomicity()
        self._check_slice_health()
        self._check_gang_degraded()
        self._check_grant_health()
        self._check_storage_degraded()
        self._check_single_writer()
        self._check_leadership_liveness()

    def _check_single_writer(self) -> None:
        """The journaled fence history must be strictly increasing and
        topped by the high-water term: a superseded term appearing after
        its successor is a split-brain commit the checkpoint layer failed
        to refuse (docs/ha.md).  Audited CONTINUOUSLY — not just at the
        failover fault's stale-leader probe — so any interleaving a
        compound fault provokes is caught at the store."""
        mgr = self._gang_mgr
        if mgr is not None and mgr.term is not None:
            try:
                high, history = mgr.fence_state()
            except Exception:  # noqa: BLE001 — mid-swap/teardown window
                return
            monotonic_ok = all(a < b for a, b in zip(history, history[1:]))
            capped_ok = not history or history[-1] == high
            if not (monotonic_ok and capped_ok):
                self._check(
                    INV_SINGLE_WRITER,
                    False,
                    key=("history", tuple(history)),
                    detail=(
                        f"fence term history {history} (high-water {high}) "
                        "is not strictly increasing — two leadership terms "
                        "interleaved gang WAL commits"
                    ),
                )
        self._pass_check(INV_SINGLE_WRITER)

    def _check_leadership_liveness(self) -> None:
        """While the apiserver is up (no outage/latency window open and no
        failover mid-flight), SOME controller must be renewing the lease:
        the lease object's resourceVersion may not sit unchanged past the
        recovery budget.  Monotonic-aged on the observed rv, like every
        other liveness check."""
        if self._elector is None:
            self._pass_check(INV_LEADERSHIP)
            return
        with self._records_lock:
            blocked = any(
                k in self._active
                for k in (
                    "apiserver_outage",
                    "apiserver_latency",
                    "controller_failover",
                )
            )
        if blocked:
            self._lease_ager.forget("lease")
            return
        try:
            with api_deadline(3.0):
                lease = self.sim.kube.get(
                    gvr.LEASES,
                    "soak-controller",
                    self.sim.config.driver_namespace,
                )
            rv = lease.get("metadata", {}).get("resourceVersion", "")
        except NotFound:
            rv = "absent"
        except ApiError:
            return  # can't tell: wait for a readable pass
        age_sim = self._lease_ager.age("lease", rv) * self.config.compression
        self._check(
            INV_LEADERSHIP,
            age_sim <= self.budget.recovery_sim_s,
            key=("lease-stalled",),
            detail=(
                f"controller lease unrenewed for {age_sim:.0f} sim-s with "
                f"the apiserver up (budget {self.budget.recovery_sim_s:.0f})"
            ),
        )

    def _quiet_and_settled(self) -> bool:
        """True when no fault window is open AND the convergence budget
        has elapsed since the last one closed — the precondition shared by
        every published-state invariant."""
        now = self._now()
        with self._records_lock:
            if self._active or self._cd_wave_inflight > 0:
                return False
            last_end = max(
                (r.t_sim_end or now for r in self._timeline), default=0.0
            )
        return not (
            now - last_end < self.budget.convergence_sim_s and last_end > 0
        )

    def _check_slice_health(self) -> None:
        """QUIET-WINDOW: no published ResourceSlice may advertise silicon
        its driver currently holds unhealthy — the withhold must actually
        have reached the apiserver, not just the in-memory set."""
        if not self._quiet_and_settled():
            return
        try:
            listing = self.sim.kube.list(gvr.RESOURCE_SLICES)
        except ApiError:
            return
        advertised: dict[str, set] = {}
        for item in listing.get("items", []):
            spec = item.get("spec", {})
            if spec.get("driver") == TPU_DRIVER_NAME:
                devs = advertised.setdefault(spec.get("nodeName", ""), set())
                for d in spec.get("devices", []):
                    devs.add(d.get("name"))
        for i in range(self.config.nodes):
            node_name = self.sim.node_names[i]
            try:
                bad = self.sim.drivers[i].unhealthy_devices()
            except Exception:  # noqa: BLE001 — mid-restart window
                continue
            leaked = advertised.get(node_name, set()) & bad
            if leaked:
                self._check(
                    INV_SLICE_HEALTH,
                    False,
                    key=(i, tuple(sorted(leaked))),
                    detail=(
                        f"node {node_name} advertises unhealthy silicon "
                        f"{sorted(leaked)} in a quiet window"
                    ),
                )
        self._pass_check(INV_SLICE_HEALTH)

    def _check_storage_degraded(self) -> None:
        """No node may sit storage-degraded past the recovery budget once
        no disk fault is active (heal probe + convergent compaction must
        clear the flag) — monotonic-aged, like the gang check.  While a
        disk_fault window is open, being degraded is the CORRECT state and
        nothing ages."""
        with self._records_lock:
            fault_active = "disk_fault" in self._active
        live_keys: list = []
        for i in range(self.config.nodes):
            try:
                degraded = self.sim.drivers[i].storage_degraded
            except Exception:  # noqa: BLE001 — mid-restart window
                continue
            if not degraded or fault_active:
                self._storage_ager.forget(i)
                continue
            live_keys.append(i)
            age_sim = (
                self._storage_ager.age(i, "degraded") * self.config.compression
            )
            self._check(
                INV_STORAGE_DEGRADED,
                age_sim <= self.budget.recovery_sim_s,
                key=("degraded", i),
                detail=(
                    f"node {i} storage-degraded for {age_sim:.0f} sim-s "
                    f"with no disk fault active (budget "
                    f"{self.budget.recovery_sim_s:.0f})"
                ),
            )
        self._storage_ager.prune(live_keys)
        self._pass_check(INV_STORAGE_DEGRADED)

    def _check_gang_degraded(self) -> None:
        """No gang may sit degraded/remediating longer than the recovery
        budget (sim time, monotonic-aged) — remediation must converge to
        all-bound-on-healthy or cleanly-released, not linger."""
        mgr = self._gang_mgr
        live_keys: list = []
        if mgr is not None:
            try:
                gangs = mgr.gangs()
            except Exception:  # noqa: BLE001 — mid-teardown window
                return
            for gang_id, status in gangs.items():
                if status.phase not in ("degraded", "remediating"):
                    self._degraded_ager.forget(gang_id)
                    continue
                live_keys.append(gang_id)
                age_sim = (
                    self._degraded_ager.age(gang_id, status.phase)
                    * self.config.compression
                )
                self._check(
                    INV_GANG_DEGRADED,
                    age_sim <= self.budget.recovery_sim_s,
                    key=("aged", gang_id),
                    detail=(
                        f"gang {gang_id} {status.phase} for {age_sim:.0f} "
                        f"sim-seconds (budget "
                        f"{self.budget.recovery_sim_s:.0f})"
                    ),
                )
            self._degraded_ager.prune(live_keys)
        self._pass_check(INV_GANG_DEGRADED)

    def _check_grant_health(self) -> None:
        """QUIET-WINDOW: no fully-bound gang may hold a member grant on a
        node whose driver reports unhealthy silicon — after every
        remediation wave, grants live only on healthy nodes."""
        if not self._quiet_and_settled():
            return
        mgr = self._gang_mgr
        if mgr is not None:
            node_idx = {n: i for i, n in enumerate(self.sim.node_names)}
            try:
                gangs = mgr.gangs()
            except Exception:  # noqa: BLE001 — mid-teardown window
                return
            for gang_id, status in gangs.items():
                if status.phase != "bound":
                    continue  # degraded/remediating: the age check owns it
                for m in status.members:
                    i = node_idx.get(m.node)
                    if i is None:
                        continue
                    try:
                        bad = self.sim.drivers[i].unhealthy_devices()
                    except Exception:  # noqa: BLE001 — mid-restart window
                        continue
                    if bad:
                        self._check(
                            INV_GRANT_HEALTH,
                            False,
                            key=("quiet", gang_id, m.node),
                            detail=(
                                f"bound gang {gang_id} holds a grant on "
                                f"{m.node} with unhealthy silicon "
                                f"{sorted(bad)}"
                            ),
                        )
        self._pass_check(INV_GRANT_HEALTH)

    def _check_gang_atomicity(self) -> None:
        """QUIET-WINDOW check: no gang may be partially bound — every gang
        is all-bound (complete record, every member claim in its node's
        plugin checkpoint) or none-bound (no record, no member claims).
        While faults or a wave are in flight the gang may legitimately be
        mid-bind/mid-rollback, so — like slice convergence — the check
        only asserts in quiet windows; a vacuous pass (no gangs yet)
        still counts as one whole-cluster evaluation."""
        with self._records_lock:
            busy = bool(self._active) or self._cd_wave_inflight > 0
        if busy:
            return
        mgr = self._gang_mgr
        if mgr is not None:
            drivers = self._cd_drivers

            def probe(m) -> bool:
                d = drivers.get(m.node)
                return (
                    d is not None
                    and m.claim_uid in d.state.prepared_claim_uids()
                )

            try:
                partial = mgr.partially_bound(probe)
                known = {
                    m.claim_uid
                    for status in mgr.gangs().values()
                    for m in status.members
                }
            except Exception:  # noqa: BLE001 — mid-teardown window
                logger.info("gang-atomicity scan skipped", exc_info=True)
                return
            for gang_id in partial:
                self._check(
                    INV_GANG_ATOMICITY,
                    False,
                    key=("partial", gang_id),
                    detail=f"gang {gang_id} partially bound in a quiet window",
                )
            # Residue: a bound member claim whose gang record is gone is
            # the other partial shape (rollback dropped the record but a
            # member survived).
            for node, d in drivers.items():
                try:
                    uids = d.state.prepared_claim_uids()
                except Exception:  # noqa: BLE001 — mid-teardown window
                    continue
                for uid in uids:
                    # Gang-member uids from BOTH gang-creating faults
                    # (cd_wave and chip_fault, incl. its -rN replacements).
                    if uid.startswith(("soak-cdw-", "soak-chipg-")) and uid not in known:
                        self._check(
                            INV_GANG_ATOMICITY,
                            False,
                            key=("orphan", node, uid),
                            detail=(
                                f"bound gang member {uid} on {node} has no "
                                "gang record"
                            ),
                        )
        self._pass_check(INV_GANG_ATOMICITY)

    def _check_claim_stuck(self) -> None:
        """No claim may sit in a non-terminal phase (PrepareStarted) for
        more than T sim seconds — across crashes, restarts, and GC."""
        live_keys = []
        for i in range(self.config.nodes):
            try:
                statuses = self.sim.drivers[i].state.prepared_claim_uids()
            except Exception:  # noqa: BLE001 — mid-restart window
                logger.info("claim-stuck scan skipped node %d", i, exc_info=True)
                continue
            for uid, (_, _, status) in statuses.items():
                key = (i, uid)
                live_keys.append(key)
                if status != PREPARE_STARTED:
                    self._stuck_ager.forget(key)
                    continue
                age_sim = (
                    self._stuck_ager.age(key, status) * self.config.compression
                )
                with self._records_lock:
                    self._max_stuck_sim = max(self._max_stuck_sim, age_sim)
                self._check(
                    INV_CLAIM_STUCK,
                    age_sim <= self.budget.max_claim_stuck_sim_s,
                    key=key,
                    detail=(
                        f"claim {uid} on node {i} stuck in {status} for "
                        f"{age_sim:.0f} sim-seconds (budget "
                        f"{self.budget.max_claim_stuck_sim_s:.0f})"
                    ),
                )
        self._stuck_ager.prune(live_keys)
        self._pass_check(INV_CLAIM_STUCK)

    def _check_leaks(self) -> None:
        """No CDI spec file and no per-uid flock file may outlive its
        checkpoint record beyond the leak grace (sim time) — the leaks a
        crashed prepare or a half-done unprepare would leave."""
        grace = self.budget.leak_grace_sim_s
        live_keys = []
        for i in range(self.config.nodes):
            try:
                uids = set(self.sim.drivers[i].state.prepared_claim_uids())
            except Exception:  # noqa: BLE001 — mid-restart window
                logger.info("leak scan skipped node %d", i, exc_info=True)
                continue
            for sub, invariant in (("c", INV_CDI_LEAK), ("p", INV_FLOCK_LEAK)):
                root = os.path.join(self.sim._base, f"{sub}{i}")
                if sub == "p":
                    root = os.path.join(root, "claims")
                try:
                    names = os.listdir(root)
                except OSError:
                    continue
                for name in names:
                    if sub == "p" and not name.endswith(".lock"):
                        continue
                    if sub == "c" and not name.endswith(".json"):
                        continue
                    orphan = not any(uid in name for uid in uids)
                    key = (invariant, i, name)
                    live_keys.append(key)
                    if not orphan:
                        self._leak_ager.forget(key)
                        continue
                    age_sim = (
                        self._leak_ager.age(key, "orphan")
                        * self.config.compression
                    )
                    self._check(
                        invariant,
                        age_sim <= grace,
                        key=key,
                        detail=(
                            f"{name} on node {i} has no checkpoint record "
                            f"for {age_sim:.0f} sim-seconds (grace {grace:.0f})"
                        ),
                    )
        # The CD plugin stack's CDI roots (cdw-c{i}): a remediation wave
        # must not leave a displaced member's spec behind — the "zero CDI
        # leaks across remediation waves" contract.
        cd_drivers = self._cd_drivers
        if cd_drivers:
            for i, node_name in enumerate(self.sim.node_names):
                d = cd_drivers.get(node_name)
                if d is None:
                    continue
                try:
                    uids = set(d.state.prepared_claim_uids())
                except Exception:  # noqa: BLE001 — mid-teardown window
                    continue
                root = os.path.join(self.sim._base, f"cdw-c{i}")
                try:
                    names = os.listdir(root)
                except OSError:
                    continue
                for name in names:
                    if not name.endswith(".json"):
                        continue
                    orphan = not any(uid in name for uid in uids)
                    key = (INV_CDI_LEAK, f"cd-{i}", name)
                    live_keys.append(key)
                    if not orphan:
                        self._leak_ager.forget(key)
                        continue
                    age_sim = (
                        self._leak_ager.age(key, "orphan")
                        * self.config.compression
                    )
                    self._check(
                        INV_CDI_LEAK,
                        age_sim <= grace,
                        key=key,
                        detail=(
                            f"CD spec {name} on node {i} has no checkpoint "
                            f"record for {age_sim:.0f} sim-seconds "
                            f"(grace {grace:.0f})"
                        ),
                    )
        self._leak_ager.prune(live_keys)
        self._pass_check(INV_CDI_LEAK)
        self._pass_check(INV_FLOCK_LEAK)

    def _check_partition_leak(self) -> None:
        """The fractional-chip bijection (docs/partitioning.md): every
        LIVE partition on every node is explained by checkpoint truth (a
        Live-phase partition record or a completed claim's grant), and
        every Live-phase record points at a live partition.  Aged by the
        leak grace so in-flight create/destroy windows (Creating/
        Destroying phases are exempt by construction) never false-fire;
        crashes must converge through the recovery sweep inside it."""
        from tpudra.plugin import partitions as partrec_mod
        from tpudra.plugin.checkpoint import PREPARE_COMPLETED

        grace = self.budget.leak_grace_sim_s
        live_keys: list = []
        for i in range(self.config.nodes):
            try:
                cp = self.sim.drivers[i].state._cp.read_view()
                live = {p.uuid for p in self.sim._libs[i].list_partitions()}
            except Exception:  # noqa: BLE001 — mid-restart window
                logger.info("partition scan skipped node %d", i, exc_info=True)
                continue
            records = partrec_mod.records_in(cp)
            explained = {
                rec.partition_uuid
                for rec in records.values()
                if rec.phase != partrec_mod.PHASE_CREATING
                and rec.partition_uuid
            }
            for uid, claim in cp.prepared_claims.items():
                if partrec_mod.is_partition_record(uid):
                    continue
                if claim.status != PREPARE_COMPLETED:
                    continue
                for dev in claim.all_devices():
                    u = dev.attributes.get("partitionUUID")
                    if u:
                        explained.add(u)
            suspects: list[tuple] = []
            for uuid in live - explained:
                suspects.append(("hardware", i, uuid))
            for rec_uid, rec in records.items():
                if (
                    rec.phase == partrec_mod.PHASE_LIVE
                    and rec.partition_uuid not in live
                ):
                    suspects.append(("record", i, rec_uid))
            for key in suspects:
                live_keys.append(key)
                age_sim = (
                    self._partition_ager.age(key, "orphan")
                    * self.config.compression
                )
                kind, _, what = key
                self._check(
                    INV_PARTITION_LEAK,
                    age_sim <= grace,
                    key=key,
                    detail=(
                        f"{kind} {what} on node {i} unexplained for "
                        f"{age_sim:.0f} sim-seconds (grace {grace:.0f}) — "
                        "live partitions and checkpoint records diverged"
                    ),
                )
        self._partition_ager.prune(live_keys)
        self._pass_check(INV_PARTITION_LEAK)

    def _check_slice_convergence(self) -> None:
        """After every fault window (plus the convergence budget), the
        published ResourceSlice content must equal checkpoint truth: every
        allocatable device of every node advertised, nothing else.  Only
        asserted in QUIET windows — while faults are live the slices may
        legitimately lag."""
        if not self._quiet_and_settled():
            return
        try:
            listing = self.sim.kube.list(gvr.RESOURCE_SLICES)
        except ApiError:
            return
        by_node: dict[str, set] = {}
        for item in listing.get("items", []):
            spec = item.get("spec", {})
            if spec.get("driver") == TPU_DRIVER_NAME:
                devs = by_node.setdefault(spec.get("nodeName", ""), set())
                for d in spec.get("devices", []):
                    devs.add(d.get("name"))
        for i in range(self.config.nodes):
            node_name = self.sim.node_names[i]
            try:
                driver = self.sim.drivers[i]
                expected = (
                    set(driver.state.allocatable)
                    - driver.unhealthy_devices()
                    - driver.state.bound_sibling_devices()
                )
            except Exception:  # noqa: BLE001 — mid-restart window
                logger.info("slice scan skipped node %d", i, exc_info=True)
                continue
            published = by_node.get(node_name, set())
            self._check(
                INV_SLICE_CONVERGENCE,
                published == expected,
                key=(i, "slices", len(self._timeline)),
                detail=(
                    f"node {node_name}: published {sorted(published)} != "
                    f"checkpoint truth {sorted(expected)} in a quiet window"
                ),
            )
        self._pass_check(INV_SLICE_CONVERGENCE)

    def _check_lock_witness(self) -> None:
        """Finalize-time merge of the runtime witness log against the
        static lock model: a witnessed cycle or a model gap under compound
        faults is an ordering bug the quiet-path tests never provoked."""
        if not self.config.witness:
            return
        log = lockwitness.log_path()
        if not os.path.exists(log):
            self._anomaly("witness armed but no witness log was written")
            return
        from tpudra.analysis.witness import build_graph, merge

        graph = build_graph(os.path.dirname(os.path.dirname(__file__)))
        report = merge(graph, log)
        self._check(
            INV_LOCK_WITNESS,
            report.ok,
            key="witness",
            detail=(
                f"cycles={report.witnessed_cycles} "
                f"gaps={report.model_gaps}"
            ),
        )

    def _check_race_witness(self) -> None:
        """Finalize-time merge of the vector-clock race witness log
        against the static thread/race model: a witnessed unordered
        cross-thread write pair, or an access from a role the model cannot
        route to the field, is a race (or a model hole) the quiet-path
        tests never provoked."""
        if not self.config.witness:
            return
        log = racewitness.log_path()
        if not os.path.exists(log):
            self._anomaly("race witness armed but no race log was written")
            return
        from tpudra.analysis.racemerge import build_graph, merge

        result = build_graph(os.path.dirname(os.path.dirname(__file__)))
        report = merge(result, log)
        self._check(
            INV_RACE_WITNESS,
            report.ok,
            key="witness",
            detail=(
                f"violations={len(report.violations)} "
                f"gaps={len(report.model_gaps)} "
                f"coverage={report.coverage():.0%}"
            ),
        )

    # ------------------------------------------------------------------ run

    def run(self) -> dict:
        self.sim.start()
        workers = [
            threading.Thread(
                target=self._churn_loop, args=(w,), name=f"soak-churn-{w}"
            )
            for w in range(self.config.churn_workers)
        ]
        fault_thread = threading.Thread(target=self._fault_loop, name="soak-faults")
        monitor = threading.Thread(target=self._monitor_loop, name="soak-monitor")
        for t in (*workers, fault_thread, monitor):
            t.start()
        try:
            time.sleep(self.config.wall_s)
        finally:
            self._stop.set()
            for t in (*workers, fault_thread, monitor):
                t.join(timeout=30)
            self._maybe_clear_latency(force=True)
            # A fault thread stopped mid-disk_fault must not leave the
            # process-global plan faulting the post-run settle.
            storage.clear_fault_plan()
        # Post-run settle: one GC sweep + a final convergence check in a
        # guaranteed-quiet cluster, then the witness merge.
        for i in range(self.config.nodes):
            self._gc_pass(i)
        self._check_lock_witness()
        self._check_race_witness()
        report = self._report()
        self._close_cd_stack()
        self._close_daemon_stack()
        self.sim.close()
        path = self.config.report_path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        logger.info("soak report written to %s", path)
        return report

    # --------------------------------------------------------------- report

    @staticmethod
    def _counter_value(counter) -> float:
        """Current value of an unlabeled prometheus Counter via the public
        collect() surface (no private-attr reads)."""
        total = 0.0
        for metric in counter.collect():
            for sample in metric.samples:
                if sample.name.endswith("_total"):
                    total += sample.value
        return total

    def _report(self) -> dict:
        with self._samples_lock:
            samples = list(self._bind_samples)
            errors = list(self._bind_errors)
        with self._records_lock:
            timeline = list(self._timeline)
            checks = {k: dict(v) for k, v in self._checks.items()}
            violations = list(self._violations)
            anomalies = list(self._anomalies)
            max_stuck = self._max_stuck_sim
        by_window: dict[str, list[float]] = {}
        for _, ms, tag in samples:
            by_window.setdefault(tag, []).append(ms)
        err_by_window: dict[str, int] = {}
        for _, tag, _ in errors:
            err_by_window[tag] = err_by_window.get(tag, 0) + 1
        all_ms = [ms for _, ms, _ in samples]
        overall = latency_summary(all_ms)
        by_kind: dict[str, int] = {}
        for r in timeline:
            by_kind[r.kind] = by_kind.get(r.kind, 0) + 1
        sim_hours = self._now() / 3600.0
        budget = self.budget
        slo = {
            "bind_p99_ms": {
                "value": overall["p99_ms"],
                "budget": budget.bind_p99_ms,
                "ok": bool(all_ms) and overall["p99_ms"] <= budget.bind_p99_ms,
            },
            "max_claim_stuck_sim_s": {
                "value": round(max_stuck, 1),
                "budget": budget.max_claim_stuck_sim_s,
                "ok": max_stuck < budget.max_claim_stuck_sim_s,
            },
            "invariant_violations": {
                "value": len(violations),
                "budget": 0,
                "ok": not violations,
            },
        }
        return {
            "config": {
                "seed": self.config.seed,
                "nodes": self.config.nodes,
                "chips_per_node": self.config.chips_per_node,
                "wall_s": self.config.wall_s,
                "compression": self.config.compression,
                "fault_kinds": list(self.config.fault_kinds),
                "budget": asdict(budget),
                "witness": self.config.witness,
                "trace": trace.enabled(),
            },
            "sim_hours": round(sim_hours, 3),
            "faults": {
                "injected_total": len(timeline),
                "by_kind": by_kind,
                "timeline": [
                    {
                        **r.spec(),
                        "t_sim_end": (
                            round(r.t_sim_end, 1)
                            if r.t_sim_end is not None
                            else None
                        ),
                        "recovered_sim_s": (
                            round(r.recovered_sim_s, 1)
                            if r.recovered_sim_s is not None
                            else None
                        ),
                    }
                    for r in timeline
                ],
            },
            "bind": {
                "overall": overall,
                "by_window": {
                    tag: latency_summary(ms) for tag, ms in by_window.items()
                },
                "errors": {
                    "total": len(errors),
                    "by_window": err_by_window,
                },
            },
            "invariants": {
                inv: {
                    "checks": counts["ok"] + counts["violation"],
                    "violations": counts["violation"],
                }
                for inv, counts in checks.items()
            },
            "recovery": {
                "samples_sim_s": [round(s, 1) for s in self._recovery_samples],
                "max_sim_s": (
                    round(max(self._recovery_samples), 1)
                    if self._recovery_samples
                    else 0.0
                ),
                "budget_sim_s": budget.recovery_sim_s,
            },
            "anomalies": anomalies,
            "violations": violations,
            "failover": {
                # The acceptance counter (docs/ha.md): >0 proves at least
                # one stale-leader commit was actually refused at the WAL
                # this run.  Metric value + the soak's own observation so
                # a cross-test metric residue can never fake the latter.
                "tpudra_gang_stale_leader_rejections_total": (
                    self._counter_value(metrics.GANG_STALE_LEADER_REJECTIONS)
                ),
                "stale_leader_rejections_observed": self._stale_rejections,
                "stale_probes_run": self._stale_probes_run,
                "leader_terms_started": self._elector_seq,
                "time_to_new_leader_sim_s": [
                    round(s, 1) for s in self._failover_samples_sim
                ],
            },
            "slo": slo,
        }


# --------------------------------------------------------------------- CLI

PROFILES = {
    # ≤ 120 s wall including the witness merge; ≥ 1 simulated hour.
    "short": dict(wall_s=75.0, compression=60.0),
    # A developer-box long run: ~10 simulated hours.
    "long": dict(wall_s=600.0, compression=60.0),
}


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Chaos soak: compound-fault long-run with continuous "
        "invariant assertions and a JSON SLO report (docs/chaos.md)."
    )
    parser.add_argument("--profile", choices=sorted(PROFILES), default="short")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--nodes", type=int, default=None)
    parser.add_argument("--wall-s", type=float, default=None)
    parser.add_argument("--compression", type=float, default=None)
    parser.add_argument("--report", default="/tmp/tpudra_soak.json")
    parser.add_argument(
        "--replay",
        default=None,
        metavar="REPORT_JSON",
        help="re-execute the fault timeline recorded in a prior report "
        "(or in one of its violations) instead of drawing a fresh one",
    )
    parser.add_argument(
        "--no-witness",
        action="store_true",
        help="skip the lock-witness arming + finalize merge",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    # Tracing is ON for the soak (like the lock witness): the flight
    # recorder must have spans to dump when an invariant fires, and the
    # SLO gate doubles as the "soak passes with tracing on" proof.  An
    # operator opts out (or redirects the log) via the env.
    os.environ.setdefault(trace.ENV_TRACE, "1")
    os.environ.setdefault(
        trace.ENV_TRACE_LOG, os.path.abspath(args.report) + ".trace.jsonl"
    )
    cfg_kwargs = dict(PROFILES[args.profile])
    if args.nodes is not None:
        cfg_kwargs["nodes"] = args.nodes
    if args.wall_s is not None:
        cfg_kwargs["wall_s"] = args.wall_s
    if args.compression is not None:
        cfg_kwargs["compression"] = args.compression
    replay_timeline = None
    seed = args.seed
    if args.replay:
        with open(args.replay) as f:
            prior = json.load(f)
        if prior.get("violations"):
            replay = prior["violations"][0]["replay"]
            replay_timeline = replay["timeline"]
            seed = replay["seed"]
        else:
            replay_timeline = prior["faults"]["timeline"]
            seed = prior["config"]["seed"]
    config = ChaosConfig(
        seed=seed,
        report_path=args.report,
        witness=not args.no_witness,
        replay_timeline=replay_timeline,
        **cfg_kwargs,
    )
    report = ChaosSoak(config).run()
    ok = all(entry["ok"] for entry in report["slo"].values())
    print(
        json.dumps(
            {
                "sim_hours": report["sim_hours"],
                "faults": report["faults"]["by_kind"],
                "bind_p99_ms": report["bind"]["overall"]["p99_ms"],
                "violations": len(report["violations"]),
                "slo_ok": ok,
                "report": args.report,
            },
            indent=2,
        )
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""``tpu-cluster-sim`` — run the hermetic scheduler/kubelet simulator.

Consumes a JSON config describing the simulated nodes (driver sockets, CDI
roots, node-level env) and reconciles against the apiserver named by
``--kube-api-server`` / ``KUBE_API_SERVER`` until SIGTERM.  The bats e2e
harness (tests/bats/clusterctl.py) generates the config and supervises this
process alongside the real driver binaries.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

from tpudra.kube.client import KubeClient
from tpudra.sim.kubelet import ClusterSim, parse_config


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu-cluster-sim", description=__doc__)
    p.add_argument("--config", required=True, help="sim config JSON path")
    p.add_argument(
        "--kube-api-server",
        default=os.environ.get("KUBE_API_SERVER", ""),
        help="apiserver URL (overrides the config's `server`)",
    )
    p.add_argument("--tick", type=float, default=0.15)
    p.add_argument("-v", "--verbosity", type=int,
                   default=int(os.environ.get("LOG_VERBOSITY", "0")))
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
        stream=sys.stdout,
    )
    server, nodes, base_env = parse_config(args.config)
    server = args.kube_api_server or server
    if not server:
        p.error("no apiserver: set --kube-api-server or the config's `server`")
    if not nodes:
        p.error("config has no nodes")

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())

    sim = ClusterSim(KubeClient(server), nodes, base_env)
    logging.getLogger(__name__).info(
        "cluster-sim: %d node(s) against %s", len(nodes), server
    )
    sim.run(stop, tick=args.tick)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Cluster-scale harness: hundreds of simulated nodes against one control plane.

Everything else in this repo exercises ONE node.  This harness is the
"millions of users" axis (ROADMAP item 1): N simulated nodes — each a real
in-process plugin ``Driver`` with its own checkpoint, device lib, and
(optionally) its own ResourceClaim informer — one real ``Controller``, one
shared ``FakeKube`` wrapped in per-verb request accounting, and a seeded
claim/ComputeDomain churn generator.  What it measures is the control
plane, not the silicon:

- **bind p50/p99** across nodes under sustained churn, through the real
  resolver (informer cache hit or read-through GET) and the real phased
  bind engine;
- **controller reconcile p50/p99** (every pass sampled, requeues included);
- **apiserver QPS by verb** over any measurement window (AccountingKube);
- **informer event lag**: create→handler-dispatch latency through the
  fake's watch fan-out;
- **watch fan-out stats**: event materializations, deliveries, slow-watcher
  overflows, history compactions (FakeKube.watch_stats).

Every contested mechanism has a legacy arm so the fixes are measured, not
argued (``bench.py --cluster-scale`` interleaves the arms):

=====================  ======================================  ==========================
knob                   fixed arm (default)                     legacy arm
=====================  ======================================  ==========================
share_watch_events     serialize-once event fan-out            deepcopy per watcher
fair                   priority lanes + per-key round-robin    single-heap FIFO
bulk_publish           one LIST for all nodes' slices          3 requests per node
=====================  ======================================  ==========================

Checkpoints live under ``/dev/shm`` when available (in-memory: the harness
measures control-plane behavior, not the host's fsync latency — the
checkpoint bench owns that axis).  Node count is bounded only by thread
headroom: each node informer is one thread; 256 nodes is the CI target,
1024 runs on a developer box.
"""

from __future__ import annotations

import logging
import os
import random
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Optional

from tpudra import TPU_DRIVER_NAME
from tpudra.clock import Clock
from tpudra.controller.controller import Controller, ManagerConfig
from tpudra.kube import gvr
from tpudra.kube.accounting import AccountingKube
from tpudra.kube.apply import BulkSlicePublisher
from tpudra.kube.errors import NotFound
from tpudra.kube.fake import FakeKube
from tpudra.kube.informer import Informer

logger = logging.getLogger(__name__)

CD_API_V = "resource.tpu.google.com/v1beta1"


def percentile(sorted_samples: list[float], q: float) -> float:
    """Nearest-rank percentile over an ascending-sorted sample list."""
    if not sorted_samples:
        return 0.0
    idx = min(len(sorted_samples) - 1, int(len(sorted_samples) * q))
    return sorted_samples[idx]


def latency_summary(samples_ms: list[float]) -> dict:
    s = sorted(samples_ms)
    return {
        "n": len(s),
        "p50_ms": round(percentile(s, 0.50), 3),
        "p99_ms": round(percentile(s, 0.99), 3),
        "max_ms": round(s[-1], 3) if s else 0.0,
    }


def make_claim(uid: str, node: str, devices: list[str], name: str, ns: str = "default") -> dict:
    """An allocated ResourceClaim bound to ``node``'s pool — the object the
    scheduler's allocator would have written (pool == node name, the
    driver's cache-filter contract)."""
    return {
        "metadata": {"uid": uid, "namespace": ns, "name": name},
        "status": {
            "allocation": {
                "devices": {
                    "results": [
                        {
                            "request": f"r{i}",
                            "driver": TPU_DRIVER_NAME,
                            "pool": node,
                            "device": d,
                        }
                        for i, d in enumerate(devices)
                    ],
                    "config": [],
                }
            }
        },
    }


def make_cd(name: str, ns: str = "default", num_nodes: int = 1) -> dict:
    return {
        "apiVersion": CD_API_V,
        "kind": "ComputeDomain",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "numNodes": num_nodes,
            "channel": {
                "resourceClaimTemplate": {"name": f"{name}-channel"},
                "allocationMode": "Single",
            },
        },
    }


def scratch_base() -> str:
    """An in-memory-backed scratch root when the host offers one: the
    harness's checkpoints must cost RAM, not fsync latency."""
    shm = "/dev/shm"
    if os.path.isdir(shm) and os.access(shm, os.W_OK):
        return shm
    return tempfile.gettempdir()


@dataclass
class ClusterScaleConfig:
    nodes: int = 8
    chips_per_node: int = 4
    generation: str = "v5e"
    #: Claims per churn wave; capped at nodes*chips so a wave's slots are
    #: disjoint (machinery contention, not allocation conflicts, is the
    #: thing under measurement).
    churn_claims: int = 64
    workers: int = 16
    #: Static ComputeDomain population whose spec flips each CD wave.
    compute_domains: int = 8
    seed: int = 0
    # -- A/B knobs (fixed arm defaults) -------------------------------------
    fair: bool = True
    share_watch_events: bool = True
    bulk_publish: bool = True
    #: One ResourceClaim informer per node (the production plugin's cache):
    #: this is what makes watch fan-out scale with N.
    node_informers: bool = True
    watch_queue_depth: int = 8192
    watch_history_limit: int = 32768
    driver_namespace: str = "tpudra-system"
    base_dir: Optional[str] = None
    #: Clock handed to every driver's stale-claim GC (tpudra/clock.py).
    #: The chaos soak passes a SkewedClock so its clock_skew fault can
    #: step the wall reading under live GC passes; None = system clock.
    gc_clock: Optional[Clock] = None


class ClusterScaleSim:
    """N plugin drivers + one controller against one accounted FakeKube."""

    def __init__(self, config: ClusterScaleConfig):
        self.config = config
        self._rng = random.Random(config.seed)
        self.kube = AccountingKube(
            FakeKube(
                watch_queue_depth=config.watch_queue_depth,
                watch_history_limit=config.watch_history_limit,
                per_watcher_copy=not config.share_watch_events,
            )
        )
        self._stop = threading.Event()
        #: Per-node stop events for the claim informers: a crashed node's
        #: informer must actually STOP (a dead plugin holds no watch), not
        #: ride the sim-wide event until close() — each plugin_crash in a
        #: long soak would otherwise leak a thread plus a live FakeKube
        #: watcher still being fanned events.
        self._node_stops: list[threading.Event] = [
            threading.Event() for _ in range(config.nodes)
        ]
        self._tmp = tempfile.TemporaryDirectory(
            prefix="tpudra-cluster-", dir=config.base_dir or scratch_base()
        )
        base = self._tmp.name

        self._base = base
        self.node_names: list[str] = [f"node-{i:04d}" for i in range(config.nodes)]
        for name in self.node_names:
            self.kube.create(gvr.NODES, {"metadata": {"name": name}, "spec": {}})

        # Node construction is syscall-bound (checkpoint dirs, device-state
        # files) and the syscalls release the GIL — build concurrently or a
        # 1024-node cluster pays minutes of serial mkdir/stat.
        with ThreadPoolExecutor(max_workers=max(8, config.workers)) as ctor_pool:
            built = list(ctor_pool.map(self._build_node, range(config.nodes)))
        self._libs = [lib for lib, _ in built]
        self.drivers = [driver for _, driver in built]

        self.controller = Controller(
            self.kube,
            ManagerConfig(
                driver_namespace=config.driver_namespace,
                fair_queue=config.fair,
                seed=config.seed,
            ),
        )
        # Reconcile instrumentation: every pass (ok / requeue / error) is
        # one latency sample plus a completion-log record for per-key wait
        # analysis (the flapping-CD injection reads it).
        self.reconcile_samples: list[float] = []
        self._reconcile_log: list[tuple[str, float]] = []  # (name, t_done)
        inner_reconcile = self.controller.manager.reconcile

        def timed_reconcile(namespace: str, name: str) -> None:
            t0 = time.perf_counter()
            try:
                inner_reconcile(namespace, name)
            finally:
                done = time.perf_counter()
                self.reconcile_samples.append(done - t0)
                self._reconcile_log.append((name, done))

        self.controller.manager.reconcile = timed_reconcile

        # Event-lag probe: one claims informer whose handler clocks
        # create→dispatch latency for claims this harness stamped.
        self._births: dict[str, float] = {}
        self._births_lock = threading.Lock()
        self.event_lag_samples: list[float] = []
        self._lag_informer = Informer(self.kube, gvr.RESOURCE_CLAIMS)
        self._lag_informer.add_handler(self._observe_lag)

        self._pool = ThreadPoolExecutor(
            max_workers=config.workers, thread_name_prefix="churn"
        )
        self._started = False

    def _build_node(self, i: int, initial_pool_generation: Optional[int] = 1):
        """Construct node ``i``'s device lib + plugin driver over its
        persistent dirs (``hw-{i}.json`` / ``p{i}`` / ``r{i}`` / ``c{i}``
        under the scratch base).  First build passes pool generation 1
        (fresh fake: nothing to outrank, and N constructor LISTs over a
        growing slice set would be O(N²) startup work); ``restart_node``
        passes None so a restarted driver takes the production reseed path
        and outranks its previous incarnation's slices."""
        # Imports deferred so `import tpudra.sim.cluster` stays cheap for
        # tools that only want the claim/CD builders.
        from tpudra.devicelib.mock import MockDeviceLib
        from tpudra.devicelib.topology import MockTopologyConfig
        from tpudra.plugin.driver import Driver, DriverConfig

        config = self.config
        lib = MockDeviceLib(
            config=MockTopologyConfig(
                generation=config.generation, num_chips=config.chips_per_node
            ),
            state_file=os.path.join(self._base, f"hw-{i}.json"),
        )
        driver = Driver(
            DriverConfig(
                node_name=self.node_names[i],
                plugin_dir=os.path.join(self._base, f"p{i}"),
                registry_dir=os.path.join(self._base, f"r{i}"),
                cdi_root=os.path.join(self._base, f"c{i}"),
                claim_cache=config.node_informers,
                initial_pool_generation=initial_pool_generation,
                gc_clock=config.gc_clock,
            ),
            self.kube,
            lib,
        )
        # The harness never start()s its drivers (no sockets, no publisher
        # thread — publish is inline), but the degraded-mode contract must
        # still hold under the soak's disk faults: the storage-heal
        # supervisor is the one production thread each node keeps.
        driver.start_storage_supervisor()
        # Startup reconciliation, exactly as Driver.start() runs it: the
        # partition recovery sweep (no-op unless DynamicPartitioning) —
        # a restarted node must reap crash-orphaned partitions before
        # serving (the soak's partition_fault destroy-then-SIGKILL leg).
        swept = driver.state.destroy_unknown_partitions()
        if swept:
            logger.warning(
                "node %s startup sweep destroyed %d partition(s)",
                self.node_names[i], swept,
            )
        return lib, driver

    # ----------------------------------------------------- fault injection

    def crash_node(self, i: int) -> None:
        """Abandon node ``i``'s driver the way SIGKILL would (no clean-
        shutdown journal compaction — ``Driver.crash_stop``).  The node's
        on-disk state freezes at whatever boundary its last checkpoint
        commit reached; ``restart_node`` must then converge through the
        real recovery path.  The node's claim informer stops with it — a
        dead plugin holds no watch.  The chaos soak (sim/chaos.py) is the
        caller."""
        self._node_stops[i].set()
        self.drivers[i].crash_stop()

    def restart_node(self, i: int) -> None:
        """Rebuild node ``i``'s driver over the same persistent dirs — the
        crashed (or cleanly stopped) plugin's restart.  Recovery is the
        REAL path: checkpoint snapshot + journal replay with torn-tail
        truncation, pool generation reseeded from live slices, informer
        re-sync, slice republication."""
        lib, driver = self._build_node(i, initial_pool_generation=None)
        self._libs[i] = lib
        self.drivers[i] = driver
        self._node_stops[i] = threading.Event()
        if self._started:
            driver.publish_resources()
            if self.config.node_informers and driver.claim_informer is not None:
                driver.claim_informer.start(self._node_stops[i])
                driver.claim_informer.wait_for_sync(30)

    # ------------------------------------------------------------ lifecycle

    def start(self, controller: bool = True) -> "ClusterScaleSim":
        """Publish every node's slices, start per-node informers, the lag
        probe, and (by default) the controller.  Returns self."""
        t0 = time.perf_counter()
        before = self.kube.snapshot()
        applier = BulkSlicePublisher(self.kube) if self.config.bulk_publish else None
        for d in self.drivers:
            d.publish_resources(applier=applier)
        self.publish_stats = {
            "wall_s": round(time.perf_counter() - t0, 3),
            "requests": sum(
                AccountingKube.window(before, self.kube.snapshot()).values()
            ),
        }
        if self.config.node_informers:
            for i, d in enumerate(self.drivers):
                d.claim_informer.start(self._node_stops[i])
        self._lag_informer.start(self._stop)
        self._lag_informer.wait_for_sync()
        if controller:
            self.controller.start(self._stop)
            self.controller._cd_informer.wait_for_sync()
        if self.config.node_informers:
            deadline = time.monotonic() + 60
            for d in self.drivers:
                d.claim_informer.wait_for_sync(
                    max(0.1, deadline - time.monotonic())
                )
        self._started = True
        return self

    def close(self) -> None:
        self._stop.set()
        for stop in self._node_stops:
            stop.set()
        self.controller.queue.shutdown()
        self._pool.shutdown(wait=False)
        for d in self.drivers:
            try:
                d.stop()
            except Exception:  # noqa: BLE001 — teardown must visit every node
                logger.exception("driver stop failed")
        self._tmp.cleanup()

    def __enter__(self) -> "ClusterScaleSim":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------- measurement

    def _observe_lag(self, etype: str, obj: dict) -> None:
        if etype != "ADDED":
            return
        uid = obj.get("metadata", {}).get("uid", "")
        with self._births_lock:
            born = self._births.pop(uid, None)
        if born is not None:
            self.event_lag_samples.append(time.monotonic() - born)

    def measured_window(self, fn: Callable[[], dict]) -> dict:
        """Run ``fn`` and annotate its result with the window's apiserver
        load: per-verb request deltas and aggregate QPS."""
        before = self.kube.snapshot()
        t0 = time.perf_counter()
        out = fn()
        wall = max(time.perf_counter() - t0, 1e-9)
        window = AccountingKube.window(before, self.kube.snapshot())
        out["apiserver"] = {
            "by_verb": window,
            "total": sum(window.values()),
            "qps": round(sum(window.values()) / wall, 1),
            "wall_s": round(wall, 3),
        }
        return out

    # --------------------------------------------------------------- churn

    def churn_wave(self, tag: str, n_claims: Optional[int] = None) -> dict:
        """One claim-churn wave: create → resolve (through the node's real
        resolver) → prepare → unprepare → delete, fanned across the worker
        pool on disjoint (node, chip) slots, order shuffled by the seeded
        RNG.  Returns bind latency percentiles for the wave."""
        cfg = self.config
        n = min(
            n_claims if n_claims is not None else cfg.churn_claims,
            cfg.nodes * cfg.chips_per_node,
        )
        slots = [(i % cfg.nodes, (i // cfg.nodes) % cfg.chips_per_node) for i in range(n)]
        self._rng.shuffle(slots)
        errors: list[str] = []
        err_lock = threading.Lock()

        def one(i: int) -> float:
            node_idx, chip = slots[i]
            driver = self.drivers[node_idx]
            node = self.node_names[node_idx]
            uid = f"churn-{tag}-{i}"
            claim = make_claim(uid, node, [f"tpu-{chip}"], name=uid)
            with self._births_lock:
                self._births[uid] = time.monotonic()
            self.kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            t0 = time.perf_counter()
            try:
                # The kubelet path: a claim REFERENCE resolved into the full
                # object (informer cache or read-through GET), then the
                # phased bind engine.
                resolved = driver.sockets.resolve_claim("default", uid, uid)
                resp = driver.prepare_resource_claims([resolved])
                dt = (time.perf_counter() - t0) * 1000.0
                err = resp["claims"][uid].get("error")
                if err:
                    with err_lock:
                        errors.append(err)
                    return dt
                driver.unprepare_resource_claims([{"uid": uid}])
                return dt
            finally:
                try:
                    self.kube.delete(gvr.RESOURCE_CLAIMS, uid, "default")
                except NotFound:
                    pass

        samples = list(self._pool.map(one, range(n)))
        out = latency_summary(samples)
        out["samples_ms"] = samples  # raw, for cross-wave pooling (bench)
        out["bind_errors"] = len(errors)
        if errors:
            out["first_error"] = errors[0][:160]
        return out

    # ----------------------------------------------------------- controller

    def seed_compute_domains(self) -> None:
        for i in range(self.config.compute_domains):
            self.kube.create(
                gvr.COMPUTE_DOMAINS, make_cd(f"cd-{i:03d}", num_nodes=1), "default"
            )

    def cd_wave(self, flip_to: int, timeout: float = 60.0) -> dict:
        """Flip every static CD's spec (numNodes) and wait for the
        controller to drain the resulting reconciles.  Returns the wave's
        reconcile-latency percentiles (from the samples the wave added)."""
        n_before = len(self.reconcile_samples)
        for i in range(self.config.compute_domains):
            name = f"cd-{i:03d}"
            cd = self.kube.get(gvr.COMPUTE_DOMAINS, name, "default")
            cd["spec"]["numNodes"] = flip_to
            self.kube.update(gvr.COMPUTE_DOMAINS, cd, "default")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if (
                len(self.reconcile_samples) - n_before >= self.config.compute_domains
                and self.controller.queue.drain(0.2)
            ):
                break
        wave = [s * 1000.0 for s in self.reconcile_samples[n_before:]]
        out = latency_summary(wave)
        out["samples_ms"] = wave  # raw, for cross-wave pooling (bench)
        return out

    def combined_wave(self, tag: str, flip_to: int) -> tuple[dict, dict]:
        """One churn wave and one CD-flip wave IN FLIGHT TOGETHER — the
        cluster-scale scenario proper: the controller reconciles while the
        claim churn's watch fan-out and apiserver traffic are live, so
        reconcile p99 carries the contention a quiet-cluster measurement
        would hide.  Returns (churn summary, reconcile summary)."""
        churn_result: dict = {}

        def churn() -> None:
            churn_result.update(self.churn_wave(tag))

        churn_thread = threading.Thread(target=churn, name=f"churn-{tag}")
        churn_thread.start()
        cd = self.cd_wave(flip_to)
        churn_thread.join()
        return churn_result, cd

    def flapping_injection(
        self, victims: int = 32, warm_s: float = 0.2, timeout: float = 30.0
    ) -> dict:
        """One ComputeDomain flaps (metadata churn at full producer speed)
        while ``victims`` quiet CDs arrive once each.  Reports how long the
        LAST victim waited for its first reconcile — the "no single key
        starves 999 others" bound — plus the flap volume absorbed."""
        flapper = self.kube.create(
            gvr.COMPUTE_DOMAINS, make_cd("flapper", num_nodes=1), "default"
        )
        stop_flap = threading.Event()
        flaps = [0]

        def flap() -> None:
            while not stop_flap.is_set():
                try:
                    self.kube.patch(
                        gvr.COMPUTE_DOMAINS,
                        "flapper",
                        {"metadata": {"labels": {"flap": str(flaps[0])}}},
                        "default",
                    )
                    flaps[0] += 1
                except Exception:  # noqa: BLE001 — racing teardown
                    return

        flap_thread = threading.Thread(target=flap, daemon=True, name="cd-flapper")
        flap_thread.start()
        time.sleep(warm_s)
        victim_names = {f"victim-{i:03d}" for i in range(victims)}
        log_start = len(self._reconcile_log)
        t0 = time.perf_counter()
        for name in sorted(victim_names):
            self.kube.create(gvr.COMPUTE_DOMAINS, make_cd(name, num_nodes=1), "default")
        waits: dict[str, float] = {}
        deadline = time.monotonic() + timeout
        while len(waits) < victims and time.monotonic() < deadline:
            for name, t_done in self._reconcile_log[log_start:]:
                if name in victim_names and name not in waits:
                    waits[name] = (t_done - t0) * 1000.0
            time.sleep(0.01)
        stop_flap.set()
        flap_thread.join(2)
        for name in sorted(victim_names) + ["flapper"]:
            try:
                self.kube.delete(gvr.COMPUTE_DOMAINS, name, "default")
            except NotFound:
                pass
        vals = sorted(waits.values())
        return {
            "victims": victims,
            "victims_reconciled": len(waits),
            "flap_updates": flaps[0],
            "victim_wait_p50_ms": round(percentile(vals, 0.50), 1),
            "victim_wait_max_ms": round(vals[-1], 1) if vals else float("inf"),
        }

    # --------------------------------------------------------------- report

    def watch_report(self) -> dict:
        stats = dict(self.kube.watch_stats)
        stats["watchers"] = len(self.kube._watchers)
        return stats

    def lag_report(self) -> dict:
        return latency_summary([s * 1000.0 for s in self.event_lag_samples])

    def reconcile_report(self) -> dict:
        return latency_summary([s * 1000.0 for s in self.reconcile_samples])

"""Hermetic cluster simulator — the harness the reference never had.

The reference's e2e suite (tests/bats/, SURVEY.md §4) can only run on
hardware CI runners because it needs a real cluster: a scheduler that
understands DRA, a kubelet that calls the driver's gRPC sockets, and a
container runtime that applies CDI specs.  This package simulates exactly
those three actors against the fake apiserver (tpudra/kube/httpserver.py),
so the same bats suite runs on a laptop:

- ``sched``: a DRA-aware micro-scheduler with KEP-4815 SharedCounters
  arithmetic (the scheduler-side contract of reference partitions.go:85-307).
- ``kubelet``: per-node claim prepare/unprepare over the real DRA gRPC
  sockets, container processes launched with the CDI-injected environment,
  readiness probes, and pod status/log reporting — plus minimal DaemonSet
  and Deployment controllers so the ComputeDomain stack's spawned pods run.
- ``main``: the ``tpu-cluster-sim`` entry point used by tests/bats.

Everything the simulator does to the driver is indistinguishable from a
real kubelet: it speaks the same protobuf DRA service over the same unix
sockets and applies the same transient CDI spec files the container
runtime would.
"""

"""containerd's CDI application, simplified — shared by the cluster sim's
consumers, the CDI-contract tests (tests/test_cdi_to_workload.py), and the
bench's real-chip claim→jax loop (bench.bench_claim_to_jax).

For each requested "<kind>=<name>" device id, merge that device's
containerEdits (and the spec's common containerEdits) into an OCI-ish
container view: env map, device-node list, (host, container) mount pairs.
The full pod-runtime version (env rewriting through mounts, process spawn)
lives in tpudra/sim/kubelet.py; this is the minimal merge both layers of
the contract agree on.
"""

from __future__ import annotations


def apply_cdi(spec: dict, requested_ids: list) -> tuple[dict, list, list]:
    kind = spec["kind"]
    by_name = {d["name"]: d for d in spec["devices"]}
    env: dict = {}
    device_nodes: list = []
    mounts: list = []

    def merge(edits: dict) -> None:
        for kv in edits.get("env", []):
            k, _, v = kv.partition("=")
            env[k] = v
        device_nodes.extend(n["path"] for n in edits.get("deviceNodes", []))
        mounts.extend(
            (m["hostPath"], m["containerPath"]) for m in edits.get("mounts", [])
        )

    merge(spec.get("containerEdits", {}))
    for cdi_id in requested_ids:
        req_kind, _, name = cdi_id.partition("=")
        if req_kind != kind:
            raise ValueError(f"foreign CDI kind {cdi_id}")
        if name not in by_name:
            raise ValueError(f"unresolvable CDI device {cdi_id}")
        merge(by_name[name]["containerEdits"])
    return env, device_nodes, mounts

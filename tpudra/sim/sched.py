"""DRA-aware micro-scheduler for the hermetic cluster simulator.

Allocates ResourceClaim(Template) device requests against the ResourceSlices
published in the apiserver, first-fit, with KEP-4815 SharedCounters
arithmetic — a full device blocks its partitions, disjoint partitions
co-allocate, and counter exhaustion refuses (the scheduler-side contract of
reference cmd/gpu-kubelet-plugin/partitions.go:85-307).

DeviceClass matching mirrors the CEL selectors the chart's DeviceClasses
carry (deployments/helm/tpu-dra-driver/templates/deviceclasses.yaml) without
a CEL evaluator: each class name maps to the device ``type`` attribute its
selector tests.
"""

from __future__ import annotations

import re
from typing import Iterator, Optional

from tpudra.kube import gvr


class InsufficientResources(AssertionError):
    """A device request cannot be satisfied by the published slices.

    Subclasses AssertionError so test suites can assert on refusal the same
    way the reference bats tests assert a pod stays Pending.
    """


# DeviceClass name -> predicate over the device `type` attribute, standing in
# for the CEL expression of the corresponding DeviceClass object.
_CLASS_TYPE = {
    "tpu.google.com": lambda t: t == "chip",
    "tpu-partition.google.com": lambda t: t.startswith("partition"),
    "tpu-vfio.google.com": lambda t: t == "vfio",
    "compute-domain-daemon.tpu.google.com": lambda t: t == "daemon",
    "compute-domain-default-channel.tpu.google.com": lambda t: t == "channel",
}

# extendedResourceName -> DeviceClass advertising it (chart values.yaml).
EXTENDED_RESOURCE_CLASSES = {
    "tpu.google.com/chip": "tpu.google.com",
}


# One clause of the CEL subset: device.attributes["<domain>"].<attr> ==
# <"string" | int | bool> — the shape every chart DeviceClass and demo
# selector uses.
_CEL_CLAUSE = re.compile(
    r'^device\.attributes\["([^"]*)"\]\.(\w+)\s*==\s*("(?:[^"]*)"|\d+|true|false)$'
)


def cel_matches(expr: str, attributes: dict, domain: str = "") -> bool:
    """Evaluate the CEL subset the suite's selectors use: conjunctions
    (&&) of attribute equality tests against the device driver's attribute
    domain.  Anything outside the subset — including a wrong domain or a
    type-mismatched comparison, both CEL errors — fails CLOSED (no match):
    a simulator must never grant a device a real scheduler's CEL evaluator
    might refuse."""
    expr = " ".join(expr.split())
    if not expr:
        return True
    for clause in expr.split("&&"):
        m = _CEL_CLAUSE.fullmatch(clause.strip())
        if not m:
            return False
        clause_domain, attr, literal = m.group(1), m.group(2), m.group(3)
        if domain and clause_domain != domain:
            return False
        # Typed comparison: the literal's CEL type must match the boxed
        # attribute type exactly (bool==int is a CEL error, not a match).
        if literal.startswith('"'):
            want = {"string": literal[1:-1]}
        elif literal in ("true", "false"):
            want = {"bool": literal == "true"}
        else:
            want = {"int": int(literal)}
        if attributes.get(attr) != want:
            return False
    return True


class Scheduler:
    """First-fit DRA allocator with KEP-4815 counter arithmetic."""

    def __init__(self, kube):
        self._kube = kube
        self._allocated: set[tuple[str, str]] = set()  # (pool, device)
        # KEP-4815 ledger: units consumed per (pool, counterSet, counter).
        self._consumed: dict[tuple[str, str, str], int] = {}
        self._claim_demand: dict[str, dict[tuple[str, str, str], int]] = {}
        # (pool, device) pairs each claim holds, for release-by-uid.
        self._claim_devices: dict[str, list[tuple[str, str]]] = {}

    def _published(self, node: Optional[str] = None) -> Iterator[tuple[str, str, dict]]:
        for s in self._kube.list(gvr.RESOURCE_SLICES)["items"]:
            spec = s["spec"]
            if node and spec.get("nodeName") not in (None, node):
                continue
            pool = spec["pool"]["name"]
            for dev in spec.get("devices", []):
                yield pool, spec["driver"], dev

    def _capacity(self) -> dict[tuple[str, str, str], int]:
        """Published SharedCounters across all slices of every pool (the
        split form carries them in a devices-free slice)."""
        caps: dict[tuple[str, str, str], int] = {}
        for s in self._kube.list(gvr.RESOURCE_SLICES)["items"]:
            pool = s["spec"]["pool"]["name"]
            for cs in s["spec"].get("sharedCounters", []):
                for cname, v in cs.get("counters", {}).items():
                    caps[(pool, cs["name"], cname)] = int(v["value"])
        return caps

    @staticmethod
    def _demand(pool: str, dev: dict) -> dict[tuple[str, str, str], int]:
        out: dict[tuple[str, str, str], int] = {}
        for cc in dev.get("consumesCounters", []):
            for cname, v in cc.get("counters", {}).items():
                out[(pool, cc["counterSet"], cname)] = int(v["value"])
        return out

    def _counters_fit(self, caps, demand) -> bool:
        return all(
            self._consumed.get(key, 0) + want <= caps.get(key, 0)
            for key, want in demand.items()
        )

    def allocate(
        self,
        rct,
        uid,
        namespace="default",
        name="claim",
        create=True,
        node: Optional[str] = None,
        owner: Optional[dict] = None,
    ):
        """Allocate every request of an RCT-shaped spec; returns the
        ResourceClaim (created in the apiserver unless ``create=False``).

        ``node`` restricts candidate devices to slices advertising that
        nodeName — the node-fit half of real scheduling.  Raises
        InsufficientResources (leaking nothing) when any request cannot be
        satisfied.
        """
        spec = rct["spec"]["spec"]["devices"]
        results = []
        caps = self._capacity()
        claim_demand: dict[tuple[str, str, str], int] = {}
        for req in spec.get("requests", []):
            count = req.get("exactly", {}).get("count", 1)
            matched = 0
            for pool, driver, dev in self._published(node):
                if (pool, dev["name"]) in self._allocated:
                    continue
                if not self._matches(req, dev, driver):
                    continue
                demand = self._demand(pool, dev)
                if not self._counters_fit(caps, demand):
                    continue
                self._allocated.add((pool, dev["name"]))
                for key, want in demand.items():
                    self._consumed[key] = self._consumed.get(key, 0) + want
                    claim_demand[key] = claim_demand.get(key, 0) + want
                results.append(
                    {"request": req["name"], "driver": driver,
                     "pool": pool, "device": dev["name"]}
                )
                matched += 1
                if matched == count:
                    break
            if matched != count:
                # Roll back everything this allocate reserved — a refused
                # claim must not leak devices or counters.
                for r in results:
                    self._allocated.discard((r["pool"], r["device"]))
                self._release_counters(claim_demand)
                raise InsufficientResources(f"cannot satisfy request {req['name']}")
        config = []
        for entry in spec.get("config", []):
            config.append({"source": "FromClaim", "requests": [], **entry})
        claim = {
            "apiVersion": "resource.k8s.io/v1",
            "kind": "ResourceClaim",
            "metadata": {"uid": uid, "namespace": namespace, "name": name},
            "status": {"allocation": {"devices": {"results": results, "config": config}}},
        }
        if owner:
            claim["metadata"]["ownerReferences"] = [owner]
        if create:
            # Allocation lives in the apiserver: the plugin resolves claim
            # references kubelet sends over the DRA gRPC wire.
            claim = self._kube.create(gvr.RESOURCE_CLAIMS, claim, namespace)
        real_uid = claim["metadata"]["uid"]
        self._claim_demand[real_uid] = claim_demand
        self._claim_devices[real_uid] = [(r["pool"], r["device"]) for r in results]
        return claim

    def _matches(self, req, dev, driver: str = "") -> bool:
        cls = req.get("exactly", {}).get("deviceClassName", "")
        dtype = dev["attributes"].get("type", {}).get("string", "")
        pred = _CLASS_TYPE.get(cls)
        if pred is None or not pred(dtype):
            return False
        # DRA ANDs all selectors; each must hold against the device.  The
        # attribute domain in a selector is the publishing driver's name.
        return all(
            cel_matches(
                sel.get("cel", {}).get("expression", ""), dev["attributes"], driver
            )
            for sel in req.get("exactly", {}).get("selectors", [])
        )

    def allocate_extended(
        self,
        limits: dict[str, int],
        uid: str,
        namespace="default",
        pod_name="pod",
        node: Optional[str] = None,
        owner: Optional[dict] = None,
    ):
        """The extendedResourceName translation a DRA-aware scheduler does
        (reference test_gpu_extres.bats): a pod requesting
        ``resources.limits: {"tpu.google.com/chip": N}`` gets a
        scheduler-authored ResourceClaim against the DeviceClass that
        advertises that extendedResourceName; the node plugin then sees a
        perfectly ordinary claim."""
        requests = []
        for res_name, count in limits.items():
            device_class = EXTENDED_RESOURCE_CLASSES.get(res_name)
            assert device_class, f"no DeviceClass advertises {res_name}"
            requests.append(
                {
                    "name": f"extres-{len(requests)}",
                    "exactly": {"deviceClassName": device_class, "count": count},
                }
            )
        rct = {
            "metadata": {"name": f"{pod_name}-extended-resources"},
            "spec": {"spec": {"devices": {"requests": requests, "config": []}}},
        }
        return self.allocate(
            rct, uid, namespace, f"{pod_name}-extended-resources",
            node=node, owner=owner,
        )

    def adopt(self, claim) -> None:
        """Absorb an already-allocated claim into the ledger (sim restart:
        the scheduler-cache rebuild a real scheduler does from the API)."""
        uid = claim["metadata"]["uid"]
        if uid in self._claim_devices:
            return
        results = (
            claim.get("status", {})
            .get("allocation", {})
            .get("devices", {})
            .get("results", [])
        )
        by_pool_dev = {}
        for pool, _, dev in self._published():
            by_pool_dev[(pool, dev["name"])] = dev
        demand: dict[tuple[str, str, str], int] = {}
        devices = []
        for r in results:
            key = (r["pool"], r["device"])
            devices.append(key)
            self._allocated.add(key)
            dev = by_pool_dev.get(key)
            if dev:
                for k, want in self._demand(r["pool"], dev).items():
                    demand[k] = demand.get(k, 0) + want
                    self._consumed[k] = self._consumed.get(k, 0) + want
        self._claim_devices[uid] = devices
        self._claim_demand[uid] = demand

    def release(self, claim) -> None:
        """Release a claim's devices and counters (by object)."""
        self.release_uid(
            claim["metadata"]["uid"],
            [
                (r["pool"], r["device"])
                for r in claim.get("status", {})
                .get("allocation", {})
                .get("devices", {})
                .get("results", [])
            ],
        )

    def release_uid(self, uid: str, devices=None) -> None:
        for pool_dev in devices or self._claim_devices.get(uid, []):
            self._allocated.discard(pool_dev)
        self._claim_devices.pop(uid, None)
        self._release_counters(self._claim_demand.pop(uid, {}))

    def _release_counters(self, demand: dict[tuple[str, str, str], int]) -> None:
        for key, want in demand.items():
            left = self._consumed.get(key, 0) - want
            if left > 0:
                self._consumed[key] = left
            else:
                self._consumed.pop(key, None)

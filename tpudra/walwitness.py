"""Runtime WAL record→effect witness (the effectgraph dynamic side).

tpudra-effectgraph's static model (tpudra/analysis/effectmodel.py) claims
that every registered side effect is dominated by a durable intent record
of a matching kind; this module is its runtime cross-check.  With
``TPUDRA_WAL_WITNESS=1`` in the environment, the checkpoint commit path
notes every record kind it makes durable (journal append, snapshot write,
and the recovery read — a record loaded from disk IS journaled intent),
the effect sites on the bind/teardown path note every effect they run, and
each first-seen (effect, journaled-kind-set) pair is appended to a JSONL
witness log (``TPUDRA_WAL_WITNESS_LOG``, default
``tpudra-wal-witness.jsonl`` in the working directory).
``python -m tpudra.analysis --wal-witness <log>`` then merges the log into
the static effect graph: an effect the model has no site for is a model
gap, and an effect witnessed without its required kind journaled is a
witnessed ordering violation — both fail, exactly like the lock witness.

With the variable unset (every production path), every hook is a single
falsy env check — zero allocation, zero I/O.

Conventions shared with the static model:

- Kinds are record *families*, not uids (every ``partition/<name>`` record
  is one ``partition`` node) — ``record_kind`` below is the one
  classifier, imported by the static side so the two can never drift.
- The journaled set is process-wide and monotone: durability has no
  thread affinity, and a kind once fsynced stays journaled for the life
  of the process.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from typing import Iterable, Iterator

ENV_WITNESS = "TPUDRA_WAL_WITNESS"
ENV_WITNESS_LOG = "TPUDRA_WAL_WITNESS_LOG"
DEFAULT_LOG = "tpudra-wal-witness.jsonl"

#: Record-uid namespace prefixes → stripe family.  Everything else is a
#: plain claim record (the default namespace).
_KIND_PREFIXES = (
    ("gangmeta/", "gangmeta"),
    ("gang/", "gang"),
    ("partition/", "partition"),
)


def record_kind(uid: str) -> str:
    """The stripe family of one checkpoint record uid."""
    for prefix, kind in _KIND_PREFIXES:
        if uid.startswith(prefix):
            return kind
    return "claim"


def enabled() -> bool:
    return os.environ.get(ENV_WITNESS, "") not in ("", "0")


def log_path() -> str:
    return os.environ.get(ENV_WITNESS_LOG, "") or os.path.join(
        os.getcwd(), DEFAULT_LOG
    )


# ----------------------------------------------------------------- recording

_sink_guard = threading.Lock()
_sink = None  # opened lazily, OUTSIDE _sink_guard (no open-under-lock)
_journaled: set = set()  # kinds made durable by this process (monotone)
_written: set = set()  # emitted record keys (first-seen dedup)

# Dynamic scopes mirroring the static model's two subtree directives
# (thread-local: an exempt probe on one thread must not blind the witness
# to a concurrent bind on another).
_tls = threading.local()


@contextlib.contextmanager
def exempt() -> Iterator[None]:
    """Runtime twin of ``# tpudra-wal: nonrecoverable``: effects inside
    this scope deliberately run journal-less (the static walk skips the
    annotated subtree; the witness must not report what the model
    deliberately does not check).  Use it exactly where the annotation
    sits — a scope without the annotation, or vice versa, is model
    drift the merge exists to catch."""
    _tls.exempt = getattr(_tls, "exempt", 0) + 1
    try:
        yield
    finally:
        _tls.exempt -= 1


@contextlib.contextmanager
def recovery_scope(*kinds: str) -> Iterator[None]:
    """Runtime twin of ``# tpudra-wal: recovers=KIND``: within this scope
    the declared kinds count as journaled — a recovery sweep acts FROM
    checkpoint truth, so its effects carry the checkpoint's own
    authority even when the specific record is long gone (a record-less
    stray being reaped has no uid to have journaled)."""
    prev = getattr(_tls, "assumed", ())
    _tls.assumed = prev + tuple(kinds)
    try:
        yield
    finally:
        _tls.assumed = prev


def _emit(record: dict) -> None:
    global _sink
    if _sink is None:
        # Open before taking the guard; a racing double-open leaves one
        # extra O_APPEND handle to close, never a torn line.
        fh = open(log_path(), "a", encoding="utf-8")
        with _sink_guard:
            if _sink is None:
                _sink = fh
                fh = None
        if fh is not None:
            fh.close()
    line = json.dumps(record, sort_keys=True) + "\n"
    with _sink_guard:
        _sink.write(line)
        _sink.flush()


def note_journal(uids: Iterable[str]) -> None:
    """Record that every uid's record kind is now durable.  Called by the
    checkpoint layer AFTER the fsync (journal append, snapshot replace)
    and on recovery read — before any crashpoint, so a crash-armed run
    still witnesses exactly what it made durable."""
    if not enabled():
        return
    new_records = []
    with _sink_guard:
        for uid in uids:
            kind = record_kind(uid)
            if kind in _journaled:
                continue
            _journaled.add(kind)
            key = ("record", kind)
            if key not in _written:
                _written.add(key)
                new_records.append({"t": "record", "kind": kind})
    for record in new_records:
        _emit(record)


def note_effect(effect_id: str) -> None:
    """Record that a registered side effect ran, with the kinds journaled
    at that moment — one record per first-seen (effect, kind-set) pair."""
    if not enabled() or getattr(_tls, "exempt", 0):
        return
    assumed = getattr(_tls, "assumed", ())
    with _sink_guard:
        journaled = tuple(sorted(_journaled.union(assumed)))
        key = ("effect", effect_id, journaled)
        seen = key in _written
        if not seen:
            _written.add(key)
    if not seen:
        _emit(
            {"t": "effect", "effect": effect_id, "journaled": list(journaled)}
        )


def journaled_kinds() -> tuple:
    """The process's journaled-kind set (tests)."""
    with _sink_guard:
        return tuple(sorted(_journaled))


def reset_for_tests() -> None:
    """Drop the in-process journaled/dedup/sink state so a test can
    witness into a fresh log file."""
    global _sink, _journaled, _written
    with _sink_guard:
        sink, _sink = _sink, None
        _journaled = set()
        _written = set()
    if sink is not None:
        sink.close()


# ------------------------------------------------------------------- reading


def read_log(path: str) -> tuple[set, list]:
    """(journaled kinds, [(effect_id, frozenset(journaled-at-the-time))])
    recorded in a witness log.  Malformed lines are skipped — a crashed
    witness process may tear its final line."""
    kinds: set = set()
    effects: list = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("t") == "record" and rec.get("kind"):
                    kinds.add(rec["kind"])
                elif rec.get("t") == "effect" and rec.get("effect"):
                    effects.append(
                        (rec["effect"], frozenset(rec.get("journaled", ())))
                    )
    except FileNotFoundError:
        pass
    return kinds, effects

"""Own-pod readiness informer.

The analog of compute-domain-daemon/podmanager.go:45-149: an informer on the
daemon's own pod pushes kubelet-probe readiness transitions into the clique
status, so a Ready/NotReady flip propagates on the watch event instead of a
poll tick.  The kubelet's probes (the ``check`` subcommand querying the
native daemon's status socket) are what flip the pod condition; this mirrors
kubelet's verdict back into the ComputeDomainClique daemon entry
(cdclique.go:429).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.informer import Informer

logger = logging.getLogger(__name__)


def pod_is_ready(pod: dict) -> bool:
    for cond in pod.get("status", {}).get("conditions", []):
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


class PodManager:
    """Watches this daemon's own pod and reports Ready transitions."""

    def __init__(
        self,
        kube: KubeAPI,
        namespace: str,
        pod_name: str,
        on_ready_change: Callable[[bool], None],
    ):
        self._pod_name = pod_name
        self._on_ready_change = on_ready_change
        # Field-selected to our own pod (the reference podmanager.go does
        # the same): N daemons in a shared namespace must not each cache and
        # process every pod event in it.
        self._informer = Informer(
            kube,
            gvr.PODS,
            namespace=namespace,
            field_selector=f"metadata.name={pod_name}",
        )
        self._informer.add_handler(self._on_event)
        self._last_ready: Optional[bool] = None
        self._seen = threading.Event()

    def start(self, stop: threading.Event) -> None:
        self._informer.start(stop)

    @property
    def seen_pod(self) -> bool:
        """Whether the watch has ever surfaced our pod.  Until it does (e.g.
        the pod object is not visible yet), the caller keeps the socket-poll
        fallback fast; after that, events drive readiness."""
        return self._seen.is_set()

    def _on_event(self, etype: str, obj: dict) -> None:
        if obj.get("metadata", {}).get("name") != self._pod_name:
            return
        self._seen.set()
        if etype == "DELETED":
            return
        ready = pod_is_ready(obj)
        if ready == self._last_ready:
            return
        self._last_ready = ready
        logger.info("own pod %s readiness -> %s", self._pod_name, ready)
        try:
            self._on_ready_change(ready)
        except Exception:  # noqa: BLE001 — a failed status write must not kill the watch
            logger.exception("pod readiness callback failed")

"""Coordinator proxy: the daemon-side half of the DCN rendezvous.

Channel grants point every worker at the index-0 daemon's stable DNS name
(``compute-domain-daemon-0000:7175``, cdplugin/state.py) — but
``jax.distributed``'s coordinator service is *bound by the host-0 workload
process inside its own pod*, on a different IP.  The daemon bridges that
gap: the host-0 workload registers its actual ``ip:port`` in the per-domain
host directory (the same dir the plugin mounts into both the daemon and the
workload pods), and this proxy accepts connections on the coordinator port
and splices them through to the registered endpoint.

The reference has no analog — its IMEX daemons gossip peer IPs themselves
(dnsnames.go) and NCCL carries its own bootstrap — but the *shape* is its
DNS-stability trick (main.go:368-415): peers dial a stable name; the thing
behind the name forwards to wherever the live endpoint currently is.

Connections arriving before the workload has registered are closed
immediately; ``jax.distributed.initialize`` retries its coordinator
connection for ``initialization_timeout`` (default 300 s), so early workers
simply spin until host 0 comes up.

Staleness window: nothing unregisters on workload death — between a host-0
pod dying and its replacement re-registering (every host-0 start
overwrites the file), the proxy forwards to the dead address and peers see
refused connections, which jax retries.  If the dead IP were recycled by
an unrelated listener, the spliced peers still fail at the jax coordinator
handshake (process count/id checks) rather than silently joining a wrong
domain.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
from typing import Optional

logger = logging.getLogger(__name__)

REGISTRATION_FILE = "coordinator"


def read_registration(dir_path: str) -> Optional[tuple[str, int]]:
    """Read the workload-written ``ip:port`` registration, or None."""
    try:
        with open(os.path.join(dir_path, REGISTRATION_FILE)) as f:
            text = f.read().strip()
    except OSError:
        return None
    host, _, port = text.rpartition(":")
    if not host or not port.isdigit():
        return None
    return host, int(port)


def write_registration(dir_path: str, host: str, port: int) -> str:
    """Atomically publish the live coordinator endpoint (workload side).

    The temp name is unique per writer: the domain dir is sticky-bit
    shared (cdplugin/state.py), so a crashed previous workload's leftover
    ``.tmp`` owned by another uid must not block this one's open."""
    path = os.path.join(dir_path, REGISTRATION_FILE)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(f"{host}:{port}\n")
    os.replace(tmp, path)
    return path


class CoordinatorProxy:
    """TCP proxy from the daemon's coordinator port to the registered
    workload endpoint.  One thread per direction per connection — the
    coordinator carries a handful of small rendezvous/heartbeat streams,
    not bulk traffic (collectives ride ICI, not this socket)."""

    def __init__(self, port: int, registration_dir: str, host: str = ""):
        self.port = port
        self._dir = registration_dir
        self._host = host  # "" = all interfaces
        self._server: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @property
    def bound_port(self) -> int:
        """The actual listen port (useful when constructed with port 0)."""
        return self._server.getsockname()[1] if self._server else self.port

    def start(self) -> None:
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self._host, self.port))
        self._server.listen(16)
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="coord-proxy"
        )
        self._thread.start()
        logger.info(
            "coordinator proxy on :%d -> %s/%s",
            self.bound_port, self._dir, REGISTRATION_FILE,
        )

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    # ------------------------------------------------------------- internals

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._server.accept()
            except OSError as e:
                if self._stop.is_set() or self._server.fileno() < 0:
                    return  # stop() closed us
                # Transient accept failure (EMFILE under an fd squeeze,
                # ECONNABORTED): the proxy must survive it — a silently
                # dead accept thread strands every later worker in
                # jax.distributed's 300 s connect timeout.
                logger.warning("coordinator proxy accept failed: %s", e)
                if self._stop.wait(0.1):
                    return
                continue
            target = read_registration(self._dir)
            if target is None:
                # No workload registered yet: refuse; jax.distributed's
                # client retries until initialization_timeout.
                conn.close()
                continue
            threading.Thread(
                target=self._splice, args=(conn, target, addr),
                daemon=True, name="coord-proxy-conn",
            ).start()

    def _splice(self, conn: socket.socket, target: tuple[str, int], addr) -> None:
        try:
            upstream = socket.create_connection(target, timeout=10)
        except OSError as e:
            logger.warning("coordinator %s:%d unreachable: %s", *target, e)
            conn.close()
            return

        def pump(src: socket.socket, dst: socket.socket) -> None:
            # On src EOF propagate only a write-shutdown to dst: a legal
            # TCP half-close (client sends, then SHUT_WR, then reads the
            # reply) must not tear down the opposite direction.
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        t = threading.Thread(target=pump, args=(upstream, conn), daemon=True)
        t.start()
        pump(conn, upstream)
        t.join()
        for s in (conn, upstream):
            try:
                s.close()
            except OSError:
                pass

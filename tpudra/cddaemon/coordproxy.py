"""Coordinator proxy: the daemon-side half of the DCN rendezvous.

Channel grants point every worker at the index-0 daemon's stable DNS name
(``compute-domain-daemon-0000:7175``, cdplugin/state.py) — but
``jax.distributed``'s coordinator service is *bound by the host-0 workload
process inside its own pod*, on a different IP.  The daemon bridges that
gap: the host-0 workload registers its actual ``ip:port`` in the per-domain
host directory (the same dir the plugin mounts into both the daemon and the
workload pods), and this proxy accepts connections on the coordinator port
and splices them through to the registered endpoint.

The reference has no analog — its IMEX daemons gossip peer IPs themselves
(dnsnames.go) and NCCL carries its own bootstrap — but the *shape* is its
DNS-stability trick (main.go:368-415): peers dial a stable name; the thing
behind the name forwards to wherever the live endpoint currently is.

Connections arriving before the workload has registered are closed
immediately; ``jax.distributed.initialize`` retries its coordinator
connection for ``initialization_timeout`` (default 300 s), so early workers
simply spin until host 0 comes up.

Staleness recovery (probe-and-drop): nothing unregisters on workload death,
so after a host-0 pod dies the proxy would forward to a dead address until
a replacement re-registers.  The proxy counts consecutive failed upstream
connects to the *same* registered endpoint and, once ``drop_after``
failures (default 3) have accumulated over at least ``min_fail_window``
seconds, unlinks the registration: peers then get the fast
not-yet-registered close instead of connect timeouts, and — the domain dir
being sticky-bit shared (cdplugin/state.py) — a replacement workload
running under a *different* uid, which could not have replaced the dead
owner's file, can now register.  The daemon runs as root in its pod, so
the unlink bypasses the sticky bit.

Guard rails against dropping a LIVE coordinator (registrations are written
once per workload, just before ``jax.distributed.initialize`` binds the
listener, and never rewritten — a false drop is fatal to the job):

- a registration younger than ``registration_grace`` seconds is never
  dropped (host 0's bind follows its registration within the same process;
  refusals in that window are startup, not death).  Age is measured by how
  long THIS daemon has continuously observed the same file identity
  (inode + mtime_ns) on the MONOTONIC clock (tpudra/clock.py
  ``MonotonicAger``), never by ``wall_now - mtime``: a wall-clock step
  (NTP correction, VM migration — the chaos soak's ``clock_skew`` fault)
  would otherwise make a just-written registration look aged-out
  (premature drop, fatal to the job) or a long-dead one look eternally
  young (drop deferred past the replacement's ``replace_wait_s``);
- the failure streak must *span* ``min_fail_window`` seconds, so N
  simultaneous in-flight connects failing on one network blip don't count
  as N probes;
- the drop itself renames the file aside and inspects it (atomic with
  respect to a replacement's ``os.replace``): only the probed endpoint's
  own file is removed, a fresh registration landing mid-drop is restored.

If a dead IP were recycled by an unrelated listener, the spliced peers
still fail at the jax coordinator handshake (process count/id checks)
rather than silently joining a wrong domain.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Optional

from tpudra.clock import Clock, MonotonicAger, SYSTEM

logger = logging.getLogger(__name__)

REGISTRATION_FILE = "coordinator"


def read_registration(dir_path: str) -> Optional[tuple[str, int]]:
    """Read the workload-written ``ip:port`` registration, or None."""
    try:
        with open(os.path.join(dir_path, REGISTRATION_FILE)) as f:
            text = f.read().strip()
    except OSError:
        return None
    host, _, port = text.rpartition(":")
    if not host or not port.isdigit():
        return None
    return host, int(port)


def write_registration(
    dir_path: str,
    host: str,
    port: int,
    replace_wait_s: float = 180.0,
    poll_s: float = 2.0,
) -> str:
    """Atomically publish the live coordinator endpoint (workload side).

    The temp name is unique per writer: the domain dir is sticky-bit
    shared (cdplugin/state.py), so a crashed previous workload's leftover
    ``.tmp`` owned by another uid must not block this one's open.

    The sticky bit also means a REPLACEMENT host-0 running under a
    different uid cannot os.replace the dead previous owner's registration
    (EPERM).  The daemon's proxy probe-and-drops that stale file on
    forward failures (CoordinatorProxy drop_after / unreachable_window —
    ≤ ~120 s even for timeout-class deaths), after which the replace
    succeeds — so wait that window out here instead of failing fatally,
    which would CrashLoopBackOff the pod and stack restart backoff on top
    of the drop latency (ADVICE r4)."""
    path = os.path.join(dir_path, REGISTRATION_FILE)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(f"{host}:{port}\n")
    try:
        os.replace(tmp, path)
        return path
    except PermissionError as e:
        logger.warning(
            "cannot replace existing registration %s (%s) — a dead "
            "previous owner's file under the sticky bit; waiting up to "
            "%.0fs for the daemon proxy to probe-and-drop it",
            path, e, replace_wait_s,
        )
    deadline = time.monotonic() + replace_wait_s
    while True:
        time.sleep(poll_s)
        try:
            os.replace(tmp, path)
            logger.info("registered coordinator after stale-file drop: %s", path)
            return path
        except PermissionError as e:
            if time.monotonic() >= deadline:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise PermissionError(
                    f"registration {path} still owned by a previous workload "
                    f"after {replace_wait_s:.0f}s — the daemon proxy never "
                    "dropped it (is the daemon running and probing?)"
                ) from e


class CoordinatorProxy:
    """TCP proxy from the daemon's coordinator port to the registered
    workload endpoint.  One thread per direction per connection — the
    coordinator carries a handful of small rendezvous/heartbeat streams,
    not bulk traffic (collectives ride ICI, not this socket)."""

    def __init__(
        self,
        port: int,
        registration_dir: str,
        host: str = "",
        max_connections: int = 64,
        drop_after: int = 3,
        min_fail_window: float = 5.0,
        registration_grace: float = 10.0,
        unreachable_window: float = 120.0,
        clock: Optional[Clock] = None,
    ):
        self.port = port
        self._dir = registration_dir
        self._host = host  # "" = all interfaces
        self._server: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Bound concurrent splices: this proxy coexists with the node-
        # critical slice-watch loop, so a connection flood (or a stuck
        # upstream holding the 10 s connect timeout) must not exhaust
        # threads/fds.  Excess connections are dropped early — jax clients
        # retry refused connections anyway.
        self._conn_slots = threading.BoundedSemaphore(max_connections)
        # Probe-and-drop state: consecutive connect failures to the same
        # registered endpoint (see module docstring).
        self._drop_after = drop_after
        self._min_fail_window = min_fail_window
        self._registration_grace = registration_grace
        self._unreachable_window = unreachable_window
        self._fail_lock = threading.Lock()
        self._fail_target: Optional[tuple[str, int]] = None
        self._fail_count = 0  # all consecutive failures
        self._fail_refused = 0  # the refused-class subset
        self._fail_first_ts = 0.0
        self._clock = clock if clock is not None else SYSTEM
        # Registration age = continuous monotonic observation of one file
        # identity (module docstring "Guard rails"); fed on every connect
        # failure so the age accrues across the failure streak and the
        # grace check at drop time sees the streak's whole span.
        self._reg_ager = MonotonicAger(self._clock)

    @property
    def bound_port(self) -> int:
        """The actual listen port (useful when constructed with port 0)."""
        return self._server.getsockname()[1] if self._server else self.port

    def start(self) -> None:
        # tpudra-race: handoff restart choreography: the soak's proxy bounce calls stop() first, which shuts the socket down and joins the accept thread before start() runs again — the writes are ordered by that join, which spans two methods the model cannot connect
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self._host, self.port))
        self._server.listen(16)
        # tpudra-race: handoff restart choreography: same stop()-joins-before-start() ordering as _server above
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="coord-proxy"
        )
        self._thread.start()
        logger.info(
            "coordinator proxy on :%d -> %s/%s",
            self.bound_port, self._dir, REGISTRATION_FILE,
        )

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            # shutdown() BEFORE close(): on Linux, closing a listening
            # socket from another thread does not wake a thread blocked in
            # accept() — the old close-only stop left the accept thread
            # parked until the next connection and this join timing out
            # (a silent ~5 s stall on every daemon shutdown, surfaced by
            # the chaos soak's daemon_crash proxy bounce).  shutdown()
            # does wake it, with an OSError the loop maps to clean exit.
            try:
                self._server.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # never connected / already closed: nothing parked
            try:
                self._server.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                logger.warning("coordinator proxy accept thread did not exit")

    # ------------------------------------------------------------- internals

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._server.accept()
            except OSError as e:
                if self._stop.is_set() or self._server.fileno() < 0:
                    return  # stop() closed us
                # Transient accept failure (EMFILE under an fd squeeze,
                # ECONNABORTED): the proxy must survive it — a silently
                # dead accept thread strands every later worker in
                # jax.distributed's 300 s connect timeout.
                logger.warning("coordinator proxy accept failed: %s", e)
                if self._stop.wait(0.1):
                    return
                continue
            target = read_registration(self._dir)
            if target is None:
                # No workload registered yet: refuse; jax.distributed's
                # client retries until initialization_timeout.
                conn.close()
                continue
            if not self._conn_slots.acquire(blocking=False):
                logger.warning(
                    "coordinator proxy at max concurrent connections; "
                    "dropping %s", addr,
                )
                conn.close()
                continue
            try:
                threading.Thread(
                    target=self._splice, args=(conn, target, addr),
                    daemon=True, name="coord-proxy-conn",
                ).start()
            except Exception as e:  # noqa: BLE001 — thread exhaustion
                # Thread.start raises RuntimeError (not OSError) under
                # process-wide thread exhaustion; the accept loop must
                # survive it (its own comment above) and the slot/socket
                # must not leak.
                self._conn_slots.release()
                conn.close()
                logger.warning("coordinator proxy could not spawn splice: %s", e)

    def _splice(self, conn: socket.socket, target: tuple[str, int], addr) -> None:
        try:
            self._splice_inner(conn, target)
        finally:
            self._conn_slots.release()

    def _splice_inner(self, conn: socket.socket, target: tuple[str, int]) -> None:
        try:
            upstream = socket.create_connection(target, timeout=10)
        except OSError as e:
            logger.warning("coordinator %s:%d unreachable: %s", *target, e)
            conn.close()
            # RST-class errors are strong evidence the ENDPOINT is dead
            # (a host answered and said nobody listens); timeouts and
            # unreachables are ambiguous — they look identical during a
            # transient network partition between the daemon and a LIVE
            # workload, and a false drop is unrecoverable (registrations
            # are write-once).  The ambiguous class needs a much longer
            # streak before it may drop.
            refused = isinstance(
                e, (ConnectionRefusedError, ConnectionResetError)
            )
            self._note_connect_failure(target, refused=refused)
            return
        self._note_connect_success(target)

        def pump(src: socket.socket, dst: socket.socket) -> None:
            # On src EOF propagate only a write-shutdown to dst: a legal
            # TCP half-close (client sends, then SHUT_WR, then reads the
            # reply) must not tear down the opposite direction.
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        t = threading.Thread(target=pump, args=(upstream, conn), daemon=True)
        t.start()
        pump(conn, upstream)
        t.join()
        for s in (conn, upstream):
            try:
                s.close()
            except OSError:
                pass

    # --------------------------------------------------- probe-and-drop

    def _note_connect_success(self, target: tuple[str, int]) -> None:
        with self._fail_lock:
            if self._fail_target == target:
                self._fail_target = None
                self._fail_count = 0
                self._fail_refused = 0

    def _note_connect_failure(
        self, target: tuple[str, int], refused: bool = True
    ) -> None:
        """Count consecutive failures per endpoint; past the threshold AND
        the class's window, drop the registration (module docstring: this
        is what lets a replacement workload under a different uid take
        over, and turns peers' connect timeouts into fast retries).

        Refused-class failures (RST: something answered, nobody listens)
        may drop after ``min_fail_window``; a streak with no refusal at
        all — timeouts/unreachables, which a transient daemon↔workload
        partition produces against a perfectly live coordinator — must
        span ``unreachable_window`` first.  A partition that heals resets
        the streak on the next successful forward, so only an endpoint
        that stays dark for the whole long window is dropped."""
        # Observe the registration file on every failure so its monotonic
        # age accrues across the streak: by the time the streak spans the
        # drop window, the observation spans it too, and the grace check
        # in _drop_registration compares real watched time (stat happens
        # out here — no IO under the in-process fail lock).
        self._registration_age(os.path.join(self._dir, REGISTRATION_FILE))
        now = self._clock.monotonic()
        with self._fail_lock:
            if self._fail_target != target:
                self._fail_target = target
                self._fail_count = 0
                self._fail_refused = 0
                self._fail_first_ts = now
            self._fail_count += 1
            if refused:
                self._fail_refused += 1
            if self._fail_count < self._drop_after:
                return
            span = now - self._fail_first_ts
            window = (
                self._min_fail_window
                if self._fail_refused
                else self._unreachable_window
            )
            if span < window:
                # N simultaneous in-flight connects failing on one blip
                # are one observation, not N probes of a dead endpoint.
                return
            self._fail_target = None
            self._fail_count = 0
            self._fail_refused = 0
        self._drop_registration(target)

    def _registration_age(self, path: str) -> Optional[float]:
        """How long this daemon has continuously observed the registration
        at ``path`` with an unchanged (inode, mtime_ns) identity, on the
        monotonic clock — None when the file is absent.  A rewrite or
        replacement changes the identity and restarts the age at 0; a
        wall-clock step changes nothing (the skew-immunity the module
        docstring's grace guard rail promises).

        An absent file does NOT forget the observation: the canonical
        path is legitimately missing for the instant a concurrent
        ``_drop_registration`` holds it renamed aside, and a forget here
        would reset the aged observation mid-drop — deferring the drop of
        a genuinely dead registration by a fresh grace every burst.  A
        *replacement* file re-ages naturally through its new
        (inode, mtime_ns) identity."""
        try:
            st = os.stat(path)
        except OSError:
            return None
        return self._reg_ager.age("registration", (st.st_ino, st.st_mtime_ns))

    def _drop_registration(self, target: tuple[str, int]) -> None:
        """Remove the registration iff it is the probed endpoint's own,
        aged-out file.  Rename-aside first: a replacement's ``os.replace``
        landing mid-drop creates a fresh file at the canonical path that
        this never touches — no unlink-the-new-registration race."""
        path = os.path.join(self._dir, REGISTRATION_FILE)
        age = self._registration_age(path)
        if age is None:
            return  # already gone
        if age < self._registration_grace:
            return  # young (or not-yet-watched) registration: never drop
        probe = f"{path}.probe.{os.getpid()}"
        try:
            os.rename(path, probe)
        except OSError:
            return  # raced with another drop or a fresh replace
        try:
            st = os.stat(probe)
            with open(probe) as f:
                content = f.read().strip()
            # rename(2) preserves inode and mtime, so the identity key is
            # the same observation the ager has been aging all along — a
            # fresh file swapped in between the age check and the rename
            # has a new identity and ages out at 0 here (restored below).
            stale = (
                content == f"{target[0]}:{target[1]}"
                and self._reg_ager.age("registration", (st.st_ino, st.st_mtime_ns))
                >= self._registration_grace
            )
        except OSError:
            stale = False
        if stale:
            try:
                os.unlink(probe)
            except OSError:
                pass
            self._reg_ager.forget("registration")
            logger.info(
                "dropped stale coordinator registration %s:%d after %d "
                "consecutive failed connects", target[0], target[1],
                self._drop_after,
            )
            return
        # Not the file we probed (or unreadable): put it back — unless an
        # even newer registration has already taken the canonical path.
        try:
            os.link(probe, path)  # fails if path exists: never clobbers
        except FileExistsError:
            pass  # newer registration won; discard the probe copy below
        except OSError:
            # No hard-link support (NFS root_squash, FUSE volumes): restore
            # by rename.  This can clobber a registration that landed in
            # the microseconds since — but keeping SOME live registration
            # beats silently deleting the only copy.
            try:
                os.replace(probe, path)
            except OSError:
                logger.warning(
                    "could not restore coordinator registration %s", probe
                )
            return
        try:
            os.unlink(probe)
        except OSError:
            pass

"""Child-process supervision for the native slice daemon.

The analog of compute-domain-daemon/process.go:33-223: start/stop/signal a
child process (``tpu-slicewatchd``; nvidia-imex in the reference) plus a
watchdog that restarts it on unexpected death.  Stop is graceful (SIGTERM,
then SIGKILL after a grace period).

Restart pacing is the shared full-jitter policy (tpudra/backoff.py): a
crash-looping daemon (bad config, broken binary) must not be respawned in
a tight loop — and at fleet scale N nodes' daemons dying on one shared
cause (a pushed bad config) must not march back in lockstep.  The window
collapses after the child proves stable (``STABLE_UPTIME`` seconds of
continuous run), so an isolated crash after weeks of uptime restarts
near-instantly.  Every watchdog restart counts in
``tpudra_daemon_restarts_total{daemon}``.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import subprocess
import threading
import time
from typing import Optional, Sequence

from tpudra import lockwitness, metrics
from tpudra.backoff import Backoff

logger = logging.getLogger(__name__)


class ProcessManager:
    # Window after spawn during which signals are unsafe: the child may not
    # have installed its handlers yet, and the default SIGHUP action kills it.
    SIGNAL_SAFE_AGE = 0.5

    #: A child alive this long is considered stable: the next death resets
    #: the restart backoff window instead of widening it.
    STABLE_UPTIME = 30.0

    #: Watchdog restart-delay window bounds (full jitter draws inside it).
    RESTART_BACKOFF_BASE = 0.5
    RESTART_BACKOFF_CAP = 30.0

    def __init__(
        self,
        argv: Sequence[str],
        term_grace: float = 5.0,
        restart_rng: Optional[random.Random] = None,
    ):
        self._argv = list(argv)
        self._term_grace = term_grace
        self._proc: Optional[subprocess.Popen] = None
        self._lock = lockwitness.make_rlock("process.lock")
        self._expected_stop = False
        self._started_at = 0.0
        self.restarts = 0
        #: Full-jitter restart pacing; the rng is injectable so tests (and
        #: the chaos soak) replay deterministic delay schedules.
        self._restart_backoff = Backoff(
            self.RESTART_BACKOFF_BASE, self.RESTART_BACKOFF_CAP, rng=restart_rng
        )
        self._restarts_metric = metrics.DAEMON_RESTARTS_TOTAL.labels(
            os.path.basename(self._argv[0]) if self._argv else "unknown"
        )

    # -- lifecycle ----------------------------------------------------------

    def ensure_started(self) -> bool:
        """Returns True if this call actually spawned the process."""
        with self._lock:
            if self.running:
                return False
            self._expected_stop = False
            # The spawn must be atomic with the _proc publication: with the
            # fork outside the lock, a watchdog tick between spawn and
            # publish sees "not running" and double-spawns the daemon.
            # Popen here is fork+exec only (no wait), bounded at ms.
            self._proc = subprocess.Popen(self._argv)  # tpudra-lint: disable=BLOCK-UNDER-LOCK spawn and _proc publish must be one atomic step vs the watchdog; no wait happens under the lock
            self._started_at = time.monotonic()
            logger.info("started %s (pid %d)", self._argv[0], self._proc.pid)
            return True

    def stop(self) -> None:
        with self._lock:
            self._expected_stop = True
            proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=self._term_grace)
        except subprocess.TimeoutExpired:
            logger.warning("%s ignored SIGTERM; killing", self._argv[0])
            proc.kill()
            proc.wait()

    def restart(self) -> None:
        self.stop()
        self.ensure_started()

    def reload(self) -> None:
        """Ask the daemon to re-resolve peers without restarting (the
        SIGUSR1-to-nvidia-imex analog, reference main.go:405).

        If the process was spawned moments ago — by us or by the watchdog —
        wait out the handler-install window first: a SIGHUP landing before
        the child's handler is installed would kill it.  The age check and
        the signal happen under one lock acquisition so a watchdog respawn
        cannot slip between them; a non-running process is simply not
        signaled (any fresh spawn reads the fresh config at startup)."""
        while True:
            with self._lock:
                if not self.running:
                    return
                age = time.monotonic() - self._started_at
                if age >= self.SIGNAL_SAFE_AGE:
                    self._proc.send_signal(signal.SIGHUP)
                    return
            time.sleep(self.SIGNAL_SAFE_AGE - age)

    def send_signal(self, sig: int) -> None:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                self._proc.send_signal(sig)

    @property
    def running(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self.running else None

    # -- watchdog -----------------------------------------------------------

    def watchdog(self, stop: threading.Event, tick: float = 1.0) -> None:
        """Restart the child if it died unexpectedly (process.go:170-202),
        paced by the shared full-jitter backoff: each unexpected death
        widens the delay window; a child that stayed up ``STABLE_UPTIME``
        before dying collapses it first.  The delay rides ``stop.wait`` so
        shutdown is never held hostage by a backed-off respawn."""
        while not stop.is_set():
            with self._lock:
                died = (
                    self._proc is not None
                    and self._proc.poll() is not None
                    and not self._expected_stop
                )
                uptime = time.monotonic() - self._started_at
            if died:
                if uptime >= self.STABLE_UPTIME:
                    self._restart_backoff.reset()
                delay = self._restart_backoff.next_delay()
                logger.error(
                    "%s exited unexpectedly (rc=%s); restarting in %.2fs "
                    "(attempt %d)",
                    self._argv[0], self._proc.returncode, delay,
                    self._restart_backoff.attempt,
                )
                if stop.wait(delay):
                    return
                with self._lock:
                    # Re-check under the lock after the backoff wait: a
                    # stop() landing inside the (up to 30 s) window set
                    # _expected_stop, and respawning past it would
                    # resurrect a deliberately-stopped daemon — a race the
                    # pre-backoff microsecond window never really exposed.
                    if self._expected_stop:
                        continue
                self.restarts += 1
                self._restarts_metric.inc()
                try:
                    self.ensure_started()
                except Exception:  # noqa: BLE001 — supervision must outlive spawn failures
                    # A failed spawn (binary mid-upgrade, transient EMFILE)
                    # must not kill the watchdog thread: the child is still
                    # dead, so the next tick re-enters the died branch and
                    # retries with a wider backoff window.
                    logger.exception(
                        "respawn of %s failed; retrying on the backoff",
                        self._argv[0],
                    )
            stop.wait(tick)

    def start_watchdog(self, stop: threading.Event, tick: float = 1.0) -> threading.Thread:
        t = threading.Thread(
            target=self.watchdog, args=(stop, tick), daemon=True, name="slice-daemon-watchdog"
        )
        t.start()
        return t

    def wait(self, timeout: float | None = None) -> Optional[int]:
        with self._lock:
            proc = self._proc
        if proc is None:
            return None
        try:
            return proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

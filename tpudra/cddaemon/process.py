"""Child-process supervision for the native slice daemon.

The analog of compute-domain-daemon/process.go:33-223: start/stop/signal a
child process (``tpu-slicewatchd``; nvidia-imex in the reference) plus a
watchdog that restarts it on unexpected death.  Stop is graceful (SIGTERM,
then SIGKILL after a grace period).
"""

from __future__ import annotations

import logging
import signal
import subprocess
import threading
import time
from typing import Optional, Sequence

from tpudra import lockwitness

logger = logging.getLogger(__name__)


class ProcessManager:
    # Window after spawn during which signals are unsafe: the child may not
    # have installed its handlers yet, and the default SIGHUP action kills it.
    SIGNAL_SAFE_AGE = 0.5

    def __init__(self, argv: Sequence[str], term_grace: float = 5.0):
        self._argv = list(argv)
        self._term_grace = term_grace
        self._proc: Optional[subprocess.Popen] = None
        self._lock = lockwitness.make_rlock("process.lock")
        self._expected_stop = False
        self._started_at = 0.0
        self.restarts = 0

    # -- lifecycle ----------------------------------------------------------

    def ensure_started(self) -> bool:
        """Returns True if this call actually spawned the process."""
        with self._lock:
            if self.running:
                return False
            self._expected_stop = False
            # The spawn must be atomic with the _proc publication: with the
            # fork outside the lock, a watchdog tick between spawn and
            # publish sees "not running" and double-spawns the daemon.
            # Popen here is fork+exec only (no wait), bounded at ms.
            self._proc = subprocess.Popen(self._argv)  # tpudra-lint: disable=BLOCK-UNDER-LOCK spawn and _proc publish must be one atomic step vs the watchdog; no wait happens under the lock
            self._started_at = time.monotonic()
            logger.info("started %s (pid %d)", self._argv[0], self._proc.pid)
            return True

    def stop(self) -> None:
        with self._lock:
            self._expected_stop = True
            proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=self._term_grace)
        except subprocess.TimeoutExpired:
            logger.warning("%s ignored SIGTERM; killing", self._argv[0])
            proc.kill()
            proc.wait()

    def restart(self) -> None:
        self.stop()
        self.ensure_started()

    def reload(self) -> None:
        """Ask the daemon to re-resolve peers without restarting (the
        SIGUSR1-to-nvidia-imex analog, reference main.go:405).

        If the process was spawned moments ago — by us or by the watchdog —
        wait out the handler-install window first: a SIGHUP landing before
        the child's handler is installed would kill it.  The age check and
        the signal happen under one lock acquisition so a watchdog respawn
        cannot slip between them; a non-running process is simply not
        signaled (any fresh spawn reads the fresh config at startup)."""
        while True:
            with self._lock:
                if not self.running:
                    return
                age = time.monotonic() - self._started_at
                if age >= self.SIGNAL_SAFE_AGE:
                    self._proc.send_signal(signal.SIGHUP)
                    return
            time.sleep(self.SIGNAL_SAFE_AGE - age)

    def send_signal(self, sig: int) -> None:
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                self._proc.send_signal(sig)

    @property
    def running(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self.running else None

    # -- watchdog -----------------------------------------------------------

    def watchdog(self, stop: threading.Event, tick: float = 1.0) -> None:
        """Restart the child if it died unexpectedly (process.go:170-202)."""
        while not stop.is_set():
            with self._lock:
                died = (
                    self._proc is not None
                    and self._proc.poll() is not None
                    and not self._expected_stop
                )
            if died:
                logger.error(
                    "%s exited unexpectedly (rc=%s); restarting",
                    self._argv[0], self._proc.returncode,
                )
                self.restarts += 1
                self.ensure_started()
            stop.wait(tick)

    def start_watchdog(self, stop: threading.Event, tick: float = 1.0) -> threading.Thread:
        t = threading.Thread(
            target=self.watchdog, args=(stop, tick), daemon=True, name="slice-daemon-watchdog"
        )
        t.start()
        return t

    def wait(self, timeout: float | None = None) -> Optional[int]:
        with self._lock:
            proc = self._proc
        if proc is None:
            return None
        try:
            return proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

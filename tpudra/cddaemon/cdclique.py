"""Clique membership through ComputeDomainClique CRs.

The analog of compute-domain-daemon/cdclique.go:39-500.  The k8s API server
is the rendezvous medium: each daemon upserts its DaemonInfo {nodeName, ip,
cliqueID, index} into the clique CR named ``<cdUID>.<cliqueID>``, claiming the
lowest free index (stable identity for the DNS-name scheme), watches the CR
to learn peers, and flips its own entry Ready/NotReady from local daemon
state.  Conflicts are expected (every daemon in the clique writes the same
object) and handled by re-read-and-retry.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from tpudra.api.computedomain import (
    COMPUTE_DOMAIN_STATUS_NOT_READY,
    COMPUTE_DOMAIN_STATUS_READY,
)
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import AlreadyExists, Conflict, NotFound
from tpudra.kube.informer import Informer

logger = logging.getLogger(__name__)

MAX_UPSERT_RETRIES = 20

# Callback receiving {index: ip} for the clique's current membership.
PeersCallback = Callable[[dict[int, str]], None]


def clique_name(cd_uid: str, clique_id: str) -> str:
    return f"{cd_uid}.{clique_id}"


class CliqueManager:
    def __init__(
        self,
        kube: KubeAPI,
        namespace: str,
        cd_uid: str,
        clique_id: str,
        node_name: str,
        ip_address: str,
    ):
        self._kube = kube
        self._ns = namespace
        self._cd_uid = cd_uid
        self._clique_id = clique_id
        self._node = node_name
        self._ip = ip_address
        self._informer: Optional[Informer] = None
        self._peers_cb: Optional[PeersCallback] = None
        self._last_peers: Optional[dict[int, str]] = None
        self._lock = threading.Lock()
        self.index: Optional[int] = None

    @property
    def name(self) -> str:
        return clique_name(self._cd_uid, self._clique_id)

    # -- membership ---------------------------------------------------------

    def join(self) -> int:
        """Ensure the clique CR exists and this daemon has an entry; returns
        the claimed index (syncDaemonInfoToClique + getNextAvailableIndex,
        cdclique.go:277,350)."""
        for _ in range(MAX_UPSERT_RETRIES):
            clique = self._get_or_create()
            daemons = clique.setdefault("status", {}).setdefault("daemons", [])
            mine = next((d for d in daemons if d.get("nodeName") == self._node), None)
            if mine is not None:
                if mine.get("ipAddress") == self._ip:
                    self.index = mine["index"]
                    return self.index
                mine["ipAddress"] = self._ip
            else:
                used = {d.get("index") for d in daemons}
                index = next(i for i in range(len(daemons) + 1) if i not in used)
                daemons.append(
                    {
                        "nodeName": self._node,
                        "ipAddress": self._ip,
                        "cliqueID": self._clique_id,
                        "index": index,
                        "status": COMPUTE_DOMAIN_STATUS_NOT_READY,
                    }
                )
            try:
                updated = self._kube.update_status(
                    gvr.COMPUTE_DOMAIN_CLIQUES, clique, self._ns
                )
            except Conflict:
                continue
            mine = next(
                d for d in updated["status"]["daemons"] if d["nodeName"] == self._node
            )
            self.index = mine["index"]
            logger.info("joined clique %s as index %d", self.name, self.index)
            return self.index
        raise RuntimeError(f"could not join clique {self.name}: persistent conflicts")

    def _get_or_create(self) -> dict:
        try:
            return self._kube.get(gvr.COMPUTE_DOMAIN_CLIQUES, self.name, self._ns)
        except NotFound:
            pass
        obj = {
            "apiVersion": gvr.COMPUTE_DOMAIN_CLIQUES.api_version,
            "kind": gvr.COMPUTE_DOMAIN_CLIQUES.kind,
            "metadata": {"name": self.name, "namespace": self._ns},
            "spec": {"computeDomainUID": self._cd_uid, "cliqueID": self._clique_id},
            "status": {"daemons": []},
        }
        try:
            return self._kube.create(gvr.COMPUTE_DOMAIN_CLIQUES, obj, self._ns)
        except AlreadyExists:
            return self._kube.get(gvr.COMPUTE_DOMAIN_CLIQUES, self.name, self._ns)

    def update_daemon_status(self, ready: bool) -> bool:
        """Flip this daemon's entry (updateDaemonStatus, cdclique.go:429).
        Returns True when the target state is in place (or there is nothing
        to write), False when the write could not land — callers keep the
        transition pending and retry."""
        target = COMPUTE_DOMAIN_STATUS_READY if ready else COMPUTE_DOMAIN_STATUS_NOT_READY
        for _ in range(MAX_UPSERT_RETRIES):
            try:
                clique = self._kube.get(gvr.COMPUTE_DOMAIN_CLIQUES, self.name, self._ns)
            except NotFound:
                return True
            mine = next(
                (
                    d
                    for d in clique.get("status", {}).get("daemons", [])
                    if d.get("nodeName") == self._node
                ),
                None,
            )
            if mine is None or mine.get("status") == target:
                return True
            mine["status"] = target
            try:
                self._kube.update_status(gvr.COMPUTE_DOMAIN_CLIQUES, clique, self._ns)
                return True
            except Conflict:
                continue
        logger.warning("could not update daemon status in clique %s", self.name)
        return False

    def leave(self) -> None:
        """Remove this daemon's entry on clean shutdown."""
        for _ in range(MAX_UPSERT_RETRIES):
            try:
                clique = self._kube.get(gvr.COMPUTE_DOMAIN_CLIQUES, self.name, self._ns)
            except NotFound:
                return
            daemons = clique.get("status", {}).get("daemons", [])
            remaining = [d for d in daemons if d.get("nodeName") != self._node]
            if len(remaining) == len(daemons):
                return
            clique["status"]["daemons"] = remaining
            try:
                self._kube.update_status(gvr.COMPUTE_DOMAIN_CLIQUES, clique, self._ns)
                return
            except Conflict:
                continue

    # -- peer watching ------------------------------------------------------

    def watch_peers(self, callback: PeersCallback, stop: threading.Event) -> None:
        """Invoke callback with {index: ip} whenever membership changes
        (maybePushDaemonsUpdate, cdclique.go:408)."""
        self._peers_cb = callback
        self._informer = Informer(self._kube, gvr.COMPUTE_DOMAIN_CLIQUES, namespace=self._ns)
        self._informer.add_handler(self._on_event)
        self._informer.start(stop)
        self._informer.wait_for_sync()

    def _on_event(self, etype: str, obj: dict) -> None:
        if obj.get("metadata", {}).get("name") != self.name:
            return
        if etype == "DELETED":
            peers: dict[int, str] = {}
        else:
            peers = {
                d["index"]: d.get("ipAddress", "")
                for d in obj.get("status", {}).get("daemons", [])
                if d.get("ipAddress")
            }
        with self._lock:
            if peers == self._last_peers:
                return
            self._last_peers = peers
        if self._peers_cb is not None:
            self._peers_cb(dict(peers))

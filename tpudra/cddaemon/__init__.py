"""Per-node ComputeDomain daemon.

The analog of cmd/compute-domain-daemon/: runs in the DaemonSet pod the
controller stamps out per CD, on every node the CD's workloads landed on.
Responsibilities (reference main.go:206-415):

- join the CD's clique: ensure the ``ComputeDomainClique`` CR exists and
  insert this node's DaemonInfo under a stable free index (cdclique.go)
- maintain the native slice-coordination daemon (``tpu-slicewatchd``, the
  nvidia-imex analog): peer config rendering, /etc/hosts indirection so a
  membership change is a SIGHUP re-resolve instead of a restart, watchdog
  restart on unexpected death (process.go, dnsnames.go)
- readiness: the ``check`` subcommand queries the native daemon's status
  socket expecting READY (the ``nvidia-imex-ctl -q`` probe analog)
"""

"""ComputeDomain daemon binary (the cmd/compute-domain-daemon analog).

Subcommands: ``run`` (the daemon) and ``check`` (the kubelet probe expecting
READY from the native daemon's status socket)."""

from __future__ import annotations

import argparse
import logging

from tpudra.flags import (
    add_common_flags,
    install_stop_handlers,
    make_kube_client_from_args,
    setup_common,
)

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("compute-domain-daemon")
    sub = p.add_subparsers(dest="command", required=True)
    run_p = sub.add_parser("run", help="run the per-node domain daemon")
    add_common_flags(run_p)
    sub.add_parser("check", help="probe: exit 0 iff the slice daemon is READY")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from tpudra.cddaemon.app import DaemonApp, DaemonConfig, check

    if args.command == "check":
        return check()

    setup_common(args)
    stop = install_stop_handlers()
    config = DaemonConfig.from_environ()
    # Derive this node's fabric identity from the device library: the clique
    # id is what the chips report, not a deploy-time constant.
    try:
        from tpudra.flags import make_device_lib

        from tpudra.cdplugin.allocatable import resolve_clique_id

        lib = make_device_lib("native", "")
        chips = lib.enumerate_chips()
        topo = lib.slice_topology()
        if chips and not config.clique_id:
            config.clique_id = resolve_clique_id(chips)
        config.num_hosts = topo.num_hosts
        config.host_index = topo.host_index
        lib.close()
    except Exception as e:  # noqa: BLE001 — no TPU = idle daemon, still valid
        logger.warning("no local TPU fabric identity (%s); daemon will idle", e)

    kube = make_kube_client_from_args(args)
    app = DaemonApp(kube, config)
    app.run(stop)  # blocks until stop
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

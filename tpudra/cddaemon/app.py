"""ComputeDomain daemon application: run + check.

The analog of compute-domain-daemon/main.go:206-443.

``run`` labels the pod with its cliqueID, joins the clique CR, renders the
native daemon's peer config, then runs three loops until stopped:

- peer updates: clique membership change → /etc/hosts rewrite → ensure the
  native daemon is started → reload signal (main.go:368-415)
- watchdog: restart the native daemon on unexpected death
- readiness: an informer on the daemon's own pod mirrors kubelet-probe
  Ready/NotReady transitions into this daemon's clique entry on the watch
  event (podmanager.go analog); a status-socket poll bootstraps readiness
  until the watch has surfaced the pod, then kubelet's verdict is
  authoritative

``check`` is the kubelet startup/readiness/liveness probe: query the native
daemon's status socket and exit 0 iff READY (the ``nvidia-imex-ctl -q``
analog, main.go:419-443).  A node with an empty cliqueID runs no native
daemon and reports READY unconditionally (main.go:230-236).
"""

from __future__ import annotations

import logging
import os
import socket
import threading
from dataclasses import dataclass
from typing import Optional, Sequence

from tpudra import featuregates
from tpudra.cddaemon.cdclique import CliqueManager
from tpudra.cddaemon.dnsnames import DNSNameManager
from tpudra.cddaemon.podmanager import PodManager
from tpudra.cddaemon.process import ProcessManager
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI

logger = logging.getLogger(__name__)

DEFAULT_STATUS_PORT = 7173
DEFAULT_PEER_PORT = 7174


@dataclass
class DaemonConfig:
    cd_uid: str
    node_name: str
    pod_name: str
    pod_ip: str
    namespace: str = "tpudra-system"
    # CD object coordinates, used by the legacy direct-status membership
    # path (ComputeDomainCliques gate off).
    cd_namespace: str = ""
    cd_name: str = ""
    clique_id: str = ""  # empty → no ICI fabric on this node, idle daemon
    num_hosts: int = 1
    host_index: int = 0
    status_port: int = DEFAULT_STATUS_PORT
    peer_port: int = DEFAULT_PEER_PORT
    work_dir: str = "/var/run/tpudra-cd"
    hosts_path: str = "/etc/hosts"
    # DCN rendezvous proxy: listen port (the TPUDRA_COORDINATOR port peers
    # dial at this daemon's DNS name) and the per-domain host dir where the
    # host-0 workload registers its live coordinator endpoint (the same dir
    # the plugin mounts into this pod at /etc/tpudra-cd).  Port <= 0
    # disables the proxy.  NOTE the two construction paths differ on
    # purpose: direct construction (tests, embedders) is opt-in (default
    # 0), while ``from_environ`` — the production path, driven by the
    # daemon-settings env — defaults an unset COORDINATOR_PORT to
    # DEFAULT_COORDINATOR_PORT so deployed daemons always serve the proxy.
    coordinator_port: int = 0
    coordinator_dir: str = "/etc/tpudra-cd"
    daemon_argv: Optional[Sequence[str]] = None  # default: tpu-slicewatchd
    # Single-host test mode: clique index -> UDP peer port.  When set, the
    # daemon binds the port for its own index and writes the port-annotated
    # nodes.cfg form ("name:port") that tpu-slicewatchd documents for
    # same-host peers (slicewatchd.cc:101-103).  Production leaves this
    # empty: every host binds the same --peer-port.
    peer_port_map: Optional[dict[int, int]] = None

    @classmethod
    def from_environ(cls, env: Optional[dict] = None) -> "DaemonConfig":
        env = dict(os.environ if env is None else env)
        return cls(
            cd_uid=env.get("CD_UID", ""),
            node_name=env.get("NODE_NAME", ""),
            pod_name=env.get("POD_NAME", ""),
            pod_ip=env.get("POD_IP", ""),
            namespace=env.get("NAMESPACE", "tpudra-system"),
            cd_namespace=env.get("CD_NAMESPACE", ""),
            cd_name=env.get("CD_NAME", ""),
            clique_id=env.get("CLIQUE_ID", ""),
            num_hosts=int(env.get("TPUDRA_NUM_HOSTS", "1")),
            host_index=int(env.get("TPUDRA_HOST_INDEX", "0")),
            status_port=int(env.get("STATUS_PORT", str(DEFAULT_STATUS_PORT))),
            peer_port=int(env.get("PEER_PORT", str(DEFAULT_PEER_PORT))),
            work_dir=env.get("WORK_DIR", "/var/run/tpudra-cd"),
            hosts_path=env.get("HOSTS_PATH", "/etc/hosts"),
            coordinator_port=_env_port(env, "COORDINATOR_PORT"),
            coordinator_dir=env.get("COORDINATOR_DIR", _default_cd_mount()),
            peer_port_map=_parse_port_map(env.get("TPUDRA_PEER_PORT_MAP", "")),
        )


def _default_cd_mount() -> str:
    from tpudra.cdplugin.computedomain import DAEMON_CD_MOUNT

    return DAEMON_CD_MOUNT


def _env_port(env: dict, key: str) -> int:
    from tpudra.cdplugin.computedomain import DEFAULT_COORDINATOR_PORT

    raw = env.get(key, "")
    try:
        return int(raw or DEFAULT_COORDINATOR_PORT)
    except ValueError:
        # An explicitly-set-but-garbled port is an operator error; keep the
        # proxy up on the default (a disabled proxy strands every worker in
        # jax's 300 s timeout) but say so instead of silently substituting.
        logger.warning(
            "unparseable %s=%r; falling back to %d",
            key, raw, DEFAULT_COORDINATOR_PORT,
        )
        return DEFAULT_COORDINATOR_PORT


def _parse_port_map(spec: str) -> Optional[dict[int, int]]:
    """Parse "0=5001,1=5002" (TPUDRA_PEER_PORT_MAP) into {index: port}.

    Malformed entries are reported and skipped, mirroring _env_int's
    tolerant fallback — a trailing comma in a test harness's env must not
    crash the daemon before logging is even configured."""
    if not spec:
        return None
    out: dict[int, int] = {}
    for part in spec.split(","):
        idx, _, port = part.partition("=")
        if not (idx.strip().isdigit() and port.strip().isdigit()):
            if part.strip():
                logger.warning(
                    "ignoring malformed TPUDRA_PEER_PORT_MAP entry %r", part
                )
            continue
        out[int(idx)] = int(port)
    return out or None


def query_status(port: int, host: str = "127.0.0.1", timeout: float = 2.0) -> str:
    """Ask the native daemon for its state; returns e.g. "READY"."""
    try:
        with socket.create_connection((host, port), timeout=timeout) as s:
            s.sendall(b"Q\n")
            data = s.makefile().readline()
        return data.strip()
    except OSError as e:
        return f"UNREACHABLE: {e}"


class DaemonApp:
    def __init__(self, kube: KubeAPI, config: DaemonConfig):
        self._kube = kube
        self.config = config
        self.clique: Optional[CliqueManager] = None
        self.process: Optional[ProcessManager] = None
        self.pods: Optional[PodManager] = None
        self.coordproxy = None
        self._dns: Optional[DNSNameManager] = None
        self._started = threading.Event()

    # ------------------------------------------------------------------ run

    def run(self, stop: threading.Event) -> None:
        cfg = self.config
        self._label_own_pod()
        use_cliques = featuregates.enabled(featuregates.COMPUTE_DOMAIN_CLIQUES)
        if not cfg.clique_id:
            # Non-fabric node: no native daemon.  With cliques (default) the
            # controller tracks this node through the DS pod's readiness
            # (build_non_fabric_nodes); in legacy direct-status mode there is
            # no pod path, so the daemon must still write its own
            # cd.status.nodes entry (reference cdstatus.go handles both).
            logger.info("no cliqueID on this node: idling without a native daemon")
            if not use_cliques:
                self._run_non_fabric_direct_status(stop)
                return
            self._started.set()
            stop.wait()
            return

        if featuregates.enabled(featuregates.COMPUTE_DOMAIN_CLIQUES):
            self.clique = CliqueManager(
                self._kube, cfg.namespace, cfg.cd_uid, cfg.clique_id,
                cfg.node_name, cfg.pod_ip,
            )
        else:
            # Legacy direct-status membership: daemons write cd.status.nodes
            # themselves (reference cdstatus.go:55; gate off).
            from tpudra.cddaemon.cdstatus import DirectStatusManager

            if not (cfg.cd_namespace and cfg.cd_name):
                raise RuntimeError(
                    "ComputeDomainCliques gate is off but CD_NAMESPACE/CD_NAME "
                    "are not set — the direct-status path needs the CD object"
                )
            self.clique = DirectStatusManager(
                self._kube, cfg.cd_namespace, cfg.cd_name, cfg.clique_id,
                cfg.node_name, cfg.pod_ip,
            )
        index = self.clique.join()

        # DCN rendezvous proxy: peers dial TPUDRA_COORDINATOR =
        # dns_name(0):7175, which resolves to the index-0 daemon's pod IP —
        # this pod.  The host-0 workload binds jax.distributed's coordinator
        # in its *own* pod and registers the live endpoint in the shared
        # per-domain dir; the proxy splices the two.  Every daemon runs it
        # (cheap, and index assignment can change across restarts); only
        # index 0's ever receives traffic.
        self.coordproxy = None
        if cfg.coordinator_port > 0:
            from tpudra.cddaemon.coordproxy import CoordinatorProxy

            try:
                self.coordproxy = CoordinatorProxy(
                    cfg.coordinator_port, cfg.coordinator_dir
                )
                self.coordproxy.start()
            except OSError as e:
                # A daemon without the proxy still watches the slice; the
                # rendezvous just needs cluster routing to the workload.
                logger.warning("coordinator proxy failed to bind: %s", e)
                self.coordproxy = None

        os.makedirs(cfg.work_dir, exist_ok=True)
        # With the DNS-names gate (default): peers resolve through the real
        # /etc/hosts, updated in place, and membership changes are a reload.
        # Gate off: the daemon reads a private hosts-format peer file that
        # _on_peers_update rewrites before a full restart (the reference's
        # restart-with-fresh-IPs mode, main.go:335-366).
        self._use_dns = featuregates.enabled(featuregates.DOMAIN_DAEMONS_WITH_DNS_NAMES)
        hosts_for_daemon = (
            cfg.hosts_path if self._use_dns else os.path.join(cfg.work_dir, "peers-hosts")
        )
        self._dns = DNSNameManager(
            max_nodes=max(cfg.num_hosts, 1),
            hosts_path=hosts_for_daemon,
            nodes_config_path=os.path.join(cfg.work_dir, "nodes.cfg"),
        )
        nodes_cfg = self._dns.write_nodes_config(port_map=cfg.peer_port_map)
        peer_port = (
            cfg.peer_port_map.get(index, cfg.peer_port)
            if cfg.peer_port_map
            else cfg.peer_port
        )
        if not self._use_dns:
            with open(hosts_for_daemon, "w"):
                pass  # daemon must find the file before the first update

        argv = list(cfg.daemon_argv or [])
        if not argv:
            argv = [
                "tpu-slicewatchd",
                "--nodes-config", nodes_cfg,
                "--hosts", hosts_for_daemon,
                "--index", str(index),
                "--expected", str(max(cfg.num_hosts, 1)),
                "--status-port", str(cfg.status_port),
                "--peer-port", str(peer_port),
            ]
        self.process = ProcessManager(argv)
        self.process.start_watchdog(stop)

        self.clique.watch_peers(self._on_peers_update, stop)

        # Readiness: kubelet's probes (the `check` subcommand) flip the pod
        # Ready condition; the own-pod informer mirrors those transitions
        # into the clique entry on the watch event (podmanager.go analog).
        # Until the watch has surfaced our pod (or without a pod name at
        # all), a 2 s socket poll carries readiness; after that kubelet's
        # verdict is authoritative and the poll only retries writes that
        # could not land (a transient apiserver error must not strand the
        # clique entry on a stale state until the *next* transition).
        status_lock = threading.Lock()
        desired: list[Optional[bool]] = [None]
        written: list[Optional[bool]] = [None]

        def flush() -> None:
            with status_lock:
                want = desired[0]
                if want is None or want == written[0]:
                    return
                try:
                    ok = self.clique.update_daemon_status(want)
                except Exception:  # noqa: BLE001 — keep the transition pending
                    logger.exception("daemon status write failed; will retry")
                    ok = False
                if ok:
                    written[0] = want

        def on_pod_ready(ready: bool) -> None:
            with status_lock:
                desired[0] = ready
            flush()

        if cfg.pod_name:
            self.pods = PodManager(self._kube, cfg.namespace, cfg.pod_name, on_pod_ready)
            self.pods.start(stop)
        self._started.set()

        while not stop.is_set():
            if self.pods is None or not self.pods.seen_pod:
                ready = self.is_ready()  # socket I/O outside the lock
                with status_lock:
                    # Re-check under the lock: the informer may have surfaced
                    # the pod while we were blocked on the socket, and its
                    # (kubelet-authoritative) verdict must not be overwritten
                    # by a stale poll result.
                    if self.pods is None or not self.pods.seen_pod:
                        desired[0] = ready
            flush()
            stop.wait(2.0)
        if self.coordproxy is not None:
            self.coordproxy.stop()
        self.process.stop()

    def _run_non_fabric_direct_status(self, stop: threading.Event) -> None:
        """Legacy mode, non-fabric node: maintain a cd.status.nodes entry
        with empty cliqueID so the controller can count this node (there is
        no clique CR and the legacy controller branch reads only
        status.nodes)."""
        from tpudra.cddaemon.cdstatus import DirectStatusManager

        cfg = self.config
        if not (cfg.cd_namespace and cfg.cd_name):
            raise RuntimeError(
                "ComputeDomainCliques gate is off but CD_NAMESPACE/CD_NAME "
                "are not set — the direct-status path needs the CD object"
            )
        self.clique = DirectStatusManager(
            self._kube, cfg.cd_namespace, cfg.cd_name, "", cfg.node_name, cfg.pod_ip
        )
        self.clique.join()
        self._started.set()
        last_ready: Optional[bool] = None
        while not stop.is_set():
            ready = self.is_ready()  # no clique → unconditionally True
            if ready != last_ready:
                try:
                    if self.clique.update_daemon_status(ready):
                        last_ready = ready
                except Exception:  # noqa: BLE001 — transient API error: retry next tick
                    logger.exception("direct status write failed; will retry")
            stop.wait(2.0)

    def wait_started(self, timeout: float = 30.0) -> bool:
        return self._started.wait(timeout)

    def _on_peers_update(self, peers: dict[int, str]) -> None:
        """Membership changed (main.go:368-415): with DNS names, rewrite
        /etc/hosts and send a reload; otherwise rewrite the private peer
        file and restart with fresh IPs."""
        if self.process is None:
            return
        changed = self._dns.update_hosts_file(peers)
        if self._use_dns:
            started = self.process.ensure_started()
            if changed and not started:
                # A just-spawned daemon reads the fresh hosts file itself;
                # reload() holds its own handler-install-window guard.
                self.process.reload()
        else:
            self.process.restart()
        logger.info("applied peer update: %d peers", len(peers))

    def _label_own_pod(self) -> None:
        """Label the pod with its cliqueID for debuggability
        (main.go:222)."""
        if not self.config.pod_name:
            return
        try:
            self._kube.patch(
                gvr.PODS,
                self.config.pod_name,
                {"metadata": {"labels": {"tpudra/cliqueID": self.config.clique_id or "none"}}},
                self.config.namespace,
            )
        except Exception as e:  # noqa: BLE001 — cosmetic label only
            logger.warning("could not label own pod: %s", e)

    # ---------------------------------------------------------------- check

    def is_ready(self) -> bool:
        if not self.config.clique_id:
            return True
        return query_status(self.config.status_port) == "READY"


def check(config: Optional[DaemonConfig] = None) -> int:
    """Probe entry point: 0 iff READY (main.go:419-443)."""
    cfg = config or DaemonConfig.from_environ()
    if not cfg.clique_id:
        print("READY (no clique)")
        return 0
    state = query_status(cfg.status_port)
    print(state)
    return 0 if state == "READY" else 1

"""Stable daemon identity via DNS names.

The analog of compute-domain-daemon/dnsnames.go:44-216.  The native slice
daemon wants a *static* peer list at startup; clique membership is dynamic.
The trick (reference IMEXDaemonsWithDNSNames, default on): the peer config
names ``compute-domain-daemon-0000 … -NNNN`` — the maximum domain size — and
``/etc/hosts`` maps the currently-known names to IPs.  A membership change is
then an /etc/hosts rewrite plus a reload signal instead of a daemon restart.

TPU twist: a slice's host set is fixed at slice creation, so the index space
is exactly ``num_hosts`` rather than an arbitrary ceiling — the clique index
*is* the host's position in the slice.
"""

from __future__ import annotations

import logging
import os

from tpudra import storage

logger = logging.getLogger(__name__)

DNS_NAME_FORMAT = "compute-domain-daemon-%04d"
HOSTS_BEGIN = "# BEGIN tpudra compute-domain daemons"
HOSTS_END = "# END tpudra compute-domain daemons"


def dns_name(index: int) -> str:
    return DNS_NAME_FORMAT % index


class DNSNameManager:
    def __init__(self, max_nodes: int, hosts_path: str = "/etc/hosts", nodes_config_path: str = ""):
        self._max_nodes = max_nodes
        self._hosts_path = hosts_path
        self._nodes_config_path = nodes_config_path

    def write_nodes_config(self, port_map=None) -> str:
        """Static peer list of max-size DNS names (WriteNodesConfig,
        dnsnames.go:191).  ``port_map`` ({index: port}) emits the
        port-annotated "name:port" form tpu-slicewatchd accepts for
        same-host peers (single-host test mode)."""
        def line(i: int) -> str:
            if port_map and i in port_map:
                return f"{dns_name(i)}:{port_map[i]}"
            return dns_name(i)

        content = "\n".join(line(i) for i in range(self._max_nodes)) + "\n"
        os.makedirs(os.path.dirname(self._nodes_config_path) or ".", exist_ok=True)
        # Atomic durable write through the storage seam: the slice daemon
        # reads this at startup, and a half-written peer list after a
        # crash would feed it a truncated world view.
        storage.atomic_replace(
            self._nodes_config_path, content.encode(), site="dnsnames-config"
        )
        return self._nodes_config_path

    def update_hosts_file(self, ips_by_index: dict[int, str]) -> bool:
        """Rewrite the managed /etc/hosts block; returns True if changed
        (updateHostsFile, dnsnames.go:145).  Unknown indices resolve to
        0.0.0.0 so lookups fail fast instead of hanging in DNS."""
        lines = [HOSTS_BEGIN]
        for i in range(self._max_nodes):
            ip = ips_by_index.get(i, "0.0.0.0")
            lines.append(f"{ip}\t{dns_name(i)}")
        lines.append(HOSTS_END)
        block = "\n".join(lines)

        try:
            with open(self._hosts_path) as f:
                current = f.read()
        except FileNotFoundError:
            current = ""
        begin = current.find(HOSTS_BEGIN)
        end = current.find(HOSTS_END)
        if begin != -1 and end != -1:
            new = current[:begin] + block + current[end + len(HOSTS_END):]
        else:
            new = current.rstrip("\n") + ("\n" if current.strip() else "") + block + "\n"
        if new == current:
            return False
        # In-place write, NOT an atomic rename: kubelet bind-mounts /etc/hosts
        # as a single file, and rename(2) onto a bind-mount target fails with
        # EBUSY (the reference writes in place too, dnsnames.go:183).
        # Durability is not load-bearing either — the pod's /etc/hosts is
        # reconstructed by kubelet on restart and the next membership event
        # rewrites the managed block.
        # tpudra-lint: disable=DURABLE-WRITE deliberate in-place /etc/hosts rewrite: rename onto a bind-mount target fails EBUSY, and kubelet regenerates the file on pod restart so crash durability buys nothing
        with open(self._hosts_path, "w") as f:
            f.write(new)
        logger.info("updated %s with %d peer mappings", self._hosts_path, len(ips_by_index))
        return True

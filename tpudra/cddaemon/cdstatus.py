"""Legacy direct-status membership (ComputeDomainCliques gate OFF).

The analog of compute-domain-daemon/cdstatus.go:55-477: instead of
rendezvousing through ComputeDomainClique CRs, each daemon upserts its node
entry straight into ``cd.status.nodes`` and learns peers by watching the
ComputeDomain itself.  Same interface as CliqueManager so DaemonApp can pick
one by feature gate.

Kept for one-release migration compatibility: a cluster downgrading the
gate must not strand daemons mid-domain.  The clique path is the default
(and scales better — one small CR per clique instead of every daemon
rewriting the CD object).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from tpudra.api.computedomain import (
    COMPUTE_DOMAIN_STATUS_NOT_READY,
    COMPUTE_DOMAIN_STATUS_READY,
)
from tpudra.cddaemon.cdclique import MAX_UPSERT_RETRIES, PeersCallback
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import Conflict, NotFound
from tpudra.kube.informer import Informer

logger = logging.getLogger(__name__)


class DirectStatusManager:
    """CliqueManager-shaped membership written directly to cd.status.nodes."""

    def __init__(
        self,
        kube: KubeAPI,
        cd_namespace: str,
        cd_name: str,
        clique_id: str,
        node_name: str,
        ip_address: str,
    ):
        self._kube = kube
        self._cd_ns = cd_namespace
        self._cd_name = cd_name
        self._clique_id = clique_id
        self._node = node_name
        self._ip = ip_address
        self._informer: Optional[Informer] = None
        self._peers_cb: Optional[PeersCallback] = None
        self._last_peers: Optional[dict[int, str]] = None
        self._lock = threading.Lock()
        self.index: Optional[int] = None

    @property
    def name(self) -> str:
        return f"{self._cd_ns}/{self._cd_name}"

    def _get_cd(self) -> dict:
        return self._kube.get(gvr.COMPUTE_DOMAINS, self._cd_name, self._cd_ns)

    # -- membership ---------------------------------------------------------

    def join(self) -> int:
        """Upsert this node into cd.status.nodes, claiming the lowest free
        index (the cdstatus.go analog of getNextAvailableIndex)."""
        for _ in range(MAX_UPSERT_RETRIES):
            cd = self._get_cd()
            nodes = cd.setdefault("status", {}).setdefault("nodes", [])
            mine = next((n for n in nodes if n.get("name") == self._node), None)
            if mine is not None:
                if (
                    mine.get("ipAddress") == self._ip
                    and mine.get("cliqueID") == self._clique_id
                ):
                    self.index = mine["index"]
                    return self.index
                # Restarted with a new IP or a rebuilt slice (new cliqueID):
                # refresh both, or peers' same-clique filters would exclude
                # this entry forever.
                mine["ipAddress"] = self._ip
                mine["cliqueID"] = self._clique_id
            else:
                used = {n.get("index") for n in nodes}
                index = next(i for i in range(len(nodes) + 1) if i not in used)
                nodes.append(
                    {
                        "name": self._node,
                        "ipAddress": self._ip,
                        "cliqueID": self._clique_id,
                        "index": index,
                        "status": COMPUTE_DOMAIN_STATUS_NOT_READY,
                    }
                )
            try:
                updated = self._kube.update_status(gvr.COMPUTE_DOMAINS, cd, self._cd_ns)
            except Conflict:
                continue
            mine = next(
                n for n in updated["status"]["nodes"] if n["name"] == self._node
            )
            self.index = mine["index"]
            logger.info(
                "joined %s via direct status as index %d", self.name, self.index
            )
            return self.index
        raise RuntimeError(f"could not join {self.name}: persistent conflicts")

    def update_daemon_status(self, ready: bool) -> bool:
        """Same success contract as CliqueManager.update_daemon_status:
        True = converged / nothing to write, False = write pending."""
        target = COMPUTE_DOMAIN_STATUS_READY if ready else COMPUTE_DOMAIN_STATUS_NOT_READY
        for _ in range(MAX_UPSERT_RETRIES):
            try:
                cd = self._get_cd()
            except NotFound:
                return True
            mine = next(
                (
                    n
                    for n in cd.get("status", {}).get("nodes", [])
                    if n.get("name") == self._node
                ),
                None,
            )
            if mine is None or mine.get("status") == target:
                return True
            mine["status"] = target
            try:
                self._kube.update_status(gvr.COMPUTE_DOMAINS, cd, self._cd_ns)
                return True
            except Conflict:
                continue
        logger.warning("could not update node status in %s", self.name)
        return False

    def leave(self) -> None:
        for _ in range(MAX_UPSERT_RETRIES):
            try:
                cd = self._get_cd()
            except NotFound:
                return
            nodes = cd.get("status", {}).get("nodes", [])
            remaining = [n for n in nodes if n.get("name") != self._node]
            if len(remaining) == len(nodes):
                return
            cd["status"]["nodes"] = remaining
            try:
                self._kube.update_status(gvr.COMPUTE_DOMAINS, cd, self._cd_ns)
                return
            except Conflict:
                continue

    # -- peer watching ------------------------------------------------------

    def watch_peers(self, callback: PeersCallback, stop: threading.Event) -> None:
        self._peers_cb = callback
        self._informer = Informer(self._kube, gvr.COMPUTE_DOMAINS, namespace=self._cd_ns)
        self._informer.add_handler(self._on_event)
        self._informer.start(stop)
        self._informer.wait_for_sync()

    def _on_event(self, etype: str, obj: dict) -> None:
        if obj.get("metadata", {}).get("name") != self._cd_name:
            return
        if etype == "DELETED":
            peers: dict[int, str] = {}
        else:
            peers = {
                n["index"]: n.get("ipAddress", "")
                for n in obj.get("status", {}).get("nodes", [])
                # Only same-clique peers are slice neighbors.
                if n.get("ipAddress") and n.get("cliqueID") == self._clique_id
            }
        with self._lock:
            if peers == self._last_peers:
                return
            self._last_peers = peers
        if self._peers_cb is not None:
            self._peers_cb(dict(peers))

"""tpudra: a TPU-native Kubernetes Dynamic Resource Allocation (DRA) driver.

Built from scratch with the capabilities of NVIDIA's k8s-dra-driver-gpu
(surveyed in SURVEY.md).  Two resource families are managed:

- TPUs (driver name ``tpu.google.com``): node-local allocation of full TPU
  chips, static/dynamic TensorCore partitions, and VFIO passthrough, with
  time-slicing and multi-process (MPS-analog) sharing.
- ComputeDomains (driver name ``compute-domain.tpu.google.com``): a
  cluster-level abstraction reserving ICI-connected TPU slices and exposing
  mesh topology to claimants (the analog of the reference's IMEX/MNNVL
  orchestration, reference cmd/compute-domain-*).
"""

__version__ = "0.4.0"

# DRA driver names (reference: cmd/gpu-kubelet-plugin/main.go:41,
# cmd/compute-domain-kubelet-plugin/main.go:42).
TPU_DRIVER_NAME = "tpu.google.com"
COMPUTE_DOMAIN_DRIVER_NAME = "compute-domain.tpu.google.com"

# API group for our custom resources (reference: api/nvidia.com/resource/v1beta1).
API_GROUP = "resource.tpu.google.com"
API_VERSION = "v1beta1"

# Claim-status condition type for the bound-claim health escalation:
# WRITTEN by the node plugin's health loop (plugin/driver.py) when granted
# silicon goes unhealthy under a bound claim, CONSUMED by the controller's
# claim-health watch to trigger degraded-gang remediation
# (controller/controller.py).  Lives here because both ends import it and
# neither may import the other.
CLAIM_UNHEALTHY_CONDITION = f"{TPU_DRIVER_NAME}/DeviceUnhealthy"

"""Retrying work queue with rate limiting.

The analog of the reference's pkg/workqueue (a wrapper over client-go's
rate-limiting workqueue, workqueue.go:152-190): closure-style work items that
are retried with per-item exponential backoff plus a global token bucket, and
*keyed* items with newest-wins semantics — enqueueing a newer item under the
same key drops older queued/retrying items, and a stale retry firing after a
newer enqueue is discarded.

Limiter presets mirror the reference's (workqueue.go:49-63):
- prepare/unprepare: per-item exponential 250ms→3s plus a global 5/s bucket
- compute-domain daemon: exponential 5ms→6s with jitter
- controller default: exponential 5ms→1000s plus a global 10/s bucket

Cluster-scale dispatch (docs/cluster-scale.md): ready work is served from
priority lanes (higher ``priority`` first) with per-key round-robin inside
each lane — every key with ready work gets one item per rotation, so a
flapping ComputeDomain that floods the queue cannot push 999 quiet domains'
single items arbitrarily far back.  Unkeyed closures share ONE fairness
bucket (anonymous work is a single rotation participant, not a crowd that
can monopolize the rotation).  ``fair=False`` restores the pre-lanes
single-heap FIFO — the "before" arm of ``bench.py --cluster-scale``.
Backoff jitter accepts an injected ``random.Random`` so A/B arms replay
identical schedules from one seed.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpudra import lockwitness, racewitness
from tpudra.backoff import capped_exponential

logger = logging.getLogger(__name__)


def _retry_after_of(exc: BaseException):
    """kube/errors.retry_after_of via a late import: the workqueue is a
    lower layer than the kube client (which imports TokenBucket from
    here), so a module-level import would be a cycle."""
    from tpudra.kube.errors import retry_after_of

    return retry_after_of(exc)


class ExponentialBackoff:
    """Per-item exponential backoff: base * 2^failures, capped — the
    window arithmetic comes from the shared ``tpudra/backoff.py`` policy
    (overflow-clamped ``capped_exponential``); this class adds the
    per-item failure bookkeeping and the limiter's historical
    multiplicative-jitter contract on top.

    ``rng`` injects the jitter source (``random.Random(seed)``) so
    cluster-scale A/B arms are reproducible; default is the module-global
    generator (the pre-seed behavior)."""

    def __init__(
        self,
        base: float,
        cap: float,
        jitter: float = 0.0,
        rng: Optional[random.Random] = None,
    ):
        self.base = base
        self.cap = cap
        self.jitter = jitter
        self.rng = rng if rng is not None else random
        self._failures: dict[object, int] = {}
        self._lock = lockwitness.make_lock("workqueue.backoff_lock")

    def when(self, item: object) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        delay = capped_exponential(self.base, self.cap, n)
        if self.jitter:
            delay *= 1.0 + self.rng.uniform(0, self.jitter)
        return delay

    def forget(self, item: object) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def retries(self, item: object) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class TokenBucket:
    """Global qps/burst limiter; ``reserve()`` returns the wait time."""

    def __init__(self, qps: float, burst: int):
        self.qps = qps
        self.burst = burst
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._lock = lockwitness.make_lock("workqueue.bucket_lock")

    def reserve(self) -> float:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
            self._last = now
            self._tokens -= 1.0
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self.qps


class RateLimiter:
    """Max-of(per-item backoff, global bucket) — client-go's MaxOfRateLimiter."""

    def __init__(self, backoff: ExponentialBackoff, bucket: Optional[TokenBucket] = None):
        self.backoff = backoff
        self.bucket = bucket

    def when(self, item: object) -> float:
        delay = self.backoff.when(item)
        if self.bucket is not None:
            delay = max(delay, self.bucket.reserve())
        return delay

    def forget(self, item: object) -> None:
        self.backoff.forget(item)

    def retries(self, item: object) -> int:
        return self.backoff.retries(item)


def prep_unprep_rate_limiter(rng: Optional[random.Random] = None) -> RateLimiter:
    """Preset for claim prepare/unprepare retries (reference workqueue.go:49-59)."""
    return RateLimiter(ExponentialBackoff(0.25, 3.0, rng=rng), TokenBucket(5.0, 10))


def daemon_rate_limiter(rng: Optional[random.Random] = None) -> RateLimiter:
    """Preset for compute-domain daemon loops (reference workqueue.go:61-63)."""
    return RateLimiter(ExponentialBackoff(0.005, 6.0, jitter=0.5, rng=rng))


def default_controller_rate_limiter(rng: Optional[random.Random] = None) -> RateLimiter:
    """client-go's DefaultControllerRateLimiter equivalent."""
    return RateLimiter(
        ExponentialBackoff(0.005, 1000.0, rng=rng), TokenBucket(10.0, 100)
    )


#: Priority-lane conventions.  Any int works; these name the intent so call
#: sites across the tree agree on relative order.
PRIORITY_HIGH = 10
PRIORITY_NORMAL = 0
PRIORITY_LOW = -10


@dataclass(order=True)
class _Entry:
    ready_at: float
    seq: int
    fn: Callable[[], None] = field(compare=False)
    key: Optional[object] = field(compare=False, default=None)
    gen: int = field(compare=False, default=0)
    priority: int = field(compare=False, default=0)


class _Lane:
    """Ready entries of one priority: per-key FIFO buckets served
    round-robin.  Invariant (under the queue cond): a fairness key is in
    ``rotation`` exactly once iff its bucket is non-empty."""

    __slots__ = ("by_key", "rotation")

    def __init__(self) -> None:
        self.by_key: dict[object, deque[_Entry]] = {}
        self.rotation: deque[object] = deque()


class WorkQueue:
    """A retrying queue of closures.

    - ``enqueue(fn)``: run fn; on exception, retry after the limiter's delay.
    - ``enqueue_keyed(key, fn)``: same, but a later enqueue under ``key``
      supersedes earlier queued/retrying entries (newest wins; stale retries
      are dropped on pop).
    - ``run(stop)``: worker loop; call from one or more threads.

    With ``fair=True`` (default), READY entries dispatch from priority
    lanes (higher ``priority`` first) with per-key round-robin inside a
    lane; not-yet-ready entries (retries, defers) wait in the timer heap
    and migrate to their lane when due.  ``fair=False`` is the pre-lanes
    behavior: one heap, strict (ready_at, seq) order, no priorities — kept
    as the measurable "before" arm.
    """

    def __init__(
        self,
        rate_limiter: Optional[RateLimiter] = None,
        max_retries: int | None = None,
        name: str = "default",
        fair: bool = True,
        rng: Optional[random.Random] = None,
    ):
        explicit_limiter = rate_limiter is not None
        # Plain param assignment (not `x or default()`): the lockgraph's
        # attr-type inference reads the annotation off the param, which is
        # what lets it model `self._limiter.forget()`'s backoff_lock edge
        # under callers' held locks (informer handler dispatch).
        if rate_limiter is None:
            rate_limiter = default_controller_rate_limiter(rng=rng)
        self._limiter = rate_limiter
        if rng is not None and explicit_limiter:
            # An explicit seed overrides the limiter's jitter source, so one
            # WorkQueue(seeded) call reproduces the whole retry schedule.
            self._limiter.backoff.rng = rng
        self._heap: list[_Entry] = []
        self._fair = fair
        self._lanes: dict[int, _Lane] = {}
        self._ready_count = 0
        #: key -> priority of its live (newest-generation) entry.
        #: Supersession must never DEMOTE: a LOW resync enqueue landing on
        #: a key whose pending entry is HIGH (a terminating CD) would drop
        #: the HIGH entry as stale and bury the teardown in the LOW lane.
        self._live_priority: dict[object, int] = {}
        self._cond = lockwitness.make_condition("workqueue.cond")
        self._seq = itertools.count()
        self._gens: dict[object, int] = {}
        self._active_keys: set[object] = set()
        self._shutdown = False
        #: While True, _pop hands out nothing: enqueues still land (and
        #: keyed supersession still applies) but no worker dispatches.
        #: The controller's leader-election gate (docs/ha.md): a replica
        #: that lost its lease must stop ACTING immediately, while its
        #: queue keeps absorbing informer events so a re-acquire resumes
        #: from coalesced state instead of a cold resync.
        self._paused = False
        self._max_retries = max_retries
        self._inflight = 0
        self._name = name
        # Resolve the labelled children once — .labels() takes an internal
        # lock and these are updated inside self._cond's critical section.
        from tpudra import metrics

        self._depth_gauge = metrics.WORKQUEUE_DEPTH.labels(name)
        self._retries_counter = metrics.WORKQUEUE_RETRIES.labels(name)

    def _update_depth(self) -> None:
        """Caller must hold self._cond."""
        self._depth_gauge.set(len(self._heap) + self._ready_count + self._inflight)

    # -- producers ----------------------------------------------------------

    def enqueue(self, fn: Callable[[], None], priority: int = PRIORITY_NORMAL) -> None:
        self._push(fn, key=None, delay=0.0, gen=0, priority=priority)

    def enqueue_keyed(
        self, key: object, fn: Callable[[], None], priority: int = PRIORITY_NORMAL
    ) -> None:
        with self._cond:
            gen = self._gens.get(key, 0) + 1
            self._gens[key] = gen
            # Superseding a pending entry inherits the max of the two
            # priorities: newest-wins replaces the WORK, not the urgency
            # (a LOW backstop sweep must not demote a pending HIGH
            # teardown into the LOW lane).
            priority = max(priority, self._live_priority.get(key, priority))
            self._live_priority[key] = priority
        # A fresh enqueue resets the key's backoff history: the newest intent
        # is a new piece of work, not a retry of the old one.
        self._limiter.forget(key)
        self._push(fn, key=key, delay=0.0, gen=gen, priority=priority)

    def _push(self, fn, key, delay, gen, priority=PRIORITY_NORMAL) -> None:
        entry = _Entry(
            time.monotonic() + delay, next(self._seq), fn, key, gen, priority
        )
        with self._cond:
            if self._shutdown:
                return
            if self._fair and delay <= 0:
                self._ready_add(entry)
            else:
                heapq.heappush(self._heap, entry)
            self._update_depth()
            if racewitness.enabled():
                # The enqueue→pop handoff is the queue's happens-before
                # edge; sampled inside the cond so the held lockset is real.
                racewitness.note_access("WorkQueue._heap")
                racewitness.note_hb_send("workqueue.cond")
            self._cond.notify()

    # -- fair-dispatch internals (every helper expects self._cond held) -----

    def _fairness_key(self, entry: _Entry) -> object:
        # Keyed work rotates per key; ALL unkeyed work shares one bucket —
        # an anonymous flood is one rotation participant, not a crowd.
        return entry.key

    def _ready_add(self, entry: _Entry) -> None:
        lane = self._lanes.get(entry.priority)
        if lane is None:
            lane = self._lanes[entry.priority] = _Lane()
        fkey = self._fairness_key(entry)
        bucket = lane.by_key.get(fkey)
        if bucket is None:
            bucket = lane.by_key[fkey] = deque()
            lane.rotation.append(fkey)
        bucket.append(entry)
        self._ready_count += 1

    def _ready_pop(self) -> Optional[_Entry]:
        for priority in sorted(self._lanes, reverse=True):
            lane = self._lanes[priority]
            if not lane.rotation:
                continue
            fkey = lane.rotation.popleft()
            bucket = lane.by_key[fkey]
            entry = bucket.popleft()
            if bucket:
                lane.rotation.append(fkey)
            else:
                del lane.by_key[fkey]
            self._ready_count -= 1
            return entry
        return None

    def _migrate_due(self, now: float) -> None:
        """Move due timer-heap entries into their priority lane."""
        while self._heap and self._heap[0].ready_at <= now:
            self._ready_add(heapq.heappop(self._heap))


    # -- consumer -----------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        while not stop.is_set():
            entry = self._pop(stop)
            if entry is None:
                return
            if entry.key is not None:
                defer = False
                with self._cond:
                    if self._gens.get(entry.key, 0) != entry.gen:
                        # Superseded by a newer enqueue: drop the stale item.
                        self._inflight -= 1
                        self._update_depth()
                        self._cond.notify_all()
                        continue
                    if entry.key in self._active_keys:
                        # Another worker is processing this key; never run one
                        # key concurrently (client-go dirty/processing-set
                        # semantics). Defer briefly and re-check.
                        entry = _Entry(
                            time.monotonic() + 0.005, next(self._seq),
                            entry.fn, entry.key, entry.gen, entry.priority,
                        )
                        heapq.heappush(self._heap, entry)
                        self._inflight -= 1
                        self._update_depth()
                        self._cond.notify_all()
                        defer = True
                    else:
                        self._active_keys.add(entry.key)
                if defer:
                    continue
            try:
                entry.fn()
            except Exception as e:  # noqa: BLE001 — retried work must not kill worker
                item = entry.key if entry.key is not None else entry.fn
                if (
                    self._max_retries is not None
                    and self._limiter.retries(item) >= self._max_retries
                ):
                    logger.error("work item %r failed permanently: %s", item, e)
                    self._limiter.forget(item)
                else:
                    delay = self._limiter.when(item)
                    # An apiserver 429/503's Retry-After hint floors the
                    # limiter's delay (kube/errors.retry_after_of): the
                    # server asked for quiet, and retrying into its shed
                    # window re-feeds the storm it is shedding.
                    retry_after = _retry_after_of(e)
                    if retry_after is not None:
                        delay = max(delay, retry_after)
                    logger.debug("work item %r failed (%s); retrying in %.3fs", item, e, delay)
                    self._retries_counter.inc()
                    self._push(entry.fn, entry.key, delay, entry.gen, entry.priority)
            else:
                self._limiter.forget(entry.key if entry.key is not None else entry.fn)
            finally:
                with self._cond:
                    if entry.key is not None:
                        self._active_keys.discard(entry.key)
                        # Done with the newest generation of this key (no
                        # retry queued): drop the bookkeeping so long-lived
                        # daemons don't accumulate an entry per claim ever
                        # seen.
                        if (
                            self._gens.get(entry.key) == entry.gen
                            and not self._has_queued_key(entry.key)
                        ):
                            del self._gens[entry.key]
                            self._live_priority.pop(entry.key, None)
                    self._inflight -= 1
                    self._update_depth()
                    self._cond.notify_all()

    def _has_queued_key(self, key: object) -> bool:
        """Caller must hold self._cond."""
        if any(e.key == key for e in self._heap):
            return True
        return any(lane.by_key.get(key) for lane in self._lanes.values())

    def _pop(self, stop: threading.Event) -> Optional[_Entry]:
        with self._cond:
            while True:
                if self._shutdown or stop.is_set():
                    return None
                if self._paused:
                    self._cond.wait(timeout=0.1)
                    continue
                now = time.monotonic()
                if self._fair:
                    self._migrate_due(now)
                    entry = self._ready_pop()
                    if entry is not None:
                        self._inflight += 1
                        if racewitness.enabled():
                            racewitness.note_hb_recv("workqueue.cond")
                            racewitness.note_access("WorkQueue._heap")
                        return entry
                elif self._heap and self._heap[0].ready_at <= now:
                    self._inflight += 1
                    if racewitness.enabled():
                        racewitness.note_hb_recv("workqueue.cond")
                        racewitness.note_access("WorkQueue._heap")
                    return heapq.heappop(self._heap)
                if self._heap:
                    self._cond.wait(
                        timeout=min(self._heap[0].ready_at - now, 0.1)
                    )
                else:
                    self._cond.wait(timeout=0.1)

    # -- lifecycle / introspection ------------------------------------------

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def pause(self) -> None:
        """Suspend dispatch: in-flight items finish, nothing new pops.
        Producers are unaffected.  Idempotent."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        """Lift a pause(); idempotent."""
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    @property
    def paused(self) -> bool:
        with self._cond:
            return self._paused

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until the queue is empty and no item is in flight."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._heap or self._ready_count or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(remaining, 0.05))
            return True

    def __len__(self) -> int:
        with self._cond:
            return len(self._heap) + self._ready_count

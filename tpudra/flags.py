"""CLI flag plumbing shared by all five binaries.

The analog of pkg/flags (reference kubeclient.go:33-118, featuregates.go,
LogStartupConfig): every flag has an environment-variable mirror (urfave/cli
convention — flags win over env, env over defaults), plus common groups for
logging, feature gates, and the kube client.
"""

from __future__ import annotations

import argparse
import logging
import os

from tpudra import featuregates

logger = logging.getLogger(__name__)


def env_default(env: str, fallback: str = "") -> str:
    return os.environ.get(env, fallback)


def _env_int(env: str, fallback: int) -> int:
    """Env mirror for an integer flag; malformed values fall back instead
    of crashing the binary before arg parsing."""
    try:
        return int(os.environ.get(env, "") or fallback)
    except ValueError:
        return fallback


def _env_float(env: str, fallback: float) -> float:
    try:
        return float(os.environ.get(env, "") or fallback)
    except ValueError:
        return fallback


def add_common_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--kubeconfig",
        default=env_default("KUBECONFIG"),
        help="kubeconfig path (empty: in-cluster service account) [KUBECONFIG]",
    )
    parser.add_argument(
        "--kube-api-qps",
        type=float,
        default=_env_float("KUBE_API_QPS", 5.0),
        help="client-side QPS toward the apiserver; <= 0 disables "
        "(reference kube-api-qps, kubeclient.go:54-61) [KUBE_API_QPS]",
    )
    parser.add_argument(
        "--kube-api-burst",
        type=int,
        default=_env_int("KUBE_API_BURST", 10),
        help="client-side burst toward the apiserver "
        "(reference kube-api-burst, kubeclient.go:62-69) [KUBE_API_BURST]",
    )
    parser.add_argument(
        "--feature-gates",
        default=env_default("FEATURE_GATES"),
        help="comma-separated gate=bool pairs [FEATURE_GATES]",
    )
    parser.add_argument(
        "--log-level",
        default=env_default("LOG_LEVEL", "INFO"),
        help="python logging level name [LOG_LEVEL]",
    )
    parser.add_argument(
        "--log-verbosity",
        type=int,
        default=_env_int("LOG_VERBOSITY", 0),
        help="klog-style numeric verbosity; >=4 implies DEBUG and is "
        "propagated into spawned daemon pods [LOG_VERBOSITY]",
    )
    from tpudra import buildinfo

    parser.add_argument(
        "--version", action="version", version=buildinfo.version_string()
    )


def setup_common(args: argparse.Namespace) -> None:
    level_name = args.log_level.upper()
    # Verbosity propagation: the controller renders its numeric verbosity
    # into spawned daemon pods as LOG_VERBOSITY (the reference's klog -v
    # template propagation, daemonset.go:45-56).  A klog-style v>=4 means
    # debug; an explicit LOG_LEVEL/--log-level still wins.
    verbosity = getattr(args, "log_verbosity", None)
    if verbosity is None:  # caller without common flags: the env mirror
        verbosity = _env_int("LOG_VERBOSITY", 0)
    if "LOG_LEVEL" not in os.environ and level_name == "INFO" and verbosity >= 4:
        level_name = "DEBUG"
    logging.basicConfig(
        level=getattr(logging, level_name, logging.INFO),
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    if args.feature_gates:
        featuregates.feature_gates().set_from_spec(args.feature_gates)
    featuregates.validate()
    # Every binary: kill -USR1 dumps all thread stacks to stderr
    # (internal/common/util.go:35 analog).
    from tpudra import metrics

    metrics.install_debug_handlers()
    log_startup_config(args)


def log_startup_config(args: argparse.Namespace) -> None:
    """Structured startup-config dump (pkg/flags LogStartupConfig analog)."""
    from tpudra import buildinfo

    logger.info("%s", buildinfo.version_string())
    logger.info(
        "startup config: %s",
        " ".join(f"{k}={v!r}" for k, v in sorted(vars(args).items()) if k != "func"),
    )
    logger.info(
        "feature gates: %s",
        " ".join(f"{k}={v}" for k, v in sorted(featuregates.to_map().items())),
    )


def install_stop_handlers() -> "threading.Event":
    """Install SIGTERM/SIGINT handlers that set (and return) a stop event.

    Must be called BEFORE any server/socket startup: the reference's helper
    wires signal handling ahead of kubeletplugin.Start (clean shutdown in
    cmd/gpu-kubelet-plugin/driver.go:170-200); installing afterwards leaves a
    window where a kubelet drain that observes the freshly published
    ResourceSlices can SIGTERM the process while the signal still has default
    disposition — death rc=-15 with no socket unlink or slice retraction.
    """
    import signal
    import threading

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    return stop


def make_kube_client(kubeconfig: str, qps: float = 0.0, burst: int = 0):
    from tpudra.kube.client import KubeClient

    if kubeconfig:
        return KubeClient.from_kubeconfig(kubeconfig, qps=qps, burst=burst)
    return KubeClient.auto(qps=qps, burst=burst)


def make_kube_client_from_args(args: argparse.Namespace):
    """The binaries' entry: kubeconfig + QPS/burst from the common flags."""
    return make_kube_client(
        args.kubeconfig,
        qps=getattr(args, "kube_api_qps", 0.0),
        burst=getattr(args, "kube_api_burst", 0),
    )


def make_device_lib(backend: str, config: str):
    from tpudra.devicelib import make_device_lib as factory

    kwargs = {}
    if backend == "native" and config:
        kwargs["config_path"] = config
    return factory(backend, **kwargs)

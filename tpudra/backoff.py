"""Capped exponential backoff with full jitter — the one retry-delay policy.

Before this module, every retry loop in the tree rolled its own delay
math: the informer relist doubled a float with half-jitter, the workqueue
limiter multiplied an optional jitter factor, the slice publisher slept a
flat second.  The math differences are mostly harmless; the *jitter*
differences are not.  At cluster scale an apiserver flap puts hundreds of
informers into their failure loops within milliseconds of each other, and
any deterministic (or narrowly-jittered) schedule marches them back in
lockstep — the relist storm arrives as one synchronized wave exactly when
the apiserver is weakest.  "Full jitter" (delay drawn uniformly from
``[0, min(cap, base·2ⁿ)]``) decorrelates the herd: the retry *budget*
still grows exponentially, but each client lands at an independent point
in the window, so the recovering server sees a flat trickle instead of
spikes (the AWS architecture-blog result; client-go's reflector jitters
for the same reason).

Two layers:

- :func:`capped_exponential` / :func:`full_jitter_delay` — pure delay
  arithmetic, shared with the workqueue's :class:`ExponentialBackoff`
  (which keeps its own per-item failure bookkeeping and its historical
  multiplicative-jitter contract).
- :class:`Backoff` — a stateful helper for the common loop shape
  (informer relist, publisher retry): ``next_delay()`` grows the window,
  ``reset()`` collapses it after a success.

``rng`` is injectable everywhere (``random.Random(seed)``) so the chaos
soak and the cluster-scale bench replay identical schedules from a seed;
the default is the module-global generator.
"""

from __future__ import annotations

import random
from typing import Optional


def capped_exponential(base: float, cap: float, attempt: int) -> float:
    """``min(cap, base * 2**attempt)`` without overflow: the exponent is
    clamped so attempt counts from a long outage cannot overflow a float
    (2**1024 raises OverflowError; a retry loop must never die of
    arithmetic)."""
    if base <= 0:
        return 0.0
    if attempt > 62:  # base * 2**62 already dwarfs any sane cap
        return cap
    return min(cap, base * (2.0 ** max(0, attempt)))


def full_jitter_delay(
    base: float,
    cap: float,
    attempt: int,
    rng: Optional[random.Random] = None,
) -> float:
    """One full-jitter delay: uniform over ``[0, capped_exponential(...)]``."""
    window = capped_exponential(base, cap, attempt)
    return (rng if rng is not None else random).uniform(0.0, window)


class Backoff:
    """Stateful capped-exponential-with-full-jitter for one retry loop.

    Not thread-safe by design: each loop (an informer's run thread, the
    publisher thread) owns its own instance, the way each owns its own
    failure count today.  Share across threads and the worst case is a
    sloppy attempt counter, but don't."""

    def __init__(
        self,
        base: float,
        cap: float,
        rng: Optional[random.Random] = None,
    ):
        self.base = base
        self.cap = cap
        self._rng = rng if rng is not None else random
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt

    def next_delay(self) -> float:
        """The delay before the next retry; each call widens the window."""
        delay = full_jitter_delay(self.base, self.cap, self._attempt, self._rng)
        # tpudra-race: handoff per-instance confinement: each retry loop owns its own Backoff (class docstring); the cross-role reach is different instances, never shared state
        self._attempt += 1
        return delay

    def reset(self) -> None:
        """Collapse the window after a success."""
        # tpudra-race: handoff per-instance confinement: each retry loop owns its own Backoff (class docstring); the cross-role reach is different instances, never shared state
        self._attempt = 0

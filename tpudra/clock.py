"""Clock seam: monotonic-by-default time for staleness/GC decisions.

The repo's concurrency discipline already insists on ``time.monotonic()``
for intervals, but a few GC-flavored decisions are forced to touch the
WALL clock because their evidence is wall-anchored (file mtimes, API
timestamps) — and the wall clock steps.  An NTP correction, a VM
migration, or a chrony slew of ±minutes is routine on real nodes, and a
staleness rule written as ``wall_now - mtime >= grace`` turns that step
into either a *premature* GC (clock jumps forward: everything suddenly
looks aged-out) or an *infinitely deferred* one (clock jumps back: ages
go negative and nothing ever qualifies).  Both failure modes are exactly
the kind of fault the chaos soak injects (sim/chaos.py ``clock_skew``).

This module gives those sites one injectable seam:

- :class:`Clock` — ``monotonic()`` + ``wall()``; the process-wide
  :data:`SYSTEM` instance is the default everywhere.
- :class:`SkewedClock` — a test/chaos clock whose wall (and optionally
  monotonic) reading is offset by a mutable skew, so a ±10-minute NTP
  step is one attribute assignment in a test.
- :class:`MonotonicAger` — the *discipline*, packaged: age an observed
  identity (a file's ``(ino, mtime_ns)``, a claim uid + status) by how
  long THIS process has continuously observed it on the monotonic clock,
  never by subtracting a wall mtime from wall now.  An identity change
  resets the age (the thing was replaced); wall skew cannot touch it.

The cost of monotonic aging is that a freshly restarted observer waits
one full grace period before acting — a bounded, safe-direction delay,
versus the unbounded wrong-direction failure of wall math under skew.
"""

from __future__ import annotations

import threading
import time
from typing import Hashable, Optional


class Clock:
    """Process-time source.  ``monotonic()`` for intervals, ``wall()``
    for timestamps compared against external wall-anchored evidence."""

    def monotonic(self) -> float:
        return time.monotonic()

    def wall(self) -> float:
        return time.time()


#: The default clock every production call site uses.
SYSTEM = Clock()


class SkewedClock(Clock):
    """A clock with injectable skew (tests and the chaos soak).

    ``wall_skew_s`` models an NTP step / bad RTC: it offsets ``wall()``
    only.  ``monotonic_skew_s`` exists for completeness (a paused VM's
    suspended monotonic clock) but defaults to 0 — CLOCK_MONOTONIC does
    not step on real kernels, which is the whole reason the GC discipline
    anchors on it."""

    def __init__(self, wall_skew_s: float = 0.0, monotonic_skew_s: float = 0.0):
        self.wall_skew_s = wall_skew_s
        self.monotonic_skew_s = monotonic_skew_s

    def monotonic(self) -> float:
        return time.monotonic() + self.monotonic_skew_s

    def wall(self) -> float:
        return time.time() + self.wall_skew_s


class MonotonicAger:
    """Continuous-observation aging for GC staleness decisions.

    ``age(key, identity)`` returns how long (monotonic seconds) ``key``
    has been observed with an unchanged ``identity``; the first
    observation — and every identity change — restarts the timer at 0.
    ``forget(key)`` drops a key whose object disappeared, so a later
    reappearance starts fresh.

    This is the skew-immune replacement for ``wall_now - mtime``: the
    observer vouches only for time it actually watched, on a clock that
    cannot step.  Thread-safe (GC threads and probe threads share one)."""

    def __init__(self, clock: Optional[Clock] = None):
        self._clock = clock if clock is not None else SYSTEM
        self._lock = threading.Lock()
        self._seen: dict[Hashable, tuple[Hashable, float]] = {}

    def age(self, key: Hashable, identity: Hashable) -> float:
        now = self._clock.monotonic()
        with self._lock:
            entry = self._seen.get(key)
            if entry is None or entry[0] != identity:
                self._seen[key] = (identity, now)
                return 0.0
            return now - entry[1]

    def forget(self, key: Hashable) -> None:
        with self._lock:
            self._seen.pop(key, None)

    def tracked(self) -> set:
        with self._lock:
            return set(self._seen)

    def prune(self, live_keys) -> None:
        """Drop every tracked key not in ``live_keys`` — call once per GC
        pass so a long-lived observer's table tracks live objects, not
        every object it has ever seen."""
        live = set(live_keys)
        with self._lock:
            for key in [k for k in self._seen if k not in live]:
                del self._seen[key]

"""Plugin-side ComputeDomain manager.

The analog of compute-domain-kubelet-plugin/computedomain.go:50-439: finds
CDs by UID, adds/removes this node's attraction label (the pull model that
summons the controller's DaemonSet, §3.3), checks readiness against the CD
status, and manages per-domain daemon settings (the config dir + env the
daemon claim injects).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from tpudra import storage
from tpudra.api.computedomain import (
    COMPUTE_DOMAIN_NODE_LABEL,
    COMPUTE_DOMAIN_STATUS_READY,
)
from tpudra.cddaemon.dnsnames import dns_name
from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI

logger = logging.getLogger(__name__)

DEFAULT_COORDINATOR_PORT = 7175
# In-pod mount point of the per-domain host dir (daemon pods and the
# coordinator-registration env both name it — one constant, because the
# sim's env→host-path translation only works when the env value exactly
# matches the mount's containerPath).
DAEMON_CD_MOUNT = "/etc/tpudra-cd"


class ComputeDomainManager:
    def __init__(self, kube: KubeAPI, node_name: str, plugin_dir: str):
        self._kube = kube
        self._node = node_name
        self._domains_dir = os.path.join(plugin_dir, "domains")

    # -- lookup -------------------------------------------------------------

    @property
    def kube(self):
        """The cluster client (pod lookups for the worker-hostnames
        reachability policy, cdplugin/state.py)."""
        return self._kube

    def get_by_uid(self, uid: str) -> Optional[dict]:
        for cd in self._kube.list(gvr.COMPUTE_DOMAINS).get("items", []):
            if cd["metadata"]["uid"] == uid:
                return cd
        return None

    def assert_in_namespace(self, uid: str, namespace: str) -> dict:
        """A channel claim may only consume a CD from its own namespace —
        the cross-namespace guard (device_state.go:466-475)."""
        cd = self.get_by_uid(uid)
        if cd is None:
            raise LookupError(f"ComputeDomain {uid} not found")
        if cd["metadata"]["namespace"] != namespace:
            raise PermissionError(
                f"ComputeDomain {uid} is in namespace "
                f"{cd['metadata']['namespace']!r}, claim is in {namespace!r}"
            )
        return cd

    # -- node label (the DaemonSet attractor) -------------------------------

    def add_node_label(self, uid: str) -> None:
        node = self._kube.get(gvr.NODES, self._node)
        labels = node["metadata"].get("labels", {})
        if labels.get(COMPUTE_DOMAIN_NODE_LABEL) == uid:
            return
        if COMPUTE_DOMAIN_NODE_LABEL in labels:
            # One domain per node at a time (a TPU host belongs to one slice).
            raise RuntimeError(
                f"node {self._node} already labeled for domain "
                f"{labels[COMPUTE_DOMAIN_NODE_LABEL]}"
            )
        self._kube.patch(
            gvr.NODES, self._node, {"metadata": {"labels": {COMPUTE_DOMAIN_NODE_LABEL: uid}}}
        )
        logger.info("labeled node %s for ComputeDomain %s", self._node, uid)

    def remove_node_label(self, uid: str) -> None:
        node = self._kube.get(gvr.NODES, self._node)
        if node["metadata"].get("labels", {}).get(COMPUTE_DOMAIN_NODE_LABEL) != uid:
            return
        self._kube.patch(
            gvr.NODES, self._node, {"metadata": {"labels": {COMPUTE_DOMAIN_NODE_LABEL: None}}}
        )

    # -- readiness gate -----------------------------------------------------

    def node_ready_in_domain(self, uid: str) -> bool:
        """This node's entry in cd.status.nodes is Ready
        (AssertComputeDomainReady, computedomain.go:238-294)."""
        cd = self.get_by_uid(uid)
        if cd is None:
            return False
        for node in cd.get("status", {}).get("nodes", []):
            if node.get("name") == self._node:
                return node.get("status") == COMPUTE_DOMAIN_STATUS_READY
        return False

    def domain_ready(self, uid: str) -> bool:
        cd = self.get_by_uid(uid)
        return (
            cd is not None
            and cd.get("status", {}).get("status") == COMPUTE_DOMAIN_STATUS_READY
        )

    # -- per-domain daemon settings ----------------------------------------

    def domain_dir(self, uid: str) -> str:
        return os.path.join(self._domains_dir, uid)

    def prepare_daemon_settings(
        self,
        uid: str,
        clique_id: str,
        num_hosts: int,
        host_index: int,
        libtpu_env: Optional[dict] = None,
    ) -> dict:
        """Create the config dir + env for the daemon claim
        (ComputeDomainDaemonSettings, computedomain.go:62).  ``libtpu_env``
        is the worker-bootstrap contract (cdplugin/libtpuenv.py) recorded in
        the settings so operators can read the slice's mesh-formation env
        off the daemon."""
        d = self.domain_dir(uid)
        os.makedirs(d, exist_ok=True)
        env = {
            "CD_UID": uid,
            "CLIQUE_ID": clique_id,
            "TPUDRA_NUM_HOSTS": str(num_hosts),
            "TPUDRA_HOST_INDEX": str(host_index),
            # Stable rendezvous: the index-0 daemon's DNS name.
            "TPUDRA_COORDINATOR": f"{dns_name(0)}:{DEFAULT_COORDINATOR_PORT}",
            # Where the coordinator proxy finds the host-0 workload's
            # registration — the same dir this grant mounts.  Explicit
            # (it equals the in-pod default) so environments that apply
            # CDI mounts by env translation (the cluster sim) resolve it
            # to the real host path.
            "COORDINATOR_DIR": DAEMON_CD_MOUNT,
        }
        env.update(libtpu_env or {})
        # Atomic durable write (storage seam): the daemon claim's CDI grant
        # mounts this file, and an acknowledged channel prepare must never
        # leave a torn/absent daemon.env behind a crash.
        content = "".join(f"{k}={v}\n" for k, v in sorted(env.items()))
        storage.atomic_replace(
            os.path.join(d, "daemon.env"), content.encode(),
            site="cd-daemon-settings",
        )
        return env

    def cleanup_daemon_settings(self, uid: str) -> None:
        d = self.domain_dir(uid)
        try:
            for name in os.listdir(d):
                os.unlink(os.path.join(d, name))
            os.rmdir(d)
        except FileNotFoundError:
            pass

"""Allocatable devices for the ComputeDomain plugin.

The analog of compute-domain-kubelet-plugin/{nvlib,deviceinfo,allocatable}.go:
2048 abstract channel devices (``channel-0..2047``) plus one daemon device
(``daemon-0``) per node.  Channels are not hardware — they are the per-
workload security boundary of a domain (reference computedomain.go:29-30):
pods holding the same channel in the same domain may establish slice-wide
collectives; the scheduler's job is only to pick a free channel number.

The cliqueID attribute carries this host's ICI fabric identity
(``<slice_uuid>.<partition_id>``) so DeviceClass CEL selectors can constrain
co-scheduling to one fabric (the clusterUUID.cliqueID analog,
nvlib.go:201-356).
"""

from __future__ import annotations

import logging

from tpudra import featuregates
from tpudra.cdplugin import CHANNEL_COUNT
from tpudra.devicelib import DeviceLib

logger = logging.getLogger(__name__)

TYPE_CHANNEL = "channel"
TYPE_DAEMON = "daemon"


class FabricError(RuntimeError):
    """ICI fabric state is inconsistent on this host."""

CHANNEL_DEV_DIR = "/dev/tpudra-channels"


def channel_name(i: int) -> str:
    return f"channel-{i}"


def daemon_name() -> str:
    return "daemon-0"


def channel_dev_path(i: int) -> str:
    return f"{CHANNEL_DEV_DIR}/channel{i}"


def parse_device_name(name: str) -> tuple[str, int]:
    """→ (type, id); raises ValueError on unknown names."""
    if name == daemon_name():
        return TYPE_DAEMON, 0
    if name.startswith("channel-"):
        return TYPE_CHANNEL, int(name[len("channel-"):])
    raise ValueError(f"unknown compute-domain device {name!r}")


def resolve_clique_id(chips) -> str:
    """This host's fabric identity, with the strict/legacy split of
    reference nvlib.go:201-356 keyed on the CrashOnICIFabricErrors gate
    (featuregates.go:33-59): strict mode (default) raises on inconsistent
    or missing fabric state so the plugin restarts visibly; legacy mode
    degrades the host to non-fabric membership (empty cliqueID — the
    daemon idles and the controller tracks the node through its DS pod)."""
    ids = {c.clique_id for c in chips}
    strict = featuregates.enabled(featuregates.CRASH_ON_ICI_FABRIC_ERRORS)
    if len(ids) > 1:
        msg = f"chips disagree on ICI clique: {sorted(ids)}"
        if strict:
            raise FabricError(msg)
        logger.warning("%s — degrading to non-fabric membership", msg)
        return ""
    if chips and not chips[0].clique_id:
        msg = "chips report no ICI clique membership"
        if strict:
            raise FabricError(msg)
        logger.warning("%s — degrading to non-fabric membership", msg)
        return ""
    return chips[0].clique_id if chips else ""


def build_devices(lib: DeviceLib) -> list[dict]:
    """resource.k8s.io Device entries for this node's pool."""
    chips = lib.enumerate_chips()
    clique_id = resolve_clique_id(chips)
    topo = lib.slice_topology()
    devices = [
        {
            "name": daemon_name(),
            "attributes": {
                "type": {"string": TYPE_DAEMON},
                "id": {"int": 0},
                "cliqueID": {"string": clique_id},
                "numHosts": {"int": topo.num_hosts},
                "hostIndex": {"int": topo.host_index},
            },
            "capacity": {},
        }
    ]
    for i in range(CHANNEL_COUNT):
        devices.append(
            {
                "name": channel_name(i),
                "attributes": {
                    "type": {"string": TYPE_CHANNEL},
                    "id": {"int": i},
                    "cliqueID": {"string": clique_id},
                },
                "capacity": {},
            }
        )
    return devices

"""The libtpu worker-bootstrap env contract for multi-host slices.

On a GKE-style TPU node (no TPU metadata server env), libtpu forms its ICI
mesh from a small env contract — the same one the GKE TPU device plugin
emits for multi-host podslices:

- ``TPU_WORKER_ID``                this host's index within the slice
- ``TPU_WORKER_HOSTNAMES``         all workers' hostnames, worker-id order
- ``TPU_SKIP_MDS_QUERY=true``      don't ask the metadata server for topology
- ``TPU_HOST_BOUNDS``              the host grid of the slice, "x,y,z"
- ``TPU_CHIPS_PER_HOST_BOUNDS``    each host's chip block, "x,y,z"

``jax.distributed.initialize`` (DCN rendezvous) is orthogonal: without this
contract a multi-host claim would rendezvous at the JAX level and then fail
to form the libtpu mesh.  This driver replaces the GKE TPU device plugin,
so emitting the contract is its job — the analog of the reference injecting
the IMEX channel device NCCL needs (compute-domain-kubelet-plugin/
device_state.go:466-514): inject what the comm layer needs, with the grant.

The worker hostnames are the per-domain daemon's stable DNS names
(cddaemon/dnsnames.py): one daemon per slice host, host-networked, kept
resolvable by the daemon's /etc/hosts machinery — so they name exactly the
TPU hosts libtpu must reach, in clique-index order, and survive daemon pod
churn the same way the slice-watch peer list does.

**Reachability contract** (the reason multi-host channel workloads must be
host-networked): the daemon DNS names resolve to NODE IPs.  libtpu's
inter-worker mesh-bootstrap servers bind inside the WORKLOAD pod's network
namespace, and unlike the jax.distributed coordinator (proxied on the
daemon's port, cddaemon/coordproxy.py) nothing forwards libtpu's ports.
With ``hostNetwork: true`` (the GKE multi-host podslice contract) pod IP ==
node IP and the names land on the worker's own sockets; with pod networking
they land on the node where nothing listens and mesh formation hangs until
libtpu's init timeout.  cdplugin/state.py therefore refuses multi-host
channel grants to pod-networked pods unless the pod overrides the hostnames
with names that resolve to the workload pods themselves (headless-service
style, the ``tpu.google.com/worker-hostnames`` annotation → the
``hostnames`` parameter of :func:`worker_env`).
"""

from __future__ import annotations

import logging

from tpudra.cddaemon.dnsnames import dns_name
from tpudra.devicelib.base import TpuChip
from tpudra.devicelib.topology import GENERATIONS, SliceTopology, host_origin

logger = logging.getLogger(__name__)


def slice_env(topo: SliceTopology, chips: list[TpuChip]) -> dict[str, str]:
    """The slice-geometry half of the grant env: the full ICI mesh shape
    and this host's block origin within it, straight from the device
    library's topology model.  Together with TPUDRA_NUM_HOSTS /
    TPUDRA_HOST_INDEX / TPUDRA_COORDINATOR (cdplugin/state.py), a rank
    learns its coordinator address, process count, and mesh position from
    the claim alone — no metadata server, no out-of-band config
    (ROADMAP item 2's "claim is the whole contract" requirement).

    TPUDRA_HOST_COORDS is emitted only when a generation spec is
    available to place the host block (same degraded-node rule as
    host_bounds: a chipless node keeps worker identity, loses footprint).
    """
    env = {
        "TPUDRA_MESH_SHAPE": ",".join(str(v) for v in topo.mesh_shape),
    }
    spec = GENERATIONS.get(chips[0].generation) if chips else None
    if spec is not None:
        env["TPUDRA_HOST_COORDS"] = ",".join(
            str(v) for v in host_origin(spec, topo.host_index)
        )
    return env


def host_bounds(
    topo: SliceTopology, chips: list[TpuChip]
) -> tuple[str, str] | None:
    """(TPU_HOST_BOUNDS, TPU_CHIPS_PER_HOST_BOUNDS) for this slice, or None
    when the node exposes no chips to read a generation from (a degraded
    node still gets worker identity, just no footprint).

    The host grid is the slice mesh divided elementwise by the generation's
    per-host chip block: v5p-16 = mesh (2,2,2) / host block (2,2,1) → hosts
    (1,1,2).  A non-divisible mesh (never true of real slices) degrades to
    stacking all hosts along z, with a warning.
    """
    if not chips:
        return None
    spec = GENERATIONS.get(chips[0].generation)
    if spec is None:
        return None
    hb = spec.host_bounds
    mesh = topo.mesh_shape
    if all(m % b == 0 for m, b in zip(mesh, hb)) and (
        (mesh[0] // hb[0]) * (mesh[1] // hb[1]) * (mesh[2] // hb[2])
        == topo.num_hosts
    ):
        grid = (mesh[0] // hb[0], mesh[1] // hb[1], mesh[2] // hb[2])
    else:
        logger.warning(
            "slice mesh %s is not a whole number of %s host blocks %s; "
            "falling back to a 1x1x%d host grid",
            mesh, spec.name, hb, topo.num_hosts,
        )
        grid = (1, 1, topo.num_hosts)
    fmt = lambda t: ",".join(str(v) for v in t)  # noqa: E731
    return fmt(grid), fmt(hb)


def worker_env(
    topo: SliceTopology,
    chips: list[TpuChip],
    hostnames: list[str] | None = None,
) -> dict[str, str]:
    """The full contract for one host of the granted slice.

    ``hostnames`` overrides the default daemon DNS names with caller-chosen
    worker names in worker-id order (the pod-networked escape hatch — see
    the module docstring's reachability contract)."""
    if hostnames is not None and len(hostnames) != topo.num_hosts:
        raise ValueError(
            f"{len(hostnames)} worker hostnames for {topo.num_hosts} hosts"
        )
    env = {
        "TPU_WORKER_ID": str(topo.host_index),
        "TPU_WORKER_HOSTNAMES": ",".join(
            hostnames if hostnames is not None
            else [dns_name(i) for i in range(topo.num_hosts)]
        ),
        "TPU_SKIP_MDS_QUERY": "true",
    }
    bounds = host_bounds(topo, chips)
    if bounds is not None:
        env["TPU_HOST_BOUNDS"] = bounds[0]
        env["TPU_CHIPS_PER_HOST_BOUNDS"] = bounds[1]
    return env

"""Checkpointed prepare/unprepare for ComputeDomain claims.

The analog of compute-domain-kubelet-plugin/device_state.go:147-673 — the
same idempotent checkpoint skeleton as the TPU plugin, with CD-specific
config application:

- **channel** (applyComputeDomainChannelConfig, :466): assert the CD lives in
  the claim's namespace, label the node (summoning the DaemonSet), then gate
  on this node being Ready in the CD status — raising a *retryable* error
  until it is, which holds the workload pod in ContainerCreating while the
  domain forms — and finally inject the channel device node(s) and slice
  topology env.  Channel conflicts across claims are refused from the
  checkpoint (assertImexChannelNotAllocated analog, :646).
- **daemon** (applyComputeDomainDaemonConfig, :516): create the per-domain
  settings dir, inject clique identity + rendezvous env and the config-dir
  mount.
"""

from __future__ import annotations

import logging
import os
import time

from tpudra import COMPUTE_DOMAIN_DRIVER_NAME, trace
from tpudra.api import DecodeError, decode_config
from tpudra.api.computedomain import (
    CHANNEL_ALLOCATION_MODE_ALL,
    ComputeDomainChannelConfig,
    ComputeDomainDaemonConfig,
)
from tpudra.cdplugin import CHANNEL_COUNT, allocatable as alloc
from tpudra.cdplugin.computedomain import ComputeDomainManager
from tpudra.devicelib import DeviceLib
from tpudra.plugin.cdi import CDIHandler, ContainerEdits
from tpudra.plugin.checkpoint import (
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    Checkpoint,
    CheckpointManager,
    PreparedClaim,
    PreparedDevice,
    PreparedDeviceGroup,
)
from tpudra.plugin.device_state import (
    PermanentError,
    PrepareError,
    PreparedDeviceResult,
    _claim_identity,
    _crashpoint,
)

logger = logging.getLogger(__name__)

#: Pod annotation overriding TPU_WORKER_HOSTNAMES for pod-networked
#: multi-host workloads: comma-separated worker names in worker-id order
#: that resolve to the workload pods themselves (headless-service style).
WORKER_HOSTNAMES_ANNOTATION = "tpu.google.com/worker-hostnames"


def _allocation_results(claim: dict) -> list[dict]:
    results = (
        claim.get("status", {})
        .get("allocation", {})
        .get("devices", {})
        .get("results", [])
    )
    return [r for r in results if r.get("driver") == COMPUTE_DOMAIN_DRIVER_NAME]


def _opaque_config(claim: dict):
    """CD claims carry exactly one opaque config (channel or daemon)."""
    entries = (
        claim.get("status", {})
        .get("allocation", {})
        .get("devices", {})
        .get("config", [])
    )
    decoded = []
    for entry in entries:
        opaque = entry.get("opaque")
        if not opaque or opaque.get("driver") != COMPUTE_DOMAIN_DRIVER_NAME:
            continue
        try:
            config = decode_config(opaque.get("parameters", {}), strict=True)
            config.normalize()
            config.validate()
        except (DecodeError, ValueError) as e:
            raise PermanentError(f"invalid opaque config: {e}") from e
        decoded.append(config)
    if not decoded:
        raise PermanentError("compute-domain claim has no opaque config")
    if len(decoded) > 1:
        raise PermanentError("compute-domain claim has multiple opaque configs")
    return decoded[0]


def _teardown_targets(claim: PreparedClaim | None) -> tuple[str, set]:
    """(domain uid, device kinds) a claim's teardown must touch — from the
    recorded devices for a completed claim, plus the intent stamped at
    PrepareStarted for one crashed mid-prepare (whose devices were never
    recorded).  Pure: safe both outside and inside a checkpoint RMW."""
    if claim is None:
        return "", set()
    domain_uid = ""
    kinds: set = set()
    if claim.status == PREPARE_STARTED:
        for g in claim.groups:
            domain_uid = g.config_state.get("domainUID", domain_uid)
            ctype = g.config_state.get("configType", "")
            if ctype == "channel":
                kinds.add(alloc.TYPE_CHANNEL)
            elif ctype == "daemon":
                kinds.add(alloc.TYPE_DAEMON)
    for dev in claim.all_devices():
        domain_uid = dev.attributes.get("domainUID", domain_uid)
        kinds.add(dev.type)
    return domain_uid, kinds


class ComputeDomainDeviceState:
    def __init__(
        self,
        devicelib: DeviceLib,
        cdi: CDIHandler,
        checkpoints: CheckpointManager,
        cd_manager: ComputeDomainManager,
        node_name: str,
    ):
        self._lib = devicelib
        self._cdi = cdi
        self._cp = checkpoints
        self._cdm = cd_manager
        self._node_name = node_name

    # ------------------------------------------------------------------ API

    def prepare(self, claim: dict) -> list[PreparedDeviceResult]:
        t0 = time.monotonic()
        uid, namespace, name = _claim_identity(claim)
        results = _allocation_results(claim)
        if not results:
            raise PermanentError(
                f"claim {namespace}/{name}:{uid} has no allocation for "
                f"{COMPUTE_DOMAIN_DRIVER_NAME}"
            )
        config = _opaque_config(claim)
        # Captured on the CALLING thread: the mutator closures run on
        # whichever thread leads the group commit (tpudra/trace.py).
        bind_traceparent = trace.current_traceparent() or None

        cached: list[PreparedDeviceResult] = []

        def start(cp: Checkpoint) -> None:
            existing = cp.prepared_claims.get(uid)
            if existing is not None and existing.status == PREPARE_COMPLETED:
                cached.extend(self._results_from(existing))
                return
            if isinstance(config, ComputeDomainChannelConfig):
                self._assert_channels_free(cp, uid, results, config)
            # Record the claim's intent (domain + config kind) before any
            # side effect, so a crash mid-prepare leaves enough in the
            # checkpoint for the PrepareStarted rollback branch in
            # unprepare (the TPU plugin's unpreparePartiallyPrepared
            # discipline, device_state.go:482).
            intent = {
                "domainUID": getattr(config, "domain_id", ""),
                "configType": (
                    "channel"
                    if isinstance(config, ComputeDomainChannelConfig)
                    else "daemon"
                ),
            }
            cp.prepared_claims[uid] = PreparedClaim(
                uid=uid,
                namespace=namespace,
                name=name,
                status=PREPARE_STARTED,
                traceparent=bind_traceparent,
                groups=[PreparedDeviceGroup(devices=[], config_state=intent)],
            )

        self._cp.mutate(start, touched=[uid])
        if cached:
            return cached
        _crashpoint("post-prepare-started")

        try:
            with trace.start_span("bind.config-apply", attrs={"claim": uid}):
                if isinstance(config, ComputeDomainChannelConfig):
                    group = self._apply_channel_config(
                        uid, namespace, config, results, claim
                    )
                elif isinstance(config, ComputeDomainDaemonConfig):
                    group = self._apply_daemon_config(uid, config, results)
                else:
                    raise PermanentError(
                        f"{type(config).__name__} belongs to the TPU plugin"
                    )
        except Exception:
            # Leave the claim in PrepareStarted: kubelet retries (the
            # readiness-gating path relies on this, §3.3).
            raise

        devices, edits = group
        # Side effects so far: node label + per-domain host dir (channel) or
        # daemon settings dir (daemon) — the CD plugin's "hardware mutation".
        _crashpoint("post-mutate")
        with trace.start_span("bind.cdi-write", attrs={"claim": uid}):
            self._cdi.create_claim_spec_file(
                uid, {d.canonical_name: ContainerEdits() for d in devices}, edits
            )
        _crashpoint("post-cdi")

        def complete(cp: Checkpoint) -> None:
            cp.prepared_claims[uid] = PreparedClaim(
                uid=uid,
                namespace=namespace,
                name=name,
                status=PREPARE_COMPLETED,
                traceparent=bind_traceparent,
                groups=[PreparedDeviceGroup(devices=devices, config_state={})],
            )

        self._cp.mutate(complete, touched=[uid])
        _crashpoint("post-completed")
        logger.info(
            "prepared CD claim %s/%s:%s t_prep=%.4fs",
            namespace, name, uid, time.monotonic() - t0,
        )
        return [
            PreparedDeviceResult(
                request_names=d.request_names,
                pool_name=d.pool_name,
                device_name=d.canonical_name,
                cdi_device_ids=d.cdi_device_ids,
            )
            for d in devices
        ]

    def unprepare(self, claim_uid: str) -> None:
        """Phased like the TPU plugin's unprepare (docs/bind-path.md): the
        side effects — CDI spec delete, daemon-settings teardown, node-label
        GC — run OUTSIDE the checkpoint RMW.  The claim record stays durable
        until the final pure RMW drops it, so a crash anywhere in the
        effects re-runs them on retry (all idempotent); the RMW itself only
        moves checkpoint state (RMW-PURITY)."""
        # Phase 1: snapshot the record (plain read, no cp.lock held after).
        claim = self._cp.read().prepared_claims.get(claim_uid)
        domain_uid, kinds = _teardown_targets(claim)
        if claim is not None and claim.status == PREPARE_STARTED:
            logger.info(
                "rolling back partially prepared CD claim %s (domain %s)",
                claim_uid, domain_uid or "<unknown>",
            )

        # Phase 2: effects, while the durable record still marks the claim.
        self._cdi.delete_claim_spec_file(claim_uid)
        if domain_uid and alloc.TYPE_DAEMON in kinds:
            self._cdm.cleanup_daemon_settings(domain_uid)

        # Phase 3: ONE pure RMW — drop the record and decide the label's
        # fate from the post-drop view.  The cp.lock makes the scan
        # consistent; what makes the decide-then-remove *sequence* safe
        # against a concurrent channel prepare re-labeling the node between
        # this RMW and the removal below is the CD driver's node pu.lock,
        # held across the whole prepare/unprepare on every path (kubelet
        # RPCs and the GC's _unprepare_locked alike).
        drop_label = False

        def drop(cp: Checkpoint) -> None:
            nonlocal drop_label
            cp.prepared_claims.pop(claim_uid, None)
            if not domain_uid or alloc.TYPE_CHANNEL not in kinds:
                return
            # The node label is owned by the *channel* path
            # (_apply_channel_config is the only place that sets it), so
            # only channel claims — completed ones via their devices,
            # in-flight ones via their intent stamp — keep it alive.
            # Counting daemon claims here would leak the label: the
            # daemon unprepare path never removes it.
            still_used = any(
                d.type == alloc.TYPE_CHANNEL
                and d.attributes.get("domainUID") == domain_uid
                for other in cp.prepared_claims.values()
                for d in other.all_devices()
            ) or any(
                g.config_state.get("configType") == "channel"
                and g.config_state.get("domainUID") == domain_uid
                for other in cp.prepared_claims.values()
                for g in other.groups
            )
            drop_label = not still_used

        self._cp.mutate(drop, touched=[claim_uid])

        # Label GC after the drop, best-effort as ever: a crash in the gap
        # leaks the label only until the controller's periodic
        # sweep_stale_labels (controller/node.py) or the CD's own deletion
        # reconciles it.
        if drop_label:
            try:
                self._cdm.remove_node_label(domain_uid)
            except Exception as e:  # noqa: BLE001 — label GC is best-effort
                logger.warning("removing CD node label: %s", e)

    def prepared_claim_uids(self) -> dict[str, tuple[str, str, str]]:
        cp = self._cp.read_view()
        return {
            uid: (c.namespace, c.name, c.status)
            for uid, c in cp.prepared_claims.items()
        }

    # ----------------------------------------------------------- internals

    def _results_from(self, claim: PreparedClaim) -> list[PreparedDeviceResult]:
        return [
            PreparedDeviceResult(
                request_names=d.request_names,
                pool_name=d.pool_name,
                device_name=d.canonical_name,
                cdi_device_ids=d.cdi_device_ids,
            )
            for g in claim.groups
            for d in g.devices
        ]

    def _assert_channels_free(
        self,
        cp: Checkpoint,
        uid: str,
        results: list[dict],
        config: ComputeDomainChannelConfig,
    ) -> None:
        """A channel granted to one claim may not be re-granted to another on
        this node (reference :646).  In All mode the claim takes the whole
        channel space of its domain."""
        wanted: set[tuple[str, int]] = set()
        for r in results:
            kind, cid = alloc.parse_device_name(r.get("device", ""))
            if kind == alloc.TYPE_CHANNEL:
                wanted.add((config.domain_id, cid))
        for other_uid, other in cp.prepared_claims.items():
            if other_uid == uid:
                continue
            for dev in other.all_devices():
                if dev.type != alloc.TYPE_CHANNEL:
                    continue
                key = (dev.attributes.get("domainUID", ""), int(dev.attributes.get("channelID", -1)))
                if key in wanted:
                    raise PermanentError(
                        f"channel {key[1]} of domain {key[0]} already prepared "
                        f"for claim {other.namespace}/{other.name}:{other_uid}"
                    )

    def _apply_channel_config(
        self,
        uid: str,
        namespace: str,
        config: ComputeDomainChannelConfig,
        results: list[dict],
        claim: dict,
    ) -> tuple[list[PreparedDevice], ContainerEdits]:
        try:
            self._cdm.assert_in_namespace(config.domain_id, namespace)
        except LookupError as e:
            raise PrepareError(str(e)) from e  # CD may not have synced yet
        except PermissionError as e:
            raise PermanentError(str(e)) from e
        self._cdm.add_node_label(config.domain_id)
        if not self._cdm.node_ready_in_domain(config.domain_id):
            raise PrepareError(
                f"ComputeDomain {config.domain_id} is not ready on node "
                f"{self._node_name} yet"
            )

        topo = self._lib.slice_topology()
        chips = self._lib.enumerate_chips()
        from tpudra.cdplugin import libtpuenv

        # The slice geometry rides the claim itself — recorded on every
        # prepared device (the checkpointed "what was granted" record) and
        # injected as env below, so each rank of a gang learns its mesh
        # position from the grant alone (ROADMAP item 2; the reference's
        # clusterUUID/cliqueID fabric attributes, nvlib.go:201-356).
        geometry = libtpuenv.slice_env(topo, chips)
        topo_attrs = {
            "numHosts": str(topo.num_hosts),
            "hostIndex": str(topo.host_index),
            "meshShape": geometry["TPUDRA_MESH_SHAPE"],
        }
        if "TPUDRA_HOST_COORDS" in geometry:
            topo_attrs["hostCoords"] = geometry["TPUDRA_HOST_COORDS"]

        channel_ids: list[int] = []
        devices: list[PreparedDevice] = []
        for r in results:
            kind, cid = alloc.parse_device_name(r.get("device", ""))
            if kind != alloc.TYPE_CHANNEL:
                raise PermanentError(
                    f"channel config applied to non-channel device {r.get('device')}"
                )
            channel_ids.append(cid)
            devices.append(
                PreparedDevice(
                    canonical_name=r["device"],
                    type=alloc.TYPE_CHANNEL,
                    pool_name=self._node_name,
                    request_names=[r["request"]] if r.get("request") else [],
                    cdi_device_ids=[self._cdi.qualified_device_id(uid, r["device"])],
                    attributes={
                        "domainUID": config.domain_id,
                        "channelID": str(cid),
                        **topo_attrs,
                    },
                )
            )
        granted = (
            list(range(CHANNEL_COUNT))
            if config.allocation_mode == CHANNEL_ALLOCATION_MODE_ALL
            else sorted(channel_ids)
        )
        worker_hostnames = self._worker_hostnames_policy(namespace, claim, topo)
        from tpudra.cdplugin.computedomain import DEFAULT_COORDINATOR_PORT
        from tpudra.cddaemon.dnsnames import dns_name

        # The per-domain host dir is shared three ways: the daemon pod
        # mounts it (daemon settings), and every workload pod gets it too so
        # host 0 can register its live jax.distributed coordinator endpoint
        # for the daemon's proxy to forward to (cddaemon/coordproxy.py).
        domain_dir = self._cdm.domain_dir(config.domain_id)
        os.makedirs(domain_dir, exist_ok=True)
        # The host-0 workload writes its registration here and commonly
        # runs as non-root (securityContext runAsUser); the dir is created
        # by the root plugin, so non-owners must be able to create files.
        # Sticky bit: only the file's owner (or root) may replace/unlink a
        # registration — without it any local pod could silently redirect
        # the daemon proxy (and thus every worker's rendezvous) to an
        # arbitrary endpoint by overwriting the host-0 registration.
        os.chmod(domain_dir, 0o1777)
        cd_dir_mount = "/var/run/tpudra-cd"
        edits = ContainerEdits(
            env=[
                f"TPUDRA_DOMAIN_UID={config.domain_id}",
                "TPUDRA_DOMAIN_CHANNELS=" + ",".join(str(i) for i in granted),
                f"TPUDRA_NUM_HOSTS={topo.num_hosts}",
                f"TPUDRA_HOST_INDEX={topo.host_index}",
                f"TPUDRA_CLIQUE_ID={alloc.resolve_clique_id(chips)}",
                # DCN rendezvous from the grant alone: workloads join
                # jax.distributed at the index-0 daemon's stable DNS name
                # (ClaimEnv.initialize_distributed).  Daemon claims get the
                # same value via their settings env (computedomain.py:118).
                # Host 0 binds locally instead and registers through
                # TPUDRA_CD_DIR; the daemon proxies the stable name to it.
                f"TPUDRA_COORDINATOR={dns_name(0)}:{DEFAULT_COORDINATOR_PORT}",
                f"TPUDRA_CD_DIR={cd_dir_mount}",
            ]
            # Trace propagation into the workload (tpudra/trace.py): the
            # bind's active span rides the grant env, so every worker rank
            # of the gang emits child spans of the member bind that
            # granted it — the controller→plugin→rank chain trace_report
            # reconstructs.  Absent when the bind ran untraced.
            + (
                [f"{trace.TRACEPARENT_ENV}={tp}"]
                if (tp := trace.current_traceparent())
                else []
            )
            # Slice geometry (mesh shape + this host's block origin): the
            # same values recorded on the prepared devices above, so env
            # and checkpoint attributes can never drift apart.
            + [f"{k}={v}" for k, v in sorted(geometry.items())]
            # The libtpu worker-bootstrap contract (TPU_WORKER_ID /
            # TPU_WORKER_HOSTNAMES / TPU_SKIP_MDS_QUERY / host+chip bounds):
            # jax.distributed rendezvous above is necessary but not
            # sufficient — libtpu itself forms the ICI mesh from these
            # (cdplugin/libtpuenv.py; GKE TPU device-plugin contract).
            + [
                f"{k}={v}"
                for k, v in sorted(
                    libtpuenv.worker_env(
                        topo, chips, hostnames=worker_hostnames
                    ).items()
                )
            ],
            device_nodes=[
                self._cdi.host_path(alloc.channel_dev_path(i)) for i in granted
            ],
            mounts=[(domain_dir, cd_dir_mount)],
        )
        return devices, edits

    def _worker_hostnames_policy(
        self, namespace: str, claim: dict, topo
    ) -> list[str] | None:
        """Enforce the TPU_WORKER_HOSTNAMES reachability contract
        (libtpuenv.py module docstring) for multi-host channel grants.

        Returns override hostnames from the consuming pod's
        ``tpu.google.com/worker-hostnames`` annotation (headless-service
        style, worker-id order), or None to use the daemon DNS names.
        Raises PermanentError when the consuming pod is pod-networked with
        no override — libtpu mesh formation would hang for ~300 s and fail
        opaquely; refusing at prepare puts the actionable message on the
        claim instead.
        """
        if topo.num_hosts <= 1:
            return None  # no inter-host mesh to form
        pods = self._consuming_pods(namespace, claim)
        if not pods:
            # reservedFor not set (conformance suites, manual prepares):
            # nothing to validate against — keep the default contract.
            logger.warning(
                "multi-host channel claim %s has no resolvable consuming pod; "
                "cannot validate the hostNetwork contract",
                claim.get("metadata", {}).get("name", ""),
            )
            return None
        # A claim can be reserved by several consumers (DRA allows 32); the
        # grant env is one per claim, so every consumer is validated and an
        # override must be unanimous.
        annotations = {
            pod.get("metadata", {})
            .get("annotations", {})
            .get(WORKER_HOSTNAMES_ANNOTATION, "")
            for pod in pods
        }
        annotations.discard("")
        if len(annotations) > 1:
            raise PermanentError(
                "consuming pods of claim "
                f"{claim.get('metadata', {}).get('name')} carry conflicting "
                f"{WORKER_HOSTNAMES_ANNOTATION} annotations "
                f"{sorted(annotations)} — the grant env is shared, so all "
                "consumers must agree"
            )
        if annotations:
            annotation = annotations.pop()
            names = [n.strip() for n in annotation.split(",") if n.strip()]
            if len(names) != topo.num_hosts:
                raise PermanentError(
                    f"{WORKER_HOSTNAMES_ANNOTATION} on the consuming pod(s) "
                    f"of claim {claim.get('metadata', {}).get('name')} lists "
                    f"{len(names)} hostnames for a {topo.num_hosts}-host slice"
                )
            return names
        for pod in pods:
            if not pod.get("spec", {}).get("hostNetwork"):
                raise PermanentError(
                    "multi-host ComputeDomain channel claim consumed by "
                    f"pod-networked pod {namespace}/{pod['metadata'].get('name')}: "
                    "TPU_WORKER_HOSTNAMES names the host-networked domain daemons "
                    "(node IPs), but libtpu's inter-worker ports bind inside the "
                    "pod network namespace where nothing forwards them — ICI mesh "
                    "formation would hang.  Set hostNetwork: true on the workload "
                    "pod (the GKE multi-host podslice contract), or annotate it "
                    f"with {WORKER_HOSTNAMES_ANNOTATION}=<name0,...> naming each "
                    "worker pod (headless-service style, worker-id order)."
                )
        return None

    def _consuming_pods(self, namespace: str, claim: dict) -> list[dict]:
        """Every pod the scheduler reserved this claim for (resolvable
        ones).  ResourceClaimConsumerReference carries resource (plural) +
        name; only pod consumers have a spec to validate."""
        from tpudra.kube import gvr

        pods = []
        for ref in claim.get("status", {}).get("reservedFor", []):
            if ref.get("resource", "pods") != "pods":
                continue
            name = ref.get("name", "")
            if not name:
                continue
            try:
                pods.append(self._cdm.kube.get(gvr.PODS, name, namespace))
            except Exception:  # noqa: BLE001 — pod may be gone already
                continue
        return pods

    def _apply_daemon_config(
        self, uid: str, config: ComputeDomainDaemonConfig, results: list[dict]
    ) -> tuple[list[PreparedDevice], ContainerEdits]:
        for r in results:
            kind, _ = alloc.parse_device_name(r.get("device", ""))
            if kind != alloc.TYPE_DAEMON:
                raise PermanentError(
                    f"daemon config applied to non-daemon device {r.get('device')}"
                )
        chips = self._lib.enumerate_chips()
        topo = self._lib.slice_topology()
        # Same strict/legacy fabric-error semantics as enumeration: the
        # CLIQUE_ID handed to the daemon must agree with what the published
        # devices advertised (a degraded node must not join a clique).
        clique_id = alloc.resolve_clique_id(chips)
        from tpudra.cdplugin import libtpuenv

        env = self._cdm.prepare_daemon_settings(
            config.domain_id, clique_id, topo.num_hosts, topo.host_index,
            # Worker-bootstrap contract + slice geometry: the daemon's
            # settings record the same mesh env the channel grants inject,
            # so operators read one file for the slice's formation state.
            libtpu_env={
                **libtpuenv.worker_env(topo, chips),
                **libtpuenv.slice_env(topo, chips),
            },
        )
        devices = [
            PreparedDevice(
                canonical_name=r["device"],
                type=alloc.TYPE_DAEMON,
                pool_name=self._node_name,
                request_names=[r["request"]] if r.get("request") else [],
                cdi_device_ids=[self._cdi.qualified_device_id(uid, r["device"])],
                attributes={"domainUID": config.domain_id},
            )
            for r in results
        ]
        from tpudra.cdplugin.computedomain import DAEMON_CD_MOUNT

        edits = ContainerEdits(
            env=[f"{k}={v}" for k, v in sorted(env.items())],
            mounts=[(self._cdm.domain_dir(config.domain_id), DAEMON_CD_MOUNT)],
        )
        return devices, edits

"""ComputeDomain kubelet-plugin driver.

The analog of compute-domain-kubelet-plugin/driver.go: the same two-socket
kubelet gRPC contract as the TPU plugin (tpudra/plugin/grpcserver.py) serving the
compute-domain driver name, ResourceSlice publication of the 2048 channels +
1 daemon device (chunked to the per-slice device cap), and claim fan-in to
the checkpointed CD device state.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass

from tpudra import COMPUTE_DOMAIN_DRIVER_NAME, metrics, trace
from tpudra.cdplugin.allocatable import build_devices
from tpudra.cdplugin.computedomain import ComputeDomainManager
from tpudra.cdplugin.state import ComputeDomainDeviceState
from tpudra.devicelib import DeviceLib
from tpudra.flock import Flock, FlockTimeout
from tpudra.kube.apply import next_pool_generation, publish_slices
from tpudra.kube.client import KubeAPI
from tpudra.plugin.cdi import CDIHandler
from tpudra.plugin.checkpoint import CheckpointManager
from tpudra.plugin.cleanup import CheckpointCleanupManager
from tpudra.plugin.device_state import PermanentError
from tpudra.plugin.grpcserver import PluginSockets, kube_claim_resolver
from tpudra.plugin.resourceslice import MAX_DEVICES_PER_SLICE

logger = logging.getLogger(__name__)

PU_LOCK_TIMEOUT = 10.0


@dataclass
class CDDriverConfig:
    node_name: str
    plugin_dir: str
    registry_dir: str
    cdi_root: str
    driver_root: str = "/"
    # Journaled checkpoint persistence — see tpudra/plugin/driver.py's
    # DriverConfig.journal (same WAL + group-commit layer, same downgrade
    # gate via the clean-shutdown compaction in stop()).
    journal: bool = True


class CDDriver:
    def __init__(self, config: CDDriverConfig, kube: KubeAPI, devicelib: DeviceLib):
        self._config = config
        self._kube = kube
        self._lib = devicelib
        os.makedirs(config.plugin_dir, exist_ok=True)
        self._pu_lock_path = os.path.join(config.plugin_dir, "pu.lock")
        self.cd_manager = ComputeDomainManager(kube, config.node_name, config.plugin_dir)
        self._checkpoints = CheckpointManager(
            config.plugin_dir, journal=config.journal
        )
        self.state = ComputeDomainDeviceState(
            devicelib,
            CDIHandler(config.cdi_root, config.driver_root),
            self._checkpoints,
            self.cd_manager,
            config.node_name,
        )
        self._stop = threading.Event()
        self._sockets = PluginSockets(
            COMPUTE_DOMAIN_DRIVER_NAME,
            config.plugin_dir,
            config.registry_dir,
            prepare=self.prepare_resource_claims,
            unprepare=self.unprepare_resource_claims,
            resolve_claim=kube_claim_resolver(kube),
        )
        # GC teardown goes through the node lock like the kubelet RPC
        # paths: with unprepare's label GC running after its checkpoint RMW
        # (state.py), an unserialized GC unprepare could delete the node
        # label a concurrent channel prepare just set — the pu.lock held
        # across the whole operation is what makes the decide-then-remove
        # sequence atomic against prepares.
        self.cleanup = CheckpointCleanupManager(
            kube, self.state, unprepare=self._unprepare_locked
        )
        # Seeded from live slices so a restart outranks previous publishes.
        self._pool_generation = next_pool_generation(
            kube, config.node_name, config.node_name
        )

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._sockets.start()
        self.cleanup.start(self._stop)
        self.publish_resources()

    def stop(self) -> None:
        self._stop.set()
        self._sockets.stop()
        # Clean-shutdown journal compaction — the downgrade gate (see
        # CheckpointManager.close()).
        self._checkpoints.close()

    @property
    def sockets(self) -> PluginSockets:
        return self._sockets

    # ------------------------------------------------------ prepare/unprepare

    def _pu_lock(self):
        """Fresh Flock per operation — see tpudra/plugin/driver.py: one
        shared instance cannot serve concurrent kubelet RPC threads."""
        # Distinct lock class from the TPU plugin's pu.lock: same file
        # NAME, different plugin_dir/file — collapsing them would let CD
        # runs mark main-driver bind edges as witnessed (and vice versa).
        # witness_id doubles as the static model's ID for this family.
        return Flock(self._pu_lock_path, witness_id="flock:cd-pu.lock")

    def _unprepare_locked(self, uid: str) -> None:
        """Single-claim unprepare under the node lock — the GC's entry
        point, so its teardown (including the post-RMW label removal)
        serializes against kubelet prepare/unprepare RPCs."""
        with self._pu_lock()(timeout=PU_LOCK_TIMEOUT):
            self.state.unprepare(uid)

    def prepare_resource_claims(self, claims: list[dict]) -> dict:
        out: dict[str, dict] = {}
        for claim in claims:
            uid = claim.get("metadata", {}).get("uid", "")
            t0 = time.monotonic()
            try:
                with trace.start_span(
                    "plugin.prepare",
                    attrs={"node": self._config.node_name, "claims": 1},
                ), self._pu_lock()(timeout=PU_LOCK_TIMEOUT):
                    devices = self.state.prepare(claim)
                out[uid] = {
                    "devices": [
                        {
                            "requestNames": d.request_names,
                            "poolName": d.pool_name,
                            "deviceName": d.device_name,
                            "cdiDeviceIDs": d.cdi_device_ids,
                        }
                        for d in devices
                    ]
                }
                logger.info("t_prep=%.4fs cd-claim=%s", time.monotonic() - t0, uid)
            except FlockTimeout as e:
                metrics.PREPARE_ERRORS.labels(COMPUTE_DOMAIN_DRIVER_NAME).inc()
                out[uid] = {"error": f"node prepare lock: {e}", "permanent": False}
            except Exception as e:  # noqa: BLE001 — per-claim fault barrier
                logger.info("CD prepare %s: %s", uid, e)
                metrics.PREPARE_ERRORS.labels(COMPUTE_DOMAIN_DRIVER_NAME).inc()
                out[uid] = {"error": str(e), "permanent": isinstance(e, PermanentError)}
            finally:
                metrics.PREPARE_SECONDS.labels(COMPUTE_DOMAIN_DRIVER_NAME).observe(
                    time.monotonic() - t0
                )
        return {"claims": out}

    def unprepare_resource_claims(self, claims: list[dict]) -> dict:
        out: dict[str, dict] = {}
        for ref in claims:
            uid = ref.get("uid") or ref.get("metadata", {}).get("uid", "")
            t0 = time.monotonic()
            try:
                with trace.start_span(
                    "plugin.unprepare",
                    attrs={"node": self._config.node_name, "claims": 1},
                ), self._pu_lock()(timeout=PU_LOCK_TIMEOUT):
                    self.state.unprepare(uid)
                out[uid] = {}
            except Exception as e:  # noqa: BLE001
                logger.exception("CD unprepare failed for claim %s", uid)
                out[uid] = {"error": str(e)}
            finally:
                metrics.UNPREPARE_SECONDS.labels(COMPUTE_DOMAIN_DRIVER_NAME).observe(
                    time.monotonic() - t0
                )
        return {"claims": out}

    # ---------------------------------------------------------- publication

    def publish_resources(self) -> list[dict]:
        devices = build_devices(self._lib)
        chunks = [
            devices[i : i + MAX_DEVICES_PER_SLICE]
            for i in range(0, len(devices), MAX_DEVICES_PER_SLICE)
        ]
        slices = []
        for i, chunk in enumerate(chunks):
            slices.append(
                {
                    "apiVersion": "resource.k8s.io/v1",
                    "kind": "ResourceSlice",
                    "metadata": {
                        "name": f"{self._config.node_name}-{COMPUTE_DOMAIN_DRIVER_NAME}-{i}"
                    },
                    "spec": {
                        "driver": COMPUTE_DOMAIN_DRIVER_NAME,
                        "nodeName": self._config.node_name,
                        "pool": {
                            "name": self._config.node_name,
                            "generation": self._pool_generation,
                            "resourceSliceCount": len(chunks),
                        },
                        "devices": chunk,
                    },
                }
            )
        self._pool_generation += 1
        publish_slices(
            self._kube,
            slices,
            self._config.node_name,
            f"{self._config.node_name}-{COMPUTE_DOMAIN_DRIVER_NAME}-",
        )
        metrics.SLICE_PUBLISH_TOTAL.labels(COMPUTE_DOMAIN_DRIVER_NAME).inc()
        logger.info("published %d CD ResourceSlice(s)", len(slices))
        return slices

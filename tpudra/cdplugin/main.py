"""ComputeDomain kubelet plugin binary
(the cmd/compute-domain-kubelet-plugin analog)."""

from __future__ import annotations

import argparse
import logging

from tpudra.flags import (
    add_common_flags,
    env_default,
    install_stop_handlers,
    make_device_lib,
    make_kube_client_from_args,
    setup_common,
)

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("compute-domain-kubelet-plugin")
    add_common_flags(p)
    p.add_argument("--node-name", default=env_default("NODE_NAME"), required=not env_default("NODE_NAME"))
    p.add_argument(
        "--plugin-dir",
        default=env_default("PLUGIN_DIR", "/var/lib/kubelet/plugins/compute-domain.tpu.google.com"),
    )
    p.add_argument(
        "--registry-dir",
        default=env_default("REGISTRY_DIR", "/var/lib/kubelet/plugins_registry"),
    )
    p.add_argument("--cdi-root", default=env_default("CDI_ROOT", "/var/run/cdi"))
    p.add_argument("--driver-root", default=env_default("DRIVER_ROOT", "/"))
    p.add_argument(
        "--device-backend", default=env_default("DEVICE_BACKEND", "native"),
        choices=["mock", "native"],
    )
    p.add_argument("--tpuinfo-config", default=env_default("TPUINFO_CONFIG"))
    p.add_argument(
        "--healthcheck-port", type=int,
        default=int(env_default("HEALTHCHECK_PORT", "-1")),
    )
    p.add_argument(
        "--no-journal",
        action="store_true",
        default=env_default("NO_JOURNAL", "").lower() == "true",
        help="disable the append-only checkpoint journal (see the TPU "
        "plugin's flag: full-snapshot writes per mutation, the "
        "mixed-version escape hatch) [NO_JOURNAL]",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_common(args)

    from tpudra.cdplugin.driver import CDDriver, CDDriverConfig
    from tpudra.plugin.health import Healthcheck

    kube = make_kube_client_from_args(args)
    lib = make_device_lib(args.device_backend, args.tpuinfo_config)
    driver = CDDriver(
        CDDriverConfig(
            node_name=args.node_name,
            plugin_dir=args.plugin_dir,
            registry_dir=args.registry_dir,
            cdi_root=args.cdi_root,
            driver_root=args.driver_root,
            journal=not args.no_journal,
        ),
        kube,
        lib,
    )
    # Handlers go in before driver.start() publishes sockets/slices — see
    # plugin/main.py; this main had the same SIGTERM default-disposition
    # window and the system test hit it about one run in three.
    stop = install_stop_handlers()
    hc = None
    try:
        driver.start()
        if args.healthcheck_port >= 0:
            hc = Healthcheck(driver.sockets, port=args.healthcheck_port)
            hc.start()
        logger.info("compute-domain-kubelet-plugin up on node %s", args.node_name)
        stop.wait()
    finally:
        if hc is not None:
            hc.stop()
        driver.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

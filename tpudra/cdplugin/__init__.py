"""ComputeDomain kubelet plugin (driver name ``compute-domain.tpu.google.com``).

The analog of cmd/compute-domain-kubelet-plugin/: advertises 2048 abstract
channel devices plus one daemon device per node, and prepares claims against
them:

- **channel** claims (user workloads): label the node to attract the CD's
  DaemonSet ("CD follows workload"), gate on domain readiness — the claim
  retries, holding the pod in ContainerCreating, until every host in the
  slice has a Ready daemon — then inject the channel device + slice
  topology env.
- **daemon** claims (the DaemonSet pod itself): create the per-CD config
  dir, inject the clique identity and coordination env.
"""

CHANNEL_COUNT = 2048  # reference nvlib.go:358-361

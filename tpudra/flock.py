"""Cross-process file lock built on ``flock(2)``.

The analog of the reference's pkg/flock/flock.go:70: a polling, non-blocking
flock wrapper with a timeout.  Crash-safe by construction — the kernel releases
the lock when the fd closes, so a crashed holder never wedges the node.  Guards
the node-global prepare/unprepare lock (``pu.lock``) and the checkpoint
read-mutate-write lock (``cp.lock``) across multiple driver processes on one
node (reference gpu-kubelet-plugin/driver.go:44,341, device_state.go:555).
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import os
import time


class FlockTimeout(TimeoutError):
    pass


class Flock:
    def __init__(self, path: str, poll_interval: float = 0.01):
        self._path = path
        self._poll_interval = poll_interval
        self._fd: int | None = None

    @property
    def path(self) -> str:
        return self._path

    def acquire(self, timeout: float | None = None) -> None:
        """Acquire the exclusive lock, polling every ``poll_interval`` seconds.

        Raises FlockTimeout if the lock cannot be acquired within ``timeout``
        seconds (None = wait forever).
        """
        if self._fd is not None:
            raise RuntimeError(f"lock {self._path} already held by this object")
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    self._fd = fd
                    return
                except OSError as e:
                    if e.errno not in (errno.EAGAIN, errno.EACCES):
                        raise
                if deadline is not None and time.monotonic() >= deadline:
                    raise FlockTimeout(
                        f"timeout acquiring lock {self._path} after {timeout}s"
                    )
                time.sleep(self._poll_interval)
        except BaseException:
            if self._fd is None:
                os.close(fd)
            raise

    def release(self) -> None:
        if self._fd is None:
            return
        fd, self._fd = self._fd, None
        # Closing the fd releases the flock; explicit unlock first for clarity.
        with contextlib.suppress(OSError):
            fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)

    @property
    def held(self) -> bool:
        return self._fd is not None

    @contextlib.contextmanager
    def __call__(self, timeout: float | None = None):
        self.acquire(timeout=timeout)
        try:
            yield self
        finally:
            self.release()

    def __enter__(self) -> "Flock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

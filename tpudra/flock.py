"""Cross-process file lock built on ``flock(2)``.

The analog of the reference's pkg/flock/flock.go:70: a polling, non-blocking
flock wrapper with a timeout.  Crash-safe by construction — the kernel releases
the lock when the fd closes, so a crashed holder never wedges the node.  Guards
the node-global prepare/unprepare lock (``pu.lock``) and the checkpoint
read-mutate-write lock (``cp.lock``) across multiple driver processes on one
node (reference gpu-kubelet-plugin/driver.go:44,341, device_state.go:555).
"""

from __future__ import annotations

import contextlib
import errno
import fcntl
import os
import time

from tpudra import lockwitness, metrics


class FlockTimeout(TimeoutError):
    pass


#: label → resolved FLOCK_WAIT_SECONDS child (labels() is registry-locked).
_WAIT_CHILDREN: dict = {}

#: Directories already ensured by acquire() — the bind path constructs
#: several Flocks per claim and makedirs per acquire was measurable.
_ENSURED_DIRS: set = set()


class Flock:
    def __init__(
        self,
        path: str,
        poll_interval: float = 0.01,
        metric_label: str | None = None,
        witness_id: str | None = None,
    ):
        self._path = path
        self._poll_interval = poll_interval
        self._fd: int | None = None
        # Labelled children are cached per label: .labels() takes a registry
        # lock and the bind path constructs several Flocks per claim.
        # metric_label overrides the file-name label for lock families whose
        # paths are unbounded (one lock file per claim uid).
        label = metric_label or os.path.basename(path) or path
        child = _WAIT_CHILDREN.get(label)
        if child is None:
            child = metrics.FLOCK_WAIT_SECONDS.labels(label)
            _WAIT_CHILDREN[label] = child
        self._wait_metric = child
        # Lock-witness identity (docs/static-analysis.md): families whose
        # file names are unbounded (one per claim uid) pass an explicit
        # class id; everything else is identified by its file name.  The
        # enabled() check runs once per construction so production pays
        # one env lookup, never per-acquire work.
        self._witness_id = witness_id or f"flock:{os.path.basename(path) or path}"
        self._witnessing = lockwitness.enabled()

    @property
    def path(self) -> str:
        return self._path

    def acquire(self, timeout: float | None = None) -> float:
        """Acquire the exclusive lock, polling every ``poll_interval``
        seconds; returns the wall-time this acquire spent waiting (seconds)
        — per-acquire state, so concurrent acquires through distinct Flock
        objects on one path never race on a shared field.

        Raises FlockTimeout if the lock cannot be acquired within ``timeout``
        seconds (None = wait forever).  The wait is also recorded in the
        ``tpudra_flock_wait_seconds`` histogram (labelled by lock file name)
        — including timed-out waits, which are exactly the samples a
        lock-contention investigation needs.
        """
        if self._fd is not None:
            raise RuntimeError(f"lock {self._path} already held by this object")
        parent = os.path.dirname(self._path) or "."
        if parent not in _ENSURED_DIRS:
            os.makedirs(parent, exist_ok=True)
            _ENSURED_DIRS.add(parent)
        try:
            fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
        except FileNotFoundError:
            # The ensured dir was removed since (tests tear down tempdirs).
            os.makedirs(parent, exist_ok=True)
            fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
        t0 = time.monotonic()
        deadline = None if timeout is None else t0 + timeout
        try:
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    self._fd = fd
                    if self._witnessing:
                        lockwitness.note_acquire(self._witness_id)
                    return time.monotonic() - t0
                except OSError as e:
                    if e.errno not in (errno.EAGAIN, errno.EACCES):
                        raise
                if deadline is not None and time.monotonic() >= deadline:
                    raise FlockTimeout(
                        f"timeout acquiring lock {self._path} after {timeout}s"
                    )
                time.sleep(self._poll_interval)
        except BaseException:
            if self._fd is None:
                os.close(fd)
            raise
        finally:
            self._wait_metric.observe(time.monotonic() - t0)

    def release(self) -> None:
        if self._fd is None:
            return
        fd, self._fd = self._fd, None
        if self._witnessing:
            lockwitness.note_release(self._witness_id)
        # Closing the fd releases the flock; explicit unlock first for clarity.
        with contextlib.suppress(OSError):
            fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)

    @property
    def held(self) -> bool:
        return self._fd is not None

    def fileno(self) -> int:
        """The held lock's fd (for fstat-based identity checks by lock
        families whose files may be garbage-collected)."""
        if self._fd is None:
            raise RuntimeError(f"lock {self._path} not held")
        return self._fd

    @contextlib.contextmanager
    def __call__(self, timeout: float | None = None):
        """Scoped acquire; the bound value is this acquire's wait time in
        seconds (``with lock(timeout=...) as waited:``), so callers thread
        the wait into their histograms without shared mutable state."""
        waited = self.acquire(timeout=timeout)
        try:
            yield waited
        finally:
            self.release()

    def __enter__(self) -> "Flock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

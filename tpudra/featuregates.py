"""Versioned feature-gate registry.

The analog of the reference's pkg/featuregates/featuregates.go: a k8s-style
feature-gate system with versioned defaults (a gate's default may change as the
project version advances through alpha/beta/GA), ``--feature-gates=A=true,B=false``
parsing, cross-gate dependency validation, and a ``to_map()`` export used to
propagate gate state into spawned daemon pods via template rendering
(reference featuregates.go:33-211).
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Iterable, Mapping

# ---------------------------------------------------------------------------
# Gate names (reference featuregates.go:33-58, renamed for the TPU domain)
# ---------------------------------------------------------------------------

#: Allow time-slicing settings to be customized on full-chip claims.
TIME_SLICING_SETTINGS = "TimeSlicingSettings"

#: Allow multi-process chip sharing (the MPS analog) settings to be specified.
MULTI_PROCESS_SHARING = "MultiProcessSharing"

#: Use stable DNS names instead of raw IPs for ComputeDomain daemons.
DOMAIN_DAEMONS_WITH_DNS_NAMES = "DomainDaemonsWithDNSNames"

#: Allow TPU PCI functions to be rebound to vfio-pci for VM passthrough.
PASSTHROUGH_SUPPORT = "PassthroughSupport"

#: Device health checking through the tpuinfo library (XID-analog interrupts).
TPU_DEVICE_HEALTH_CHECK = "TPUDeviceHealthCheck"

#: Serve the kubelet-facing v1alpha1.DRAResourceHealth gRPC stream on the
#: plugin socket (beyond-reference: the k8s helper registers this service
#: when a plugin implements it, vendored kubeletplugin/draplugin.go:623-663,
#: but the reference driver never does).  Requires TPUDeviceHealthCheck —
#: the stream is fed by the same health monitor.
DRA_RESOURCE_HEALTH_SERVICE = "DRAResourceHealthService"

#: Dynamic per-chip TensorCore partitioning (the dynamic-MIG analog).
DYNAMIC_PARTITIONING = "DynamicPartitioning"

#: Advertise dynamic partitions even when the device backend attests
#: partitions_supported=false (real silicon: no TPU runtime API mutates
#: sub-chip partitions).  The partitions are then a file-backed simulation
#: the hardware never enforces — a test/dev override, never production.
SIMULATED_PARTITIONS = "SimulatedPartitions"

#: Store daemon membership in ComputeDomainClique CRs instead of CD status.
COMPUTE_DOMAIN_CLIQUES = "ComputeDomainCliques"

#: Crash the kubelet plugin instead of falling back to non-fabric mode when
#: ICI fabric errors are detected during enumeration.
CRASH_ON_ICI_FABRIC_ERRORS = "CrashOnICIFabricErrors"


class Stage(enum.Enum):
    ALPHA = "ALPHA"
    BETA = "BETA"
    GA = "GA"
    DEPRECATED = "DEPRECATED"


@dataclasses.dataclass(frozen=True)
class VersionedSpec:
    """A gate's behavior starting at ``version`` (inclusive)."""

    version: tuple[int, int]
    default: bool
    stage: Stage
    locked_to_default: bool = False


# Versioned defaults (reference featuregates.go:62-119). Versions are our
# project major.minor; a spec applies from its version onward until a newer
# spec's version is reached.
DEFAULT_FEATURE_GATES: dict[str, tuple[VersionedSpec, ...]] = {
    TIME_SLICING_SETTINGS: (VersionedSpec((0, 1), False, Stage.ALPHA),),
    MULTI_PROCESS_SHARING: (VersionedSpec((0, 1), False, Stage.ALPHA),),
    DOMAIN_DAEMONS_WITH_DNS_NAMES: (VersionedSpec((0, 1), True, Stage.BETA),),
    PASSTHROUGH_SUPPORT: (VersionedSpec((0, 1), False, Stage.ALPHA),),
    DYNAMIC_PARTITIONING: (VersionedSpec((0, 1), False, Stage.ALPHA),),
    SIMULATED_PARTITIONS: (VersionedSpec((0, 1), False, Stage.ALPHA),),
    TPU_DEVICE_HEALTH_CHECK: (VersionedSpec((0, 1), False, Stage.ALPHA),),
    DRA_RESOURCE_HEALTH_SERVICE: (VersionedSpec((0, 1), False, Stage.ALPHA),),
    COMPUTE_DOMAIN_CLIQUES: (VersionedSpec((0, 1), True, Stage.BETA),),
    CRASH_ON_ICI_FABRIC_ERRORS: (VersionedSpec((0, 1), True, Stage.BETA),),
}


class FeatureGateError(ValueError):
    pass


class FeatureGates:
    """A mutable versioned feature-gate set.

    Thread-safe; mirrors the semantics of k8s component-base
    ``featuregate.MutableVersionedFeatureGate`` that the reference relies on.
    """

    def __init__(self, version: tuple[int, int] = (0, 1)):
        self._version = version
        self._specs: dict[str, tuple[VersionedSpec, ...]] = {}
        self._overrides: dict[str, bool] = {}
        self._lock = threading.Lock()

    # -- registration -------------------------------------------------------

    def add_versioned(self, gates: Mapping[str, Iterable[VersionedSpec]]) -> None:
        with self._lock:
            for name, specs in gates.items():
                ordered = tuple(sorted(specs, key=lambda s: s.version))
                if not ordered:
                    raise FeatureGateError(f"feature gate {name} has no specs")
                if name in self._specs and self._specs[name] != ordered:
                    raise FeatureGateError(f"feature gate {name} already registered")
                self._specs[name] = ordered

    def _active_spec(self, name: str) -> VersionedSpec:
        specs = self._specs.get(name)
        if specs is None:
            raise FeatureGateError(f"unknown feature gate {name!r}")
        active = None
        for spec in specs:
            if spec.version <= self._version:
                active = spec
        if active is None:
            raise FeatureGateError(
                f"feature gate {name!r} not available before version "
                f"{specs[0].version} (current {self._version})"
            )
        return active

    # -- mutation -----------------------------------------------------------

    def set_from_map(self, values: Mapping[str, bool]) -> None:
        # Validate everything first so a bad entry leaves no partial state.
        for name, value in values.items():
            with self._lock:
                if name not in self._specs:
                    raise FeatureGateError(f"unknown feature gate {name!r}")
            spec = self._active_spec(name)
            if spec.locked_to_default and value != spec.default:
                raise FeatureGateError(
                    f"cannot set feature gate {name}: locked to {spec.default}"
                )
        with self._lock:
            self._overrides.update(values)

    def set_from_spec(self, spec: str) -> None:
        """Parse a ``Gate1=true,Gate2=false`` command-line value."""
        values: dict[str, bool] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise FeatureGateError(f"missing '=' in feature-gate spec {part!r}")
            name, _, raw = part.partition("=")
            raw = raw.strip().lower()
            if raw not in ("true", "false"):
                raise FeatureGateError(
                    f"invalid value {raw!r} for feature gate {name!r} (want true/false)"
                )
            values[name.strip()] = raw == "true"
        self.set_from_map(values)

    # -- queries ------------------------------------------------------------

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
        return self._active_spec(name).default

    def known_features(self) -> list[str]:
        out = []
        for name in sorted(self._specs):
            spec = self._active_spec(name)
            out.append(f"{name}={spec.default} ({spec.stage.value} - default={spec.default})")
        return out

    def to_map(self) -> dict[str, bool]:
        """All known gates with effective values, for template propagation
        into spawned pods (reference featuregates.go:205-211)."""
        return {name: self.enabled(name) for name in self._specs}

    def validate(self) -> None:
        """Cross-gate dependency / mutual-exclusion validation
        (reference featuregates.go:170-189)."""
        if self.enabled(COMPUTE_DOMAIN_CLIQUES) and not self.enabled(
            DOMAIN_DAEMONS_WITH_DNS_NAMES
        ):
            raise FeatureGateError(
                f"feature gate {COMPUTE_DOMAIN_CLIQUES} requires "
                f"{DOMAIN_DAEMONS_WITH_DNS_NAMES} to also be enabled"
            )
        if self.enabled(DRA_RESOURCE_HEALTH_SERVICE) and not self.enabled(
            TPU_DEVICE_HEALTH_CHECK
        ):
            raise FeatureGateError(
                f"feature gate {DRA_RESOURCE_HEALTH_SERVICE} requires "
                f"{TPU_DEVICE_HEALTH_CHECK} to also be enabled"
            )
        # DynamicPartitioning composes with MultiProcessSharing (a
        # MultiProcess claim over fractional partitions is the MPS-on-MIG
        # analog; the partition subsystem journals per-partition records
        # and the MP broker is stamped per claim — docs/partitioning.md)
        # and with TPUDeviceHealthCheck (partition-scoped health events
        # resolve through live_partition uuids).  Passthrough stays
        # mutually exclusive: rebinding a partitioned chip's PCI function
        # to vfio would yank silicon out from under live partitions.
        if self.enabled(DYNAMIC_PARTITIONING) and self.enabled(PASSTHROUGH_SUPPORT):
            raise FeatureGateError(
                f"feature gate {DYNAMIC_PARTITIONING} is currently mutually "
                f"exclusive with {PASSTHROUGH_SUPPORT}"
            )


# ---------------------------------------------------------------------------
# Process-wide singleton (reference featuregates.go:121-136)
# ---------------------------------------------------------------------------

_singleton: FeatureGates | None = None
_singleton_lock = threading.Lock()


def _project_version() -> tuple[int, int]:
    from tpudra import __version__

    major, minor = __version__.split(".")[:2]
    return (int(major), int(minor))


def feature_gates() -> FeatureGates:
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            fg = FeatureGates(_project_version())
            fg.add_versioned(DEFAULT_FEATURE_GATES)
            _singleton = fg
        return _singleton


def reset_for_testing() -> None:
    global _singleton
    with _singleton_lock:
        _singleton = None


def enabled(name: str) -> bool:
    return feature_gates().enabled(name)


def validate() -> None:
    feature_gates().validate()


def to_map() -> dict[str, bool]:
    return feature_gates().to_map()

"""Prometheus metrics + debug observability shared by all five binaries.

The analog of the reference's opt-in controller HTTP endpoint serving
Prometheus metrics and pprof (compute-domain-controller/main.go:256-303)
and the SIGUSR1/SIGUSR2 goroutine-dump handlers every binary installs
(internal/common/util.go:35).  Python translation:

- metric families below cover the same signals the reference's
  legacyregistry carried (workqueue depth, client latencies) plus the
  prepare-path histogram that the reference only ever logged as
  ``t_prep`` lines (gpu-kubelet-plugin/driver.go:340-386);
- ``DebugEndpoint`` serves ``/metrics``, ``/debug/stacks`` (the
  goroutine-profile analog: a dump of every Python thread's stack) and
  ``/debug/traces`` (the trace flight recorder's recent spans,
  tpudra/trace.py);
- ``install_debug_handlers`` registers SIGUSR1/SIGUSR2 via faulthandler —
  ``kill -USR1 <pid>`` writes all thread stacks to stderr without
  disturbing the process.
"""

from __future__ import annotations

import faulthandler
import json
import logging
import signal
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from prometheus_client import (
    CONTENT_TYPE_LATEST,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

logger = logging.getLogger(__name__)

# Buckets sized for the bind path: sub-ms (mock/cached) through the
# reference's 8 s worst case and the O(seconds) partition-create hot op.
_PREPARE_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0)

# Per-phase buckets: the phases are fractions of a bind, so the resolution
# starts an order of magnitude below the bind buckets — but the top of the
# ladder must still quantify the contention tail (lock waits run up to
# PU_LOCK_TIMEOUT = 10 s; collapsing those into +Inf would blind exactly
# the investigation these histograms exist for).
_PHASE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 16.0,
)

#: Phase label values for BIND_PHASE_SECONDS (one place so the bind-path
#: instrumentation and the tests agree on spelling).
PHASE_LOCK_WAIT = "lock-wait"
PHASE_CHECKPOINT_READ = "checkpoint-read"
PHASE_CHECKPOINT_WRITE = "checkpoint-write"
PHASE_CDI_WRITE = "cdi-write"
PHASE_CONFIG_APPLY = "config-apply"

BIND_PHASE_SECONDS = Histogram(
    "tpudra_bind_phase_seconds",
    "Wall time of one bind-path phase (lock-wait, checkpoint-read, "
    "checkpoint-write, cdi-write, config-apply) so a bench regression is "
    "attributable to a phase instead of re-diagnosed from scratch",
    ["phase"],
    buckets=_PHASE_BUCKETS,
)
FLOCK_WAIT_SECONDS = Histogram(
    "tpudra_flock_wait_seconds",
    "Time spent waiting to acquire a cross-process flock, by lock file name",
    ["lock"],
    buckets=_PHASE_BUCKETS,
)
CHECKPOINT_READS_TOTAL = Counter(
    "tpudra_checkpoint_reads_total",
    "Checkpoint reads by source: 'cache' (stat-validated in-memory hit) "
    "or 'disk' (full read + checksum + decode)",
    ["source"],
)
CHECKPOINT_FALLBACKS_TOTAL = Counter(
    "tpudra_checkpoint_version_fallbacks_total",
    "Reads that fell back to an older checkpoint payload because a newer "
    "version failed its checksum",
)
CHECKPOINT_JOURNAL_RECORDS_TOTAL = Counter(
    "tpudra_checkpoint_journal_records_total",
    "Delta records (claim upsert / drop / status transition) appended to "
    "the checkpoint journal (checkpoint.wal)",
)
CHECKPOINT_GROUP_COMMIT_BATCH_SIZE = Histogram(
    "tpudra_checkpoint_group_commit_batch_size",
    "Mutations folded into one checkpoint group commit — one leader, one "
    "cp.lock acquisition, one fsync for the whole batch",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32),
)
CHECKPOINT_COMPACTIONS_TOTAL = Counter(
    "tpudra_checkpoint_compactions_total",
    "Journal-into-snapshot compactions by trigger: 'size' / 'records' "
    "(thresholds), 'shutdown' (the clean-exit compact that gates driver "
    "downgrade)",
    ["reason"],
)
CHECKPOINT_JOURNAL_TRUNCATIONS_TOTAL = Counter(
    "tpudra_checkpoint_journal_truncations_total",
    "Torn/CRC-bad journal tails dropped at replay — crash artifacts; each "
    "read of an unrepaired tail re-counts (loud until a commit repairs it)",
)
CHECKPOINT_BYTES_WRITTEN_TOTAL = Counter(
    "tpudra_checkpoint_bytes_written_total",
    "Bytes written to checkpoint storage by kind: 'journal' (delta "
    "records — O(delta) per mutate) or 'snapshot' (full dual-version "
    "envelope — O(state) per write/compaction)",
    ["kind"],
)
CHECKPOINT_FSYNCS_TOTAL = Counter(
    "tpudra_checkpoint_fsyncs_total",
    "fsync(2) calls issued by checkpoint storage by target: 'journal' "
    "(one per group commit), 'snapshot' (temp file before rename), 'dir' "
    "(parent directory after rename — what makes the rename durable)",
    ["kind"],
)


# Labelled children resolved once: .labels() takes a registry lock and the
# bind path records several phase samples per claim.
_PHASE_CHILDREN = {
    p: BIND_PHASE_SECONDS.labels(p)
    for p in (
        PHASE_LOCK_WAIT,
        PHASE_CHECKPOINT_READ,
        PHASE_CHECKPOINT_WRITE,
        PHASE_CDI_WRITE,
        PHASE_CONFIG_APPLY,
    )
}


def observe_phase(phase: str, seconds: float) -> None:
    """Record one bind-path phase sample (helper so call sites stay short)."""
    child = _PHASE_CHILDREN.get(phase)
    (child if child is not None else BIND_PHASE_SECONDS.labels(phase)).observe(
        seconds
    )

PREPARE_SECONDS = Histogram(
    "tpudra_prepare_seconds",
    "Per-call NodePrepareResources wall time (the t_prep path; one "
    "sample per kubelet batch since the phased engine)",
    ["driver"],
    buckets=_PREPARE_BUCKETS,
)
UNPREPARE_SECONDS = Histogram(
    "tpudra_unprepare_seconds",
    "Per-call NodeUnprepareResources wall time (one sample per batch)",
    ["driver"],
    buckets=_PREPARE_BUCKETS,
)
PREPARE_ERRORS = Counter(
    "tpudra_prepare_errors_total",
    "Per-claim prepare failures returned to kubelet",
    ["driver"],
)
UNHEALTHY_DEVICES = Gauge(
    "tpudra_unhealthy_devices",
    "Devices currently withheld from the ResourceSlice due to health events",
    ["driver"],
)
SLICE_PUBLISH_TOTAL = Counter(
    "tpudra_resourceslice_publish_total",
    "ResourceSlice publication passes",
    ["driver"],
)
#: Resolution-source label values for CLAIM_RESOLUTIONS (one place so the
#: resolver and its tests agree on spelling).  ``cache`` is an informer hit;
#: every ``get-*`` source is a read-through fallback GET, keyed by why the
#: cache could not answer.
RESOLVE_CACHE = "cache"
RESOLVE_GET_PRESYNC = "get-presync"
RESOLVE_GET_MISS = "get-miss"
RESOLVE_GET_STALE_UID = "get-stale-uid"
RESOLVE_GET_UNALLOCATED = "get-unallocated"
RESOLVE_GET_WATCH_DOWN = "get-watch-down"

CLAIM_RESOLUTIONS = Counter(
    "tpudra_claim_resolutions_total",
    "Claim-reference resolutions by source: 'cache' (watch-backed informer "
    "hit) or 'get-*' (read-through apiserver GET: pre-sync, cache miss, "
    "stale cached uid, cached copy not yet allocated, watch connection "
    "down).  Steady state is ~all cache: fallback GETs are the apiserver "
    "load the informer exists to remove",
    ["source"],
)
#: Labelled children resolved once: .labels() takes a registry lock and the
#: resolver counts one sample per claim resolution on the bind hot path
#: (same pattern as _PHASE_CHILDREN below).
_RESOLUTION_CHILDREN = {
    s: CLAIM_RESOLUTIONS.labels(s)
    for s in (
        RESOLVE_CACHE,
        RESOLVE_GET_PRESYNC,
        RESOLVE_GET_MISS,
        RESOLVE_GET_STALE_UID,
        RESOLVE_GET_UNALLOCATED,
        RESOLVE_GET_WATCH_DOWN,
    )
}


def count_resolution(source: str) -> None:
    """Record one claim-resolution sample (hot path: pre-resolved child)."""
    child = _RESOLUTION_CHILDREN.get(source)
    (child if child is not None else CLAIM_RESOLUTIONS.labels(source)).inc()


CLAIM_SINGLEFLIGHT_COLLAPSED = Counter(
    "tpudra_claim_singleflight_collapsed_total",
    "Concurrent resolver threads that piggybacked on another thread's "
    "in-flight GET for the same claim instead of issuing their own",
)
SLICE_PUBLISH_COALESCED = Counter(
    "tpudra_resourceslice_publish_coalesced_total",
    "Publish signals absorbed into an already-pending rebuild by the "
    "publisher thread's debounce window (a burst of K health/withheld "
    "events costing one rebuild records K-1 here)",
    ["driver"],
)
SLICE_PUBLISH_NOOP = Counter(
    "tpudra_resourceslice_publish_noop_total",
    "Publication passes skipped because the rebuilt slice content hashed "
    "identical to what is already published (no API write issued)",
    ["driver"],
)
INFORMER_RELISTS = Counter(
    "tpudra_informer_relists_total",
    "Full LIST operations issued by an informer (initial sync plus every "
    "relist after a watch failure), by resource",
    ["resource"],
)
WORKQUEUE_DEPTH = Gauge(
    "tpudra_workqueue_depth",
    "Items waiting or in flight in a work queue",
    ["queue"],
)
WORKQUEUE_RETRIES = Counter(
    "tpudra_workqueue_retries_total",
    "Work items re-enqueued after a failure",
    ["queue"],
)
RECONCILES_TOTAL = Counter(
    "tpudra_reconciles_total",
    "Controller reconcile passes by outcome",
    ["manager", "outcome"],
)
RECONCILE_LATENCY_SECONDS = Histogram(
    "tpudra_reconcile_latency_seconds",
    "Wall time of one controller reconcile pass (including passes that "
    "end in a requeue or error — the tail a flapping object inflicts on "
    "its queue is exactly what this histogram exists to expose), by "
    "manager",
    ["manager"],
    buckets=_PREPARE_BUCKETS,
)
SOAK_FAULTS_INJECTED_TOTAL = Counter(
    "tpudra_soak_faults_injected_total",
    "Faults injected by the chaos soak (sim/chaos.py), by kind: "
    "apiserver_latency, watch_close, kubelet_restart, plugin_crash, "
    "torn_wal, clock_skew, cd_wave, chip_fault, daemon_crash, "
    "disk_fault, partition_fault, apiserver_outage, controller_failover "
    "— the denominator every soak SLO is asserted against",
    ["kind"],
)
SOAK_INVARIANT_CHECKS_TOTAL = Counter(
    "tpudra_soak_invariant_checks_total",
    "Continuous invariant evaluations by the soak's monitor thread, by "
    "invariant (claim-stuck, cdi-leak, flock-leak, slice-convergence, "
    "lock-witness, gang-atomicity, slice-health, gang-degraded, "
    "grant-health, single-writer, leadership-liveness, ...) and result "
    "(ok / violation) — a healthy soak is all ok with a nonzero check "
    "count per invariant",
    ["invariant", "result"],
)
CLAIM_HEALTH_ESCALATIONS = Counter(
    "tpudra_claim_health_escalations_total",
    "Bound-claim health escalations by the node plugin's health loop "
    "(plugin/driver.py): an unhealthy device transition that intersected "
    "a checkpointed bound claim and was surfaced on the claim's status, "
    "by result (written / failed) — a nonzero failed rate means claim "
    "holders are computing on sick silicon without a signal",
    ["result"],
)
DAEMON_RESTARTS_TOTAL = Counter(
    "tpudra_daemon_restarts_total",
    "Watchdog restarts of a supervised child process "
    "(cddaemon/process.py), by daemon (argv[0] basename) — a climbing "
    "rate is a crash-looping slice daemon the full-jitter backoff is "
    "pacing, not curing",
    ["daemon"],
)
GANG_REMEDIATIONS_TOTAL = Counter(
    "tpudra_gang_remediations_total",
    "Degraded-gang remediations (controller/gang.py) by outcome: "
    "remediated (re-reserved onto healthy spare nodes), released (no "
    "viable spares — cleanly torn down), failed (the remediation pass "
    "raised and the record was kept for recovery)",
    ["outcome"],
)
GANG_RESERVATIONS_TOTAL = Counter(
    "tpudra_gang_reservations_total",
    "Gang (all-or-nothing) slice reservations by outcome: bound (every "
    "member bound), rolled-back (a member bind failed and the bound "
    "prefix was unwound), recovered (a crash-interrupted gang converged "
    "to none-bound at controller start), released (a bound gang torn "
    "down) — controller/gang.py",
    ["outcome"],
)
GANG_STALE_LEADER_REJECTIONS = Counter(
    "tpudra_gang_stale_leader_rejections_total",
    "Gang-record mutates refused at the CHECKPOINT layer because the "
    "journaled leadership term outranks the writer's fencing token "
    "(controller/gang.py StaleLeader) — every count is a split-brain "
    "write that the lease layer failed to prevent and the WAL fence "
    "stopped from corrupting gang state",
)
LEADER_ELECTIONS_TOTAL = Counter(
    "tpudra_leader_elections_total",
    "Leader-election lifecycle transitions (controller/lease.py), by "
    "outcome: acquired (this candidate took the lease and got a fresh "
    "fencing term), lost (the lease expired or another holder took it "
    "before a renew landed), released (graceful handoff at shutdown), "
    "renew-failed (one renew attempt failed; leadership held through the "
    "grace window)",
    ["outcome"],
)
LEADER_IS_LEADER = Gauge(
    "tpudra_leader_is_leader",
    "1 while this candidate holds the controller lease, by candidate "
    "identity (identity-labeled because tests and the chaos soak run "
    "several candidates in one process; a single unlabeled gauge would "
    "let one replica's loss mask another's hold)",
    ["identity"],
)
GANG_BIND_SECONDS = Histogram(
    "tpudra_gang_bind_seconds",
    "Wall time of one successful gang reservation (journal intent + N "
    "member binds + completion commit), by gang size",
    ["nodes"],
    buckets=_PREPARE_BUCKETS,
)
PARTITION_LIFECYCLE_TOTAL = Counter(
    "tpudra_partition_lifecycle_total",
    "Dynamic-partition hardware mutations and record reconciliations "
    "(docs/partitioning.md), by op: create / destroy are bind-path "
    "devicelib mutations, sweep-destroy is a recovery-sweep teardown of "
    "an unexplained or Destroying-phase partition, record-drop is a "
    "sweep-dropped checkpoint record with no live hardware to explain "
    "it — nonzero sweep rates in steady state mean crashes are leaking "
    "partitions",
    ["op"],
)
STORAGE_FAULTS_TOTAL = Counter(
    "tpudra_storage_faults_total",
    "Storage-errno failures (ENOSPC/EIO/EROFS/EDQUOT/ENODEV) surfaced by "
    "the storage seam (tpudra/storage.py), injected or real, by op "
    "(open/write/fsync/fsync_dir/replace/truncate) and errno name — the "
    "misbehaving-disk signal every degraded-mode transition traces back "
    "to",
    ["op", "errno"],
)
STORAGE_FSYNCS_TOTAL = Counter(
    "tpudra_storage_fsyncs_total",
    "fsyncs issued by the seam's durable-write helpers (atomic_replace / "
    "write_file), by call site (cdi, checkpoint-snapshot, storage-probe, "
    "dnsnames-config, cd-daemon-settings, ...) — each durable "
    "atomic_replace costs two (file + parent directory), so a site whose "
    "rate is odd or zero has lost its durability",
    ["site"],
)
STORAGE_DEGRADED = Gauge(
    "tpudra_storage_degraded",
    "1 while the plugin's checkpoint storage cannot persist (a commit "
    "failed with a storage errno and the heal probe has not yet "
    "succeeded) — new prepare/unprepare work is shed with a typed "
    "retryable error while this is set, by node (node-labeled because "
    "the cluster sim runs many drivers in one process; a single-writer "
    "driver-name label would let one node's heal edge mask another's "
    "open degraded window)",
    ["node"],
)
STORAGE_SHED_TOTAL = Counter(
    "tpudra_storage_shed_total",
    "NodePrepare/NodeUnprepare batches refused fail-fast because the "
    "checkpoint storage is degraded (plugin/driver.py shed path), by op "
    "(prepare / unprepare) — kubelet retries these; a climbing rate with "
    "a zero degraded gauge is a bug",
    ["op"],
)
APISERVER_REQUESTS_TOTAL = Counter(
    "tpudra_apiserver_requests_total",
    "Requests issued through an accounting-wrapped kube client "
    "(kube/accounting.py), by verb — the control plane's apiserver load; "
    "divide a window's delta by its wall time for QPS by verb",
    ["verb"],
)


def render_latest() -> tuple[bytes, str]:
    return generate_latest(), CONTENT_TYPE_LATEST


def format_thread_stacks() -> str:
    """All Python thread stacks — the goroutine-dump analog."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def install_debug_handlers() -> None:
    """SIGUSR1/SIGUSR2 → all-thread stack dump to stderr
    (internal/common/util.go:35 analog).  Safe to call more than once;
    no-ops where signals are unavailable (non-main thread, Windows)."""
    try:
        faulthandler.register(signal.SIGUSR1, all_threads=True, chain=False)
        faulthandler.register(signal.SIGUSR2, all_threads=True, chain=False)
        logger.info("debug handlers installed: SIGUSR1/SIGUSR2 dump thread stacks")
    except (AttributeError, ValueError, RuntimeError) as e:
        logger.debug("debug handlers not installed: %s", e)


def parse_http_endpoint(value: str) -> tuple[str, int]:
    """Parse a ``host:port`` / ``:port`` / ``[v6]:port`` endpoint flag.
    Raises ValueError with a readable message on malformed input."""
    host, sep, port = value.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(
            f"--http-endpoint must be host:port or :port, got {value!r}"
        )
    host = host.strip("[]")  # IPv6 literal brackets
    return host or "0.0.0.0", int(port)


class DebugEndpoint:
    """Opt-in HTTP endpoint serving /metrics, /debug/stacks, /debug/traces
    and /healthz.

    The controller binary binds it from ``--http-endpoint`` (reference
    SetupHTTPEndpoint, main.go:256); the node plugins mount the same routes
    on their healthcheck server instead of running a second listener.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._server: Optional[ThreadingHTTPServer] = None

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> None:
        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — http.server API
                if not handle_debug_request(self):
                    self.send_error(404)

            def log_message(self, fmt, *args):  # noqa: D102
                logger.debug("debug-endpoint: " + fmt, *args)

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True, name="debug-endpoint"
        ).start()
        logger.info("debug endpoint serving on %s:%d", self._host, self._port)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def handle_debug_request(handler: BaseHTTPRequestHandler) -> bool:
    """Serve /metrics, /debug/stacks, /debug/traces and /healthz on any
    BaseHTTPRequestHandler.  Returns False — with nothing written to the
    connection — when the path is not a debug route, so the caller decides
    what a miss means (404 or its own routing)."""
    if handler.path == "/metrics":
        body, ctype = render_latest()
        handler.send_response(200)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
        return True
    if handler.path == "/debug/stacks":
        body = format_thread_stacks().encode()
        handler.send_response(200)
        handler.send_header("Content-Type", "text/plain; charset=utf-8")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
        return True
    if handler.path == "/debug/traces":
        # The trace flight recorder (tpudra/trace.py): recent spans,
        # newest first, bounded by the ring — the live half of what a
        # soak violation dumps.  Empty list when tracing is disabled.
        from tpudra import trace

        body = json.dumps(
            {"enabled": trace.enabled(), "spans": trace.recent_spans(256)}
        ).encode()
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
        return True
    if handler.path == "/healthz":
        handler.send_response(200)
        handler.send_header("Content-Length", "2")
        handler.end_headers()
        handler.wfile.write(b"ok")
        return True
    return False

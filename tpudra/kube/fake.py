"""In-memory fake Kubernetes API.

The reference has no fake device or API backend — its e2e suite needs a real
cluster (tests/bats/README.md:1) — while our CI target is a hermetic harness
(BASELINE.json: "kind cluster, CPU-only mock NVML").  This fake implements the
apiserver semantics the driver's controllers actually rely on:

- resourceVersion bumping and optimistic-concurrency Conflict on stale updates
- create/AlreadyExists, get/NotFound, generateName
- finalizers: delete sets deletionTimestamp; removal happens when the last
  finalizer is cleared by an update
- ownerReferences cascade GC (the apiserver's GC controller, simplified)
- status subresource updates
- list with label/field selectors
- watch with resourceVersion resume (event history replay + live queues)

Cluster-scale semantics (docs/cluster-scale.md): each event is materialized
ONCE and the same frozen payload is shared by the history and every watcher
queue — N watchers cost N queue appends, not N deep copies (watch consumers
must treat delivered objects as read-only, the client-go contract).  Watcher
queues are bounded: a consumer that falls ``watch_queue_depth`` events behind
has its stream closed with a 410 "Expired" ERROR event (what a real apiserver
does to slow watchers), and the watch history is compacted to the newest
``watch_history_limit`` events — resuming from a resourceVersion older than
the horizon gets the same 410, which an Informer answers with a relist.

It implements the same ``KubeAPI`` protocol as the real REST client, and can be
served over HTTP (kube/httpserver.py) so the real client can be tested against
it end-to-end.
"""

from __future__ import annotations

import copy
import queue
import threading
import time
import uuid as uuidlib
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from tpudra.kube import deadline, errors
from tpudra.kube.gvr import GVR


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def match_label_selector(selector: str | None, labels: dict) -> bool:
    """Equality-based selector matching: "k=v", "k==v", "k!=v", "k", "!k"."""
    if not selector:
        return True
    labels = labels or {}
    for term in selector.split(","):
        term = term.strip()
        if not term:
            continue
        if term.startswith("!"):
            if term[1:].strip() in labels:
                return False
        elif "!=" in term:
            k, _, v = term.partition("!=")
            if labels.get(k.strip()) == v.strip():
                return False
        elif "=" in term:
            k, _, v = term.partition("==") if "==" in term else term.partition("=")
            if labels.get(k.strip()) != v.strip():
                return False
        else:
            if term not in labels:
                return False
    return True


def match_field_selector(selector: str | None, obj: dict) -> bool:
    """Supports metadata.name / metadata.namespace / spec.nodeName equality."""
    if not selector:
        return True
    for term in selector.split(","):
        term = term.strip()
        if not term:
            continue
        # Both k8s forms: "k=v" and "k==v" (partition leaves the extra "="
        # on the value side).
        k, _, v = term.partition("=")
        k = k.strip()
        v = v.lstrip("=")
        parts = k.split(".")
        cur = obj
        for p in parts:
            cur = cur.get(p, {}) if isinstance(cur, dict) else {}
        if (cur or "") != v.strip():
            return False
    return True


@dataclass
class _ErrorRule:
    """One injected-failure rule: ``verb`` ("get"/"update"/... or "*") ×
    ``gvr_key`` (FakeKube._key form, or None for every resource) failing
    with HTTP ``code`` (429/500/503), ``times`` more times (None =
    sustained until the plan is cleared/healed), optionally carrying a
    ``Retry-After`` hint."""

    verb: str = "*"
    gvr_key: Optional[str] = None
    code: int = 503
    times: Optional[int] = None
    retry_after_s: Optional[float] = None
    message: str = ""


class ApiErrorPlan:
    """Per-verb × per-GVR apiserver error injection for :class:`FakeKube`
    — the refusal counterpart of ``set_latency`` (which only delays).  The
    chaos soak's ``apiserver_outage`` fault installs one to manufacture
    the failure mode real apiservers exhibit most: 429-with-Retry-After
    load shedding, 500 storms, and full 503 outage windows (fail-once and
    sustained).  Thread-safe; ``injected`` counts the failures actually
    delivered so an injector can assert its storm landed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: list[_ErrorRule] = []
        self._outage = False
        self._outage_retry_after: Optional[float] = None
        self.injected = 0

    def fail(
        self,
        verb: str = "*",
        gvr: Optional[GVR] = None,
        code: int = 503,
        times: Optional[int] = None,
        retry_after_s: Optional[float] = None,
        message: str = "",
    ) -> "ApiErrorPlan":
        """Add one rule; returns self for chaining."""
        if code not in (429, 500, 503):
            raise ValueError(f"unsupported injected error code {code}")
        with self._lock:
            self._rules.append(
                _ErrorRule(
                    verb=verb,
                    gvr_key=None if gvr is None else FakeKube._key(gvr),
                    code=code,
                    times=times,
                    retry_after_s=retry_after_s,
                    message=message,
                )
            )
        return self

    def outage(self, retry_after_s: Optional[float] = None) -> "ApiErrorPlan":
        """Every request verb on every resource fails 503 until
        :meth:`heal` — the full-outage window.  (Watch streams are closed
        separately via ``FakeKube.close_watches``: a dead apiserver drops
        both, but they are distinct injectors so tests can exercise each
        recovery path alone.)"""
        with self._lock:
            self._outage = True
            self._outage_retry_after = retry_after_s
        return self

    def heal(self) -> None:
        """Drop every rule and close the outage window."""
        with self._lock:
            self._rules.clear()
            self._outage = False
            self._outage_retry_after = None

    def _error_for(self, verb: str, gvr_key: str) -> Optional[errors.ApiError]:
        with self._lock:
            if self._outage:
                self.injected += 1
                return errors.ServiceUnavailable(
                    f"injected outage: {verb} refused",
                    retry_after_s=self._outage_retry_after,
                )
            for rule in self._rules:
                if rule.verb not in (verb, "*"):
                    continue
                if rule.gvr_key is not None and rule.gvr_key != gvr_key:
                    continue
                if rule.times is not None:
                    if rule.times <= 0:
                        continue
                    rule.times -= 1
                self.injected += 1
                message = rule.message or f"injected {rule.code}: {verb} refused"
                if rule.code == 429:
                    return errors.TooManyRequests(
                        message, retry_after_s=rule.retry_after_s
                    )
                if rule.code == 503:
                    return errors.ServiceUnavailable(
                        message, retry_after_s=rule.retry_after_s
                    )
                return errors.InternalError(message)
        return None


def _expired_event(message: str) -> dict:
    """The in-band watch-termination event a real apiserver sends when the
    requested resourceVersion predates its retained history (a slow watcher
    or a too-old resume): ``{"type": "ERROR", "object": <410 Status>}``.
    It travels the same path as data events, so the HTTP frontend needs no
    special-casing mid-stream and the Informer sees identical semantics
    over both transports."""
    return {"type": "ERROR", "object": errors.Expired(message).to_status()}


class _Watcher:
    def __init__(
        self,
        gvr_key: str,
        namespace: Optional[str],
        label_selector: Optional[str],
        field_selector: Optional[str] = None,
        depth: int = 0,
    ):
        self.gvr_key = gvr_key
        self.namespace = namespace
        self.label_selector = label_selector
        self.field_selector = field_selector
        self.queue: queue.Queue = queue.Queue(maxsize=depth)
        self.stopped = threading.Event()
        #: Set by the emitter when this watcher's queue overflowed: the
        #: stream has a gap, so delivery stops with a 410 ERROR event.
        self.overflowed = threading.Event()
        #: Set by FakeKube.close_watches (the chaos harness's apiserver
        #: watch-flap injector): delivery stops with the same in-band 410
        #: a real apiserver sends when it expires a stream server-side.
        self.expired = threading.Event()

    def stop(self) -> None:
        self.stopped.set()
        try:
            self.queue.put_nowait(None)
        except queue.Full:
            # The consumer is behind anyway; the stopped flag alone ends
            # the loop on its next wakeup.
            pass


class FakeKube:
    """An in-memory KubeAPI implementation."""

    #: Verbs the latency knob applies to.  Watch delivery stays instant:
    #: it is the push channel the latency knob exists to favor.
    LATENCY_VERBS = ("get", "list", "create", "update", "delete")

    #: Default bound on how far one watcher may fall behind before its
    #: stream is closed with 410 (a real apiserver's slow-watcher drop).
    WATCH_QUEUE_DEPTH = 1024
    #: Default number of events retained for resourceVersion resume; older
    #: resumes get 410 Expired and must relist (etcd compaction analog).
    WATCH_HISTORY_LIMIT = 4096

    def __init__(
        self,
        watch_queue_depth: int = WATCH_QUEUE_DEPTH,
        watch_history_limit: int = WATCH_HISTORY_LIMIT,
        per_watcher_copy: bool = False,
    ):
        self._lock = threading.RLock()
        self._objects: dict[str, dict[tuple, dict]] = {}  # gvr_key -> {(ns, name): obj}
        self._rv = 0
        self._history: list[tuple[int, str, dict]] = []  # (rv, gvr_key, event)
        self._watchers: list[_Watcher] = []
        self._reactors: list[tuple[str, str, Callable]] = []  # (verb, gvr_key, fn)
        self._latency_s = 0.0
        self._error_plan: Optional[ApiErrorPlan] = None
        self._watch_queue_depth = int(watch_queue_depth)
        self._watch_history_limit = int(watch_history_limit)
        #: rv of the newest event dropped by history compaction — resumes
        #: at or below an OLDER rv than this are unrecoverable (410).
        self._compacted_rv = 0
        #: True restores the pre-cluster-scale behavior (one deepcopy per
        #: watcher per event) — the "before" arm of bench --cluster-scale.
        self._per_watcher_copy = per_watcher_copy
        #: Observability for the fan-out path (bench + regression tests):
        #: materializations counts event deep-copies, deliveries counts
        #: watcher-queue appends, overflows counts slow-watcher stream
        #: closes, compactions counts history-trim passes.
        self.watch_stats = {
            "materializations": 0,
            "deliveries": 0,
            "overflows": 0,
            "compactions": 0,
            "forced_closes": 0,
        }

    # -- test hooks ---------------------------------------------------------

    def react(self, verb: str, gvr: GVR, fn: Callable[[str, GVR, dict | None], None]) -> None:
        """Install a reactor called before ``verb`` ("create", "update",
        "delete", "get", "list") executes; raise from it to inject failures."""
        self._reactors.append((verb, self._key(gvr), fn))

    def close_watches(self, gvr: Optional[GVR] = None) -> int:
        """Force-close live watch streams with an in-band 410 ERROR — the
        chaos soak's watch-flap injector (a real apiserver expires streams
        server-side on timeouts, restarts, and etcd compactions; clients
        must answer with a relist).  ``gvr`` narrows the flap to one
        resource; default is every stream.  Returns the number of streams
        closed.  A consumer parked in its queue wait notices within its
        1 s poll — the same order of delay a TCP FIN takes to surface
        through a real client's buffered reader."""
        with self._lock:
            targets = [
                w
                for w in self._watchers
                if (gvr is None or w.gvr_key == self._key(gvr))
                and not w.expired.is_set()
                and not w.overflowed.is_set()
            ]
            for w in targets:
                w.expired.set()
                self.watch_stats["forced_closes"] += 1
        return len(targets)

    def set_error_plan(self, plan: Optional[ApiErrorPlan]) -> None:
        """Install (or clear, with None) an error-injection plan.  Every
        request verb consults it AFTER the latency/deadline simulation —
        a 429 storm during a latency spike costs the RTT and then the
        refusal, exactly like a slow-then-shedding real apiserver."""
        # tpudra-race: handoff atomic publication knob: a single reference assignment the request threads read per-verb; guarding it with the store lock would park the fault injector behind the simulated RTT sleep
        self._error_plan = plan

    def set_latency(self, seconds: float) -> None:
        """Simulate apiserver round-trip time: every request verb (not
        watch delivery) sleeps ``seconds`` before executing, while holding
        the store lock.  Sleeping under the lock is deliberate: requests
        from one client serialize, which is what a production driver sees
        anyway — its client-side QPS limiter (``--kube-api-qps``, default
        5) spaces concurrent requests out far more aggressively than the
        RTT itself.  N concurrent GETs therefore cost ~N×RTT, the cost the
        watch-backed caches exist to remove (bench.py
        --apiserver-latency-ms)."""
        # tpudra-race: handoff atomic publication knob: a single float assignment read per-request; same rationale as set_error_plan
        self._latency_s = float(seconds)

    # tpudra-lock: nonblocking the latency sleep is the simulated-RTT knob itself — set_latency's docstring argues why it sleeps under the store lock on purpose
    def _run_reactors(self, verb: str, gvr: GVR, obj: dict | None) -> None:
        if verb in self.LATENCY_VERBS:
            # Ambient deadline (kube/deadline.py): a latency spike may
            # consume a caller's remaining budget but never exceed it —
            # sleep to the deadline, then fail with the typed 504 the
            # real client maps socket timeouts to.  This is what lets a
            # bind's fallback GET fail fast and retryably during the chaos
            # soak's apiserver_latency fault instead of wedging the RPC
            # past its gRPC deadline.
            rem = deadline.remaining()
            if self._latency_s > 0:
                if rem is not None and self._latency_s >= rem:
                    time.sleep(max(0.0, min(self._latency_s, rem)))
                    raise errors.Timeout(
                        f"{verb}: simulated RTT {self._latency_s:.3f}s "
                        f"exceeds the caller's remaining deadline"
                    )
                time.sleep(self._latency_s)
            elif rem is not None and rem <= 0:
                raise errors.Timeout(f"{verb}: deadline already exceeded")
            plan = self._error_plan
            if plan is not None:
                err = plan._error_for(verb, self._key(gvr))
                if err is not None:
                    raise err
        for v, key, fn in self._reactors:
            if v in (verb, "*") and key == self._key(gvr):
                fn(verb, gvr, obj)

    # -- internals ----------------------------------------------------------

    @staticmethod
    def _key(gvr: GVR) -> str:
        return f"{gvr.group}/{gvr.version}/{gvr.resource}"

    def _bucket(self, gvr: GVR) -> dict[tuple, dict]:
        return self._objects.setdefault(self._key(gvr), {})

    def _obj_key(self, gvr: GVR, namespace: Optional[str], name: str) -> tuple:
        return (namespace if gvr.namespaced else None, name)

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _emit(self, gvr: GVR, event_type: str, obj: dict) -> None:
        # ONE materialization per event: the history entry and every
        # matching watcher share the same payload.  Per-watcher deep copies
        # turn each mutation into O(watchers) serialization work — the
        # fan-out cost that dominates a 1000-node control plane (each
        # node's informer is a watcher).  Consumers own the read-only
        # contract (client-go's: never mutate a watch-delivered object).
        event = {"type": event_type, "object": copy.deepcopy(obj)}
        self.watch_stats["materializations"] += 1
        self._history.append((int(obj["metadata"]["resourceVersion"]), self._key(gvr), event))
        if len(self._history) > self._watch_history_limit:
            drop = len(self._history) - self._watch_history_limit
            self._compacted_rv = self._history[drop - 1][0]
            del self._history[:drop]
            self.watch_stats["compactions"] += 1
        for w in list(self._watchers):
            if (
                w.gvr_key != self._key(gvr)
                or w.overflowed.is_set()
                or w.expired.is_set()
            ):
                continue
            meta = obj.get("metadata", {})
            if w.namespace and meta.get("namespace") != w.namespace:
                continue
            if not match_label_selector(w.label_selector, meta.get("labels", {})):
                continue
            if not match_field_selector(w.field_selector, obj):
                continue
            payload = copy.deepcopy(event) if self._per_watcher_copy else event
            if self._per_watcher_copy:
                self.watch_stats["materializations"] += 1
            try:
                w.queue.put_nowait(payload)
                self.watch_stats["deliveries"] += 1
            except queue.Full:
                # The consumer fell watch_queue_depth events behind: its
                # stream now has a gap, so terminate it the way a real
                # apiserver does — 410 on the stream, client must relist.
                # The flag (not a queued sentinel — the queue is full)
                # makes the delivery loop surface the ERROR event.
                w.overflowed.set()
                self.watch_stats["overflows"] += 1

    # -- KubeAPI protocol ---------------------------------------------------

    def get(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> dict:
        with self._lock:
            self._run_reactors("get", gvr, None)
            obj = self._bucket(gvr).get(self._obj_key(gvr, namespace, name))
            if obj is None:
                raise errors.NotFound(f"{gvr.resource} {namespace or ''}/{name} not found")
            return copy.deepcopy(obj)

    def list(
        self,
        gvr: GVR,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> dict:
        with self._lock:
            self._run_reactors("list", gvr, None)
            items = []
            for (ns, _), obj in self._bucket(gvr).items():
                if gvr.namespaced and namespace and ns != namespace:
                    continue
                if not match_label_selector(label_selector, obj["metadata"].get("labels", {})):
                    continue
                if not match_field_selector(field_selector, obj):
                    continue
                items.append(copy.deepcopy(obj))
            items.sort(key=lambda o: (o["metadata"].get("namespace") or "", o["metadata"]["name"]))
            return {
                "apiVersion": gvr.api_version,
                "kind": gvr.kind + "List",
                "metadata": {"resourceVersion": str(self._rv)},
                "items": items,
            }

    def create(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict:
        with self._lock:
            self._run_reactors("create", gvr, obj)
            obj = copy.deepcopy(obj)
            meta = obj.setdefault("metadata", {})
            if gvr.namespaced:
                meta.setdefault("namespace", namespace or "default")
                namespace = meta["namespace"]
            name = meta.get("name")
            if not name:
                gen = meta.get("generateName")
                if not gen:
                    raise errors.Invalid("name or generateName required")
                name = gen + uuidlib.uuid4().hex[:5]
                meta["name"] = name
            key = self._obj_key(gvr, namespace, name)
            if key in self._bucket(gvr):
                raise errors.AlreadyExists(
                    f"{gvr.resource} {namespace or ''}/{name} already exists"
                )
            # A real apiserver owns uid assignment; the fake honors a
            # pre-set uid so tests can use deterministic claim uids while
            # still getting server-assigned ones when omitted.
            meta["uid"] = meta.get("uid") or str(uuidlib.uuid4())
            meta["resourceVersion"] = self._next_rv()
            meta["creationTimestamp"] = _now()
            meta.setdefault("generation", 1)
            obj.setdefault("apiVersion", gvr.api_version)
            obj.setdefault("kind", gvr.kind)
            self._bucket(gvr)[key] = obj
            self._emit(gvr, "ADDED", obj)
            return copy.deepcopy(obj)

    def _update(
        self, gvr: GVR, obj: dict, namespace: Optional[str], status_only: bool
    ) -> dict:
        with self._lock:
            self._run_reactors("update", gvr, obj)
            obj = copy.deepcopy(obj)
            meta = obj.get("metadata", {})
            name = meta.get("name")
            if not name:
                raise errors.Invalid("name required")
            if gvr.namespaced:
                namespace = meta.get("namespace") or namespace or "default"
            key = self._obj_key(gvr, namespace, name)
            current = self._bucket(gvr).get(key)
            if current is None:
                raise errors.NotFound(f"{gvr.resource} {namespace or ''}/{name} not found")
            rv = meta.get("resourceVersion")
            if rv and rv != current["metadata"]["resourceVersion"]:
                raise errors.Conflict(
                    f"operation cannot be fulfilled on {gvr.resource} {name}: "
                    "object has been modified"
                )
            if status_only:
                updated = copy.deepcopy(current)
                updated["status"] = obj.get("status", {})
            else:
                updated = obj
                # Immutable/system-owned fields are preserved.
                updated["metadata"]["uid"] = current["metadata"]["uid"]
                updated["metadata"]["creationTimestamp"] = current["metadata"][
                    "creationTimestamp"
                ]
                if "deletionTimestamp" in current["metadata"]:
                    updated["metadata"]["deletionTimestamp"] = current["metadata"][
                        "deletionTimestamp"
                    ]
                if current.get("spec") != updated.get("spec"):
                    updated["metadata"]["generation"] = (
                        current["metadata"].get("generation", 1) + 1
                    )
                updated.setdefault("status", current.get("status", {}))
            updated["metadata"]["resourceVersion"] = self._next_rv()
            updated.setdefault("apiVersion", gvr.api_version)
            updated.setdefault("kind", gvr.kind)

            # Finalizer semantics: a terminating object whose finalizers have
            # all been removed is actually deleted by this update.
            if (
                updated["metadata"].get("deletionTimestamp")
                and not updated["metadata"].get("finalizers")
            ):
                del self._bucket(gvr)[key]
                self._emit(gvr, "DELETED", updated)
                self._cascade_delete(updated["metadata"]["uid"])
                return copy.deepcopy(updated)

            self._bucket(gvr)[key] = updated
            self._emit(gvr, "MODIFIED", updated)
            return copy.deepcopy(updated)

    def update(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict:
        return self._update(gvr, obj, namespace, status_only=False)

    def update_status(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict:
        return self._update(gvr, obj, namespace, status_only=True)

    def patch(
        self, gvr: GVR, name: str, patch: dict, namespace: Optional[str] = None
    ) -> dict:
        """RFC 7386 JSON merge patch."""
        with self._lock:
            current = self.get(gvr, name, namespace)

            def merge(dst, src):
                for k, v in src.items():
                    if v is None:
                        dst.pop(k, None)
                    elif isinstance(v, dict) and isinstance(dst.get(k), dict):
                        merge(dst[k], v)
                    else:
                        dst[k] = v

            merge(current, patch)
            current["metadata"]["resourceVersion"] = ""  # skip conflict check
            return self._update(gvr, current, namespace, status_only=False)

    def delete(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> None:
        with self._lock:
            self._run_reactors("delete", gvr, None)
            key = self._obj_key(gvr, namespace, name)
            obj = self._bucket(gvr).get(key)
            if obj is None:
                raise errors.NotFound(f"{gvr.resource} {namespace or ''}/{name} not found")
            if obj["metadata"].get("finalizers"):
                if not obj["metadata"].get("deletionTimestamp"):
                    obj["metadata"]["deletionTimestamp"] = _now()
                    obj["metadata"]["resourceVersion"] = self._next_rv()
                    self._emit(gvr, "MODIFIED", obj)
                return
            del self._bucket(gvr)[key]
            obj["metadata"]["resourceVersion"] = self._next_rv()
            self._emit(gvr, "DELETED", obj)
            self._cascade_delete(obj["metadata"]["uid"])

    def _cascade_delete(self, owner_uid: str) -> None:
        """Owner-reference GC: delete dependents of a removed owner."""
        from tpudra.kube.gvr import ALL_GVRS

        for gvr in ALL_GVRS:
            bucket = self._objects.get(self._key(gvr), {})
            doomed = []
            for (ns, name), obj in bucket.items():
                for ref in obj["metadata"].get("ownerReferences", []):
                    if ref.get("uid") == owner_uid:
                        doomed.append((ns, name))
                        break
            for ns, name in doomed:
                try:
                    self.delete(gvr, name, ns)
                except errors.NotFound:
                    pass

    # -- watch --------------------------------------------------------------

    def watch(
        self,
        gvr: GVR,
        namespace: Optional[str] = None,
        resource_version: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
        stop: Optional[threading.Event] = None,
    ) -> Iterator[dict]:
        """Yield {"type": ..., "object": ...} events.

        With resource_version, replays history events newer than it first
        (k8s watch resume), then streams live events.  A resume older than
        the compacted history horizon, or a consumer that overflows its
        bounded queue, gets a terminal ``{"type": "ERROR"}`` event carrying
        a 410 Expired Status — the client's cue to relist.  Terminates when
        ``stop`` is set.
        """
        watcher = _Watcher(
            self._key(gvr),
            namespace if gvr.namespaced else None,
            label_selector,
            field_selector,
            depth=self._watch_queue_depth,
        )
        with self._lock:
            backlog = []
            if resource_version is not None:
                rv = int(resource_version)
                if rv < self._compacted_rv:
                    # Events in (rv, compacted_rv] are gone; replay would
                    # silently skip them.  410, exactly like etcd-compacted
                    # history behind a real apiserver.
                    backlog = None
                else:
                    for ev_rv, key, event in self._history:
                        if key != watcher.gvr_key or ev_rv <= rv:
                            continue
                        meta = event["object"].get("metadata", {})
                        if watcher.namespace and meta.get("namespace") != watcher.namespace:
                            continue
                        if not match_label_selector(label_selector, meta.get("labels", {})):
                            continue
                        if not match_field_selector(field_selector, event["object"]):
                            continue
                        if self._per_watcher_copy:
                            event = copy.deepcopy(event)
                            self.watch_stats["materializations"] += 1
                        backlog.append(event)
            if backlog is not None:
                self._watchers.append(watcher)
        if backlog is None:
            yield _expired_event(
                f"too old resource version: {resource_version} "
                f"(history starts after {self._compacted_rv})"
            )
            return
        try:
            yield from backlog
            while True:
                if stop is not None and stop.is_set():
                    return
                if watcher.overflowed.is_set():
                    yield _expired_event(
                        f"watch fell more than {self._watch_queue_depth} "
                        "events behind; resume requires a fresh list"
                    )
                    return
                if watcher.expired.is_set():
                    yield _expired_event(
                        "watch stream closed by the server; resume "
                        "requires a fresh list"
                    )
                    return
                try:
                    # Deliveries wake the blocking get instantly; the
                    # timeout only bounds stop-latency.  Keep it LONG: at
                    # cluster scale every watcher is a thread, and N×20
                    # idle wakeups/s of GIL+futex churn was measurably
                    # slower than the churn being benchmarked.
                    event = watcher.queue.get(timeout=1.0)
                except queue.Empty:
                    # The stop() sentinel can be lost when the queue is at
                    # capacity; the flag ends the loop once drained.
                    if watcher.stopped.is_set():
                        return
                    continue
                if event is None:
                    return
                yield event
        finally:
            with self._lock:
                if watcher in self._watchers:
                    self._watchers.remove(watcher)

"""List+watch informer with a local cache and event handlers.

The analog of the generated informers the reference gets from informer-gen
plus client-go's shared informer machinery: list, then watch from the list's
resourceVersion, re-listing on watch failure; handlers fire on add/update/
delete; ``wait_for_sync`` gates controller startup.

Also provides MutationCache: after a controller writes an object, the freshly
written version is layered over the informer cache so the controller doesn't
act on its own stale read (reference compute-domain-controller/
computedomain.go:117-125).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from tpudra.kube.client import KubeAPI
from tpudra.kube.gvr import GVR

logger = logging.getLogger(__name__)

Handler = Callable[[str, dict], None]  # (event_type, object)


def obj_key(obj: dict) -> tuple:
    meta = obj.get("metadata", {})
    return (meta.get("namespace"), meta.get("name"))


class Informer:
    def __init__(
        self,
        api: KubeAPI,
        gvr: GVR,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
        resync_period: float = 0.0,
    ):
        self._api = api
        self._gvr = gvr
        self._namespace = namespace
        self._label_selector = label_selector
        self._field_selector = field_selector
        self._resync_period = resync_period
        self._store: dict[tuple, dict] = {}
        self._lock = threading.Lock()
        self._handlers: list[Handler] = []
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._indices: dict[str, Callable[[dict], str | None]] = {}
        self._backoff = 0.2  # relist backoff, reset by each successful list

    # -- configuration ------------------------------------------------------

    def add_handler(self, handler: Handler) -> None:
        self._handlers.append(handler)

    def add_index(self, name: str, fn: Callable[[dict], str | None]) -> None:
        """Register a secondary index (e.g. by uid, by label value)."""
        self._indices[name] = fn

    # -- store access -------------------------------------------------------

    def get(self, name: str, namespace: Optional[str] = None) -> Optional[dict]:
        with self._lock:
            return self._store.get((namespace, name))

    def list(self) -> list[dict]:
        with self._lock:
            return list(self._store.values())

    def by_index(self, index: str, value: str) -> list[dict]:
        fn = self._indices[index]
        with self._lock:
            return [o for o in self._store.values() if fn(o) == value]

    # -- lifecycle ----------------------------------------------------------

    def start(self, stop: threading.Event) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(stop,), daemon=True, name=f"informer-{self._gvr.resource}"
        )
        self._thread.start()

    def wait_for_sync(self, timeout: float = 30.0) -> bool:
        return self._synced.wait(timeout)

    @property
    def has_synced(self) -> bool:
        """Non-blocking: True once the initial LIST has populated the
        store.  Read-through consumers must fall back to a direct API call
        until then — an empty pre-sync cache looks like 'nothing exists'."""
        return self._synced.is_set()

    def _run(self, stop: threading.Event) -> None:
        # Jittered exponential relist backoff: when the apiserver is down,
        # every informer in every binary hits this loop at once — fixed
        # short sleeps synchronize them into a relist storm at recovery
        # (client-go's reflector backs off the same way).
        import random

        self._backoff = 0.2
        while not stop.is_set():
            try:
                self._list_and_watch(stop)
                self._backoff = 0.2
            except Exception as e:  # noqa: BLE001 — informer must survive apiserver blips
                delay = self._backoff * (0.5 + random.random())
                logger.warning(
                    "informer %s: list/watch failed: %s; re-listing in %.1fs",
                    self._gvr.resource, e, delay,
                )
                self._backoff = min(self._backoff * 2, 30.0)
                stop.wait(delay)

    def _list_and_watch(self, stop: threading.Event) -> None:
        listing = self._api.list(
            self._gvr,
            self._namespace,
            label_selector=self._label_selector,
            field_selector=self._field_selector,
        )
        # A healthy LIST resets the relist backoff even if the WATCH below
        # dies every cycle (an LB idle-timeout resetting watches must not
        # escalate us to 30 s event-delivery gaps — client-go's reflector
        # resets on successful list the same way).
        self._backoff = 0.2
        rv = listing.get("metadata", {}).get("resourceVersion")
        fresh = {obj_key(o): o for o in listing.get("items", [])}
        with self._lock:
            old = self._store
            self._store = fresh
        for key, obj in fresh.items():
            if key not in old:
                self._dispatch("ADDED", obj)
            elif old[key].get("metadata", {}).get("resourceVersion") != obj.get(
                "metadata", {}
            ).get("resourceVersion"):
                self._dispatch("MODIFIED", obj)
        for key, obj in old.items():
            if key not in fresh:
                self._dispatch("DELETED", obj)
        self._synced.set()

        for event in self._api.watch(
            self._gvr,
            self._namespace,
            resource_version=rv,
            label_selector=self._label_selector,
            field_selector=self._field_selector,
            stop=stop,
        ):
            if stop.is_set():
                return
            etype, obj = event["type"], event["object"]
            key = obj_key(obj)
            with self._lock:
                if etype == "DELETED":
                    self._store.pop(key, None)
                else:
                    self._store[key] = obj
            self._dispatch(etype, obj)

    def _dispatch(self, etype: str, obj: dict) -> None:
        for handler in self._handlers:
            try:
                handler(etype, obj)
            except Exception:  # noqa: BLE001
                logger.exception("informer %s handler failed", self._gvr.resource)


class MutationCache:
    """Layer controller-written objects over an informer cache so a controller
    never acts on its own stale read.  Entries expire after ttl (the informer
    catches up well before that)."""

    def __init__(self, informer: Informer, ttl: float = 10.0):
        self._informer = informer
        self._ttl = ttl
        self._mutated: dict[tuple, tuple[float, dict]] = {}
        self._lock = threading.Lock()

    def mutated(self, obj: dict) -> None:
        with self._lock:
            self._mutated[obj_key(obj)] = (time.monotonic() + self._ttl, obj)

    def get(self, name: str, namespace: Optional[str] = None) -> Optional[dict]:
        key = (namespace, name)
        cached = self._informer.get(name, namespace)
        with self._lock:
            entry = self._mutated.get(key)
            if entry is None:
                return cached
            expires, obj = entry
            if time.monotonic() > expires:
                del self._mutated[key]
                return cached
        if cached is not None:
            try:
                if int(cached["metadata"]["resourceVersion"]) >= int(
                    obj["metadata"]["resourceVersion"]
                ):
                    return cached  # informer caught up
            except (KeyError, ValueError):
                return cached
        return obj

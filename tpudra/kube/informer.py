"""List+watch informer with a local cache and event handlers.

The analog of the generated informers the reference gets from informer-gen
plus client-go's shared informer machinery: list, then watch from the list's
resourceVersion, re-listing on watch failure; handlers fire on add/update/
delete; ``wait_for_sync`` gates controller startup.  Secondary indices
(``add_index``/``by_index``) are real inverted maps maintained on every
store mutation, and a nonzero ``resync_period`` re-dispatches MODIFIED for
all cached objects on the period (client-go's periodic resync) as a drift
backstop for level-triggered consumers.

Also provides MutationCache: after a controller writes an object, the freshly
written version is layered over the informer cache so the controller doesn't
act on its own stale read (reference compute-domain-controller/
computedomain.go:117-125).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from tpudra import lockwitness, metrics, racewitness
from tpudra.backoff import Backoff
from tpudra.kube import errors
from tpudra.kube.client import KubeAPI
from tpudra.kube.gvr import GVR

logger = logging.getLogger(__name__)

Handler = Callable[[str, dict], None]  # (event_type, object)


def obj_key(obj: dict) -> tuple:
    meta = obj.get("metadata", {})
    return (meta.get("namespace"), meta.get("name"))


class Informer:
    def __init__(
        self,
        api: KubeAPI,
        gvr: GVR,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
        resync_period: float = 0.0,
        cache_filter: Optional[Callable[[dict], bool]] = None,
        rng=None,
    ):
        self._api = api
        self._gvr = gvr
        self._namespace = namespace
        self._label_selector = label_selector
        self._field_selector = field_selector
        self._resync_period = resync_period
        #: Client-side store filter: objects failing it are never cached
        #: (and an update that stops matching evicts — dispatched as
        #: DELETED, the filtered-informer convention).  Bounds a node
        #: agent's cache to the objects it can ever act on when the
        #: apiserver offers no server-side selector for the predicate.
        self._cache_filter = cache_filter
        self._store: dict[tuple, dict] = {}
        self._lock = lockwitness.make_lock("informer.store_lock")
        self._handlers: list[Handler] = []
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._indices: dict[str, Callable[[dict], str | None]] = {}
        #: index name -> index value -> store keys.  Real inverted indices,
        #: maintained on every store mutation: ``by_index`` is called per
        #: reconcile by the controller, and a full store scan per call turns
        #: the informer cache into an O(store) lookup under load.
        self._index_data: dict[str, dict[str, set[tuple]]] = {}
        #: Relist backoff: capped exponential with FULL jitter (shared
        #: tpudra/backoff.py policy), reset by each successful list.  At
        #: cluster scale every node informer enters this loop within
        #: milliseconds of an apiserver flap; full jitter is what keeps
        #: their relists from landing as one synchronized storm at
        #: recovery.  ``rng`` (an optional ``random.Random``) makes the
        #: schedule reproducible for the chaos soak and benches.
        self._relist_backoff = Backoff(0.2, 30.0, rng=rng)
        self._watch_ok = False  # see watch_healthy
        #: Serializes handler deliveries between the list/watch thread and
        #: the resync thread — handlers are written for single-threaded
        #: dispatch, and interleaving could hand them a resync replay
        #: AFTER a fresher watch event (client-go serializes through one
        #: processor queue for the same reason).  RLock: the resync loop
        #: holds it across its store re-read + dispatch, and _dispatch
        #: re-acquires it.
        self._dispatch_lock = lockwitness.make_rlock("informer.dispatch_lock")

    # -- configuration ------------------------------------------------------

    def add_handler(self, handler: Handler) -> None:
        # tpudra-race: handoff init-before-start publication across call sites: controllers register every handler before start() spawns the watch thread, and the dispatch side only iterates — the ordering edge is the Thread.start the model cannot tie to this method
        self._handlers.append(handler)

    def add_index(self, name: str, fn: Callable[[dict], str | None]) -> None:
        """Register a secondary index (e.g. by uid, by label value).
        Objects already in the store are indexed immediately."""
        with self._lock:
            self._indices[name] = fn
            self._index_data[name] = {}
            for key, obj in self._store.items():
                self._index_one(name, fn, key, obj)

    # -- index maintenance (every helper expects self._lock held) -----------

    def _index_one(self, name: str, fn: Callable, key: tuple, obj: dict) -> None:
        value = fn(obj)
        if value is not None:
            self._index_data[name].setdefault(value, set()).add(key)

    def _index_add(self, key: tuple, obj: dict) -> None:
        for name, fn in self._indices.items():
            self._index_one(name, fn, key, obj)

    def _index_drop(self, key: tuple, obj: dict) -> None:
        for name, fn in self._indices.items():
            value = fn(obj)
            if value is None:
                continue
            keys = self._index_data[name].get(value)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._index_data[name][value]

    def _index_rebuild(self) -> None:
        self._index_data = {name: {} for name in self._indices}
        for key, obj in self._store.items():
            self._index_add(key, obj)

    # -- store access -------------------------------------------------------

    def get(self, name: str, namespace: Optional[str] = None) -> Optional[dict]:
        with self._lock:
            return self._store.get((namespace, name))

    def list(self) -> list[dict]:
        with self._lock:
            return list(self._store.values())

    def by_index(self, index: str, value: str) -> list[dict]:
        with self._lock:
            keys = self._index_data[index].get(value, ())
            return [self._store[k] for k in keys]

    # -- lifecycle ----------------------------------------------------------

    def start(self, stop: threading.Event) -> None:
        # tpudra-race: handoff lifecycle: start() runs once per informer from whichever single thread owns setup; the field is written before the watch thread exists and only read by the join in stop choreography
        self._thread = threading.Thread(
            target=self._run, args=(stop,), daemon=True, name=f"informer-{self._gvr.resource}"
        )
        if racewitness.enabled():
            # Publication edge: everything configured before start()
            # happens-before the watch/resync loops' first read.
            racewitness.note_hb_send("informer.start")
        self._thread.start()
        if self._resync_period > 0:
            threading.Thread(
                target=self._resync_loop,
                args=(stop,),
                daemon=True,
                name=f"informer-resync-{self._gvr.resource}",
            ).start()

    def _resync_loop(self, stop: threading.Event) -> None:
        """Periodic resync, as client-go's shared informer does it:
        re-dispatch MODIFIED for every cached object on the period, so
        level-triggered handlers converge on drift (a missed edge, an
        external actor) without waiting for the next real event.  Each
        object is re-read from the store at dispatch time under the
        dispatch mutex, so a resync delivery is never an OLDER state than
        an event the watch thread already delivered (client-go gets the
        same guarantee from its single processor queue)."""
        if racewitness.enabled():
            racewitness.note_hb_recv("informer.start")
        while not stop.wait(self._resync_period):
            if not self._synced.is_set():
                continue
            with self._lock:
                keys = list(self._store.keys())
            for key in keys:
                with self._dispatch_lock:
                    with self._lock:
                        obj = self._store.get(key)
                    if obj is not None:
                        self._dispatch("MODIFIED", obj)

    def wait_for_sync(self, timeout: float = 30.0) -> bool:
        ok = self._synced.wait(timeout)
        if ok and racewitness.enabled():
            racewitness.note_hb_recv("informer.synced")
        return ok

    @property
    def has_synced(self) -> bool:
        """Non-blocking: True once the initial LIST has populated the
        store.  Read-through consumers must fall back to a direct API call
        until then — an empty pre-sync cache looks like 'nothing exists'."""
        return self._synced.is_set()

    @property
    def watch_healthy(self) -> bool:
        """True while the current list+watch cycle is live (last LIST
        succeeded, watch has not failed since).  While False the cache may
        lag by up to the relist backoff (≤ 30 s); read-through consumers
        that need tighter staleness than that should treat an unhealthy
        watch like pre-sync and fall back to direct reads."""
        return self._watch_ok

    def _run(self, stop: threading.Event) -> None:
        # Jittered exponential relist backoff: when the apiserver is down,
        # every informer in every binary hits this loop at once — fixed
        # short sleeps synchronize them into a relist storm at recovery
        # (client-go's reflector backs off the same way).
        if racewitness.enabled():
            racewitness.note_hb_recv("informer.start")
        self._relist_backoff.reset()
        while not stop.is_set():
            try:
                self._list_and_watch(stop)
                self._relist_backoff.reset()
            except errors.Expired as e:
                # 410 Gone: the server compacted past our resourceVersion
                # (too-old resume, or it dropped us as a slow watcher).
                # This is the server TELLING us to relist — client-go's
                # reflector relists immediately, without backoff: the
                # apiserver is healthy, our vantage point is just stale.
                # The tiny wait only guards against a pathological server
                # that answers every watch with 410.
                self._watch_ok = False
                logger.info(
                    "informer %s: watch expired (%s); re-listing",
                    self._gvr.resource, e,
                )
                stop.wait(0.01)
            except Exception as e:  # noqa: BLE001 — informer must survive apiserver blips
                self._watch_ok = False
                delay = self._relist_backoff.next_delay()
                # A 429/503's Retry-After hint floors the jittered delay:
                # the server asked for AT LEAST that much quiet, and
                # relisting into its shed window only re-feeds the storm.
                retry_after = errors.retry_after_of(e)
                if retry_after is not None:
                    delay = max(delay, retry_after)
                logger.warning(
                    "informer %s: list/watch failed: %s; re-listing in %.1fs",
                    self._gvr.resource, e, delay,
                )
                stop.wait(delay)

    def _list_and_watch(self, stop: threading.Event) -> None:
        listing = self._api.list(
            self._gvr,
            self._namespace,
            label_selector=self._label_selector,
            field_selector=self._field_selector,
        )
        # A healthy LIST resets the relist backoff even if the WATCH below
        # dies every cycle (an LB idle-timeout resetting watches must not
        # escalate us to 30 s event-delivery gaps — client-go's reflector
        # resets on successful list the same way).
        self._relist_backoff.reset()
        metrics.INFORMER_RELISTS.labels(self._gvr.resource).inc()
        rv = listing.get("metadata", {}).get("resourceVersion")
        fresh = {
            obj_key(o): o
            for o in listing.get("items", [])
            if self._cache_filter is None or self._cache_filter(o)
        }
        with self._lock:
            old = self._store
            self._store = fresh
            self._index_rebuild()
            if racewitness.enabled():
                racewitness.note_access("Informer._store")
        self._watch_ok = True
        for key, obj in fresh.items():
            if key not in old:
                self._dispatch("ADDED", obj)
            elif old[key].get("metadata", {}).get("resourceVersion") != obj.get(
                "metadata", {}
            ).get("resourceVersion"):
                self._dispatch("MODIFIED", obj)
        for key, obj in old.items():
            if key not in fresh:
                self._dispatch("DELETED", obj)
        if racewitness.enabled():
            racewitness.note_hb_send("informer.synced")
        self._synced.set()

        try:
            self._watch_events(stop, rv)
        finally:
            # The watch is over — cleanly (a real apiserver closes streams
            # on its server-side timeout every few minutes), by stop, or by
            # exception: events are invisible until the next LIST lands, so
            # the cache is no longer delivery-fresh.  Without this, clean
            # closes would leave watch_healthy True across the whole relist
            # window — exactly the staleness the flag exists to expose.
            self._watch_ok = False

    def _watch_events(self, stop: threading.Event, rv) -> None:
        for event in self._api.watch(
            self._gvr,
            self._namespace,
            resource_version=rv,
            label_selector=self._label_selector,
            field_selector=self._field_selector,
            stop=stop,
        ):
            if stop.is_set():
                return
            etype, obj = event["type"], event["object"]
            if etype == "ERROR":
                # In-band watch termination (a Status object, not a
                # resource): raise the typed error so _run picks the right
                # recovery — Expired relists immediately, anything else
                # takes the backoff path.
                status = obj if isinstance(obj, dict) else {}
                raise errors.from_status(status, int(status.get("code") or 500))
            key = obj_key(obj)
            keep = etype != "DELETED" and (
                self._cache_filter is None or self._cache_filter(obj)
            )
            with self._lock:
                prev = self._store.get(key)
                if prev is not None:
                    self._index_drop(key, prev)
                if keep:
                    self._store[key] = obj
                    self._index_add(key, obj)
                else:
                    self._store.pop(key, None)
                if racewitness.enabled():
                    racewitness.note_access("Informer._store")
            if self._cache_filter is None:
                self._dispatch(etype, obj)
            elif keep:
                # Entering the cache by STARTING to match (e.g. a claim
                # gaining its allocation via MODIFIED) is an Add to
                # consumers, mirroring client-go's filtering handler.
                self._dispatch("ADDED" if prev is None else etype, obj)
            elif prev is not None:
                # Stopped matching the filter: evicted from the cache, and
                # handlers see the eviction the way client-go's filtered
                # informers surface it — the DELETED payload is the LAST
                # CACHED state (cleanup is keyed on what the handler saw),
                # not the non-matching object it never saw.
                self._dispatch("DELETED", prev)

    def _dispatch(self, etype: str, obj: dict) -> None:
        with self._dispatch_lock:
            for handler in self._handlers:
                try:
                    handler(etype, obj)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "informer %s handler failed", self._gvr.resource
                    )


class MutationCache:
    """Layer controller-written objects over an informer cache so a controller
    never acts on its own stale read.  Entries expire after ttl (the informer
    catches up well before that)."""

    def __init__(self, informer: Informer, ttl: float = 10.0):
        self._informer = informer
        self._ttl = ttl
        self._mutated: dict[tuple, tuple[float, dict]] = {}
        self._lock = lockwitness.make_lock("mutationcache.lock")

    def mutated(self, obj: dict) -> None:
        with self._lock:
            self._mutated[obj_key(obj)] = (time.monotonic() + self._ttl, obj)

    def get(self, name: str, namespace: Optional[str] = None) -> Optional[dict]:
        key = (namespace, name)
        cached = self._informer.get(name, namespace)
        with self._lock:
            entry = self._mutated.get(key)
            if entry is None:
                return cached
            expires, obj = entry
            if time.monotonic() > expires:
                del self._mutated[key]
                return cached
        if cached is not None:
            try:
                if int(cached["metadata"]["resourceVersion"]) >= int(
                    obj["metadata"]["resourceVersion"]
                ):
                    return cached  # informer caught up
            except (KeyError, ValueError):
                return cached
        return obj

"""Ambient deadline propagation for apiserver verbs.

The bind path is allowed to touch the apiserver in exactly one place —
the claim resolver's read-through GET — but "one place" is enough to
wedge: a NodePrepareResources whose fallback GET lands during an
apiserver latency spike used to sit in that GET for the client's full
socket timeout (30 s), sail past kubelet's gRPC deadline, and burn a gRPC
worker thread answering a call nobody was waiting for anymore.  The chaos
soak's ``apiserver_latency`` fault manufactures exactly this scenario.

The fix is the same one gRPC itself uses: a *deadline* that travels with
the request.  ``with api_deadline(seconds):`` establishes (or tightens —
nesting only ever shortens) a monotonic deadline in a ``contextvars``
context; every KubeAPI implementation consults it:

- ``FakeKube`` sleeps its injected RTT only up to the deadline, then
  raises :class:`tpudra.kube.errors.Timeout` — the fault the latency knob
  should produce, instead of unbounded blocking;
- ``KubeClient`` clamps its per-request socket timeout to the remaining
  budget and maps the socket timeout to the same typed error.

Deadlines are ambient rather than threaded through every call signature
because the verbs are behind the ``KubeAPI`` protocol shared by a dozen
call sites; a ``timeout=`` parameter on each would churn every signature
for what is fundamentally per-*request-context* state.  ``contextvars``
(not a bare thread-local) so call paths that fan out through an executor
can carry it along with ``contextvars.copy_context()`` — which is what
the DRA socket's claim-resolution pool does (grpcserver._resolve_all).

A raised :class:`~tpudra.kube.errors.Timeout` is retryable by contract:
kubelet re-calls a failed NodePrepareResources, the informer relist loop
backs off and retries, the publisher keeps its signals pending.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator, Optional

from tpudra.kube import errors

_DEADLINE: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "tpudra_api_deadline", default=None
)


@contextlib.contextmanager
def api_deadline(seconds: float) -> Iterator[float]:
    """Establish an ambient apiserver deadline ``seconds`` from now.

    Nested deadlines only tighten (the inner scope may not outlive the
    outer budget).  Yields the absolute monotonic deadline in force."""
    proposed = time.monotonic() + seconds
    current = _DEADLINE.get()
    effective = proposed if current is None else min(current, proposed)
    token = _DEADLINE.set(effective)
    try:
        yield effective
    finally:
        _DEADLINE.reset(token)


def remaining() -> Optional[float]:
    """Seconds left in the ambient deadline (negative when overrun), or
    None when no deadline is in force."""
    d = _DEADLINE.get()
    return None if d is None else d - time.monotonic()


def check(what: str = "request") -> None:
    """Raise :class:`errors.Timeout` if the ambient deadline has passed —
    the cheap guard a verb runs before doing real work."""
    rem = remaining()
    if rem is not None and rem <= 0:
        raise errors.Timeout(
            f"{what}: deadline exceeded by {-rem:.3f}s before it started"
        )


def clamp(timeout: float) -> float:
    """``timeout`` clamped to the remaining ambient budget (for handing to
    a socket-level API).  Raises :class:`errors.Timeout` when the budget
    is already spent — a zero-second socket timeout would surface as a
    confusing transport error instead of the typed deadline fault."""
    rem = remaining()
    if rem is None:
        return timeout
    if rem <= 0:
        raise errors.Timeout("deadline exceeded before the request was sent")
    return min(timeout, rem)

"""Group/Version/Resource identifiers for every API type the driver touches.

The analog of the typed clientsets the reference generates under
pkg/nvidia.com/ (client-gen/informer-gen, Makefile:117-165) — but since our
client is a generic REST layer, a GVR constant plus the dynamic client replaces
each generated typed client.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpudra import API_GROUP, API_VERSION


@dataclass(frozen=True)
class GVR:
    group: str  # "" for core
    version: str
    resource: str  # plural, lowercase
    kind: str
    namespaced: bool = True

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version

    @property
    def prefix(self) -> str:
        """URL path prefix: /api/v1 or /apis/<group>/<version>."""
        if self.group:
            return f"/apis/{self.group}/{self.version}"
        return f"/api/{self.version}"

    def path(self, namespace: str | None = None, name: str | None = None) -> str:
        parts = [self.prefix]
        if self.namespaced and namespace:
            parts.append(f"namespaces/{namespace}")
        parts.append(self.resource)
        if name:
            parts.append(name)
        return "/".join(parts)


# -- core/v1 ----------------------------------------------------------------

PODS = GVR("", "v1", "pods", "Pod")
NODES = GVR("", "v1", "nodes", "Node", namespaced=False)
NAMESPACES = GVR("", "v1", "namespaces", "Namespace", namespaced=False)
CONFIGMAPS = GVR("", "v1", "configmaps", "ConfigMap")
SERVICES = GVR("", "v1", "services", "Service")

# -- apps/v1 ----------------------------------------------------------------

DAEMONSETS = GVR("apps", "v1", "daemonsets", "DaemonSet")
DEPLOYMENTS = GVR("apps", "v1", "deployments", "Deployment")

# -- coordination.k8s.io ----------------------------------------------------

LEASES = GVR("coordination.k8s.io", "v1", "leases", "Lease")

# -- resource.k8s.io (DRA) --------------------------------------------------

RESOURCE_CLAIMS = GVR("resource.k8s.io", "v1", "resourceclaims", "ResourceClaim")
RESOURCE_CLAIM_TEMPLATES = GVR(
    "resource.k8s.io", "v1", "resourceclaimtemplates", "ResourceClaimTemplate"
)
RESOURCE_SLICES = GVR(
    "resource.k8s.io", "v1", "resourceslices", "ResourceSlice", namespaced=False
)
DEVICE_CLASSES = GVR(
    "resource.k8s.io", "v1", "deviceclasses", "DeviceClass", namespaced=False
)

# -- our CRDs (resource.tpu.google.com) -------------------------------------

COMPUTE_DOMAINS = GVR(API_GROUP, API_VERSION, "computedomains", "ComputeDomain")
COMPUTE_DOMAIN_CLIQUES = GVR(
    API_GROUP, API_VERSION, "computedomaincliques", "ComputeDomainClique"
)

ALL_GVRS = [
    PODS,
    NODES,
    NAMESPACES,
    CONFIGMAPS,
    SERVICES,
    DAEMONSETS,
    DEPLOYMENTS,
    LEASES,
    RESOURCE_CLAIMS,
    RESOURCE_CLAIM_TEMPLATES,
    RESOURCE_SLICES,
    DEVICE_CLASSES,
    COMPUTE_DOMAINS,
    COMPUTE_DOMAIN_CLIQUES,
]


def by_path(group: str, version: str, resource: str) -> GVR | None:
    for gvr in ALL_GVRS:
        if (gvr.group, gvr.version, gvr.resource) == (group, version, resource):
            return gvr
    return None

"""Per-verb request accounting over any ``KubeAPI`` implementation.

The production driver's apiserver footprint is invisible until something
counts it: the reference relies on client-go's ``rest_client_requests_total``
family; this wrapper is our analog, feeding
``tpudra_apiserver_requests_total{verb}`` plus an in-process counter table
that bench harnesses snapshot around a measurement window (QPS by verb =
window delta / wall time — docs/cluster-scale.md).

It wraps, never replaces: ``AccountingKube(FakeKube())`` in the cluster
harness, ``AccountingKube(KubeClient(...))`` in a binary — everything else
keeps talking plain ``KubeAPI``.  Unknown attributes (``react``,
``set_latency``, ``watch_stats``) pass through to the wrapped
implementation so test hooks keep working.
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional

from tpudra import lockwitness, metrics
from tpudra.kube.gvr import GVR

#: One label value per KubeAPI verb; ``update_status`` is its own verb the
#: way apiserver audit logs split status writes out (they hit a different
#: endpoint and a different controller's write budget).
VERBS = (
    "get",
    "list",
    "create",
    "update",
    "update_status",
    "patch",
    "delete",
    "watch",
)

# Labelled children resolved once: .labels() takes a registry lock and the
# wrapper sits on every control-plane request.
_VERB_CHILDREN = {v: metrics.APISERVER_REQUESTS_TOTAL.labels(v) for v in VERBS}


class AccountingKube:
    """A ``KubeAPI`` that counts every request by verb, then delegates."""

    def __init__(self, inner):
        self._inner = inner
        self._counts = {v: 0 for v in VERBS}
        self._counts_lock = lockwitness.make_lock("accounting.counts_lock")

    def _count(self, verb: str) -> None:
        with self._counts_lock:
            self._counts[verb] += 1
        # Outside the lock: the prometheus child takes its own mutex.
        _VERB_CHILDREN[verb].inc()

    def snapshot(self) -> dict[str, int]:
        """Cumulative per-verb request counts; subtract two snapshots for a
        measurement window."""
        with self._counts_lock:
            return dict(self._counts)

    @staticmethod
    def window(before: dict[str, int], after: dict[str, int]) -> dict[str, int]:
        """Per-verb deltas between two snapshots, zero verbs dropped."""
        return {
            v: after.get(v, 0) - before.get(v, 0)
            for v in VERBS
            if after.get(v, 0) - before.get(v, 0)
        }

    # -- KubeAPI -------------------------------------------------------------

    def get(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> dict:
        self._count("get")
        return self._inner.get(gvr, name, namespace)

    def list(
        self,
        gvr: GVR,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> dict:
        self._count("list")
        return self._inner.list(
            gvr,
            namespace,
            label_selector=label_selector,
            field_selector=field_selector,
        )

    def create(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict:
        self._count("create")
        return self._inner.create(gvr, obj, namespace)

    def update(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict:
        self._count("update")
        return self._inner.update(gvr, obj, namespace)

    def update_status(
        self, gvr: GVR, obj: dict, namespace: Optional[str] = None
    ) -> dict:
        self._count("update_status")
        return self._inner.update_status(gvr, obj, namespace)

    def patch(
        self, gvr: GVR, name: str, patch: dict, namespace: Optional[str] = None
    ) -> dict:
        self._count("patch")
        return self._inner.patch(gvr, name, patch, namespace)

    def delete(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> None:
        self._count("delete")
        self._inner.delete(gvr, name, namespace)

    def watch(
        self,
        gvr: GVR,
        namespace: Optional[str] = None,
        resource_version: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
        stop: Optional[threading.Event] = None,
    ) -> Iterator[dict]:
        # One count per watch ESTABLISHMENT; streamed events are free, the
        # same way the client-side QPS limiter charges watches (client.py).
        self._count("watch")
        return self._inner.watch(
            gvr,
            namespace,
            resource_version=resource_version,
            label_selector=label_selector,
            field_selector=field_selector,
            stop=stop,
        )

    # -- passthrough ---------------------------------------------------------

    def __getattr__(self, name: str):
        # Test hooks and fake-only surfaces (react, set_latency,
        # watch_stats) reach the wrapped implementation untouched.
        return getattr(self._inner, name)

"""API error model mirroring k8s apimachinery's StatusError semantics."""

from __future__ import annotations


class ApiError(Exception):
    code = 500
    reason = "InternalError"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason

    def to_status(self) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Status",
            "status": "Failure",
            "message": self.message,
            "reason": self.reason,
            "code": self.code,
        }


class NotFound(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExists(ApiError):
    code = 409
    reason = "AlreadyExists"


class Conflict(ApiError):
    code = 409
    reason = "Conflict"


class Invalid(ApiError):
    code = 422
    reason = "Invalid"


class BadRequest(ApiError):
    code = 400
    reason = "BadRequest"


class Forbidden(ApiError):
    code = 403
    reason = "Forbidden"


class Timeout(ApiError):
    """504 — the request outlived its deadline (apimachinery's
    StatusReasonTimeout).  Raised by the fake when an injected latency
    spike would exceed the ambient ``kube.deadline`` budget and by the
    real client on a socket timeout: a bind-path apiserver call fails
    FAST and retryably instead of wedging past its caller's gRPC
    deadline."""

    code = 504
    reason = "Timeout"


class Expired(ApiError):
    """410 Gone — the requested resourceVersion is older than the server's
    retained watch history (apimachinery's StatusReasonExpired).  A watch
    client answers it with a fresh LIST + watch, never a blind retry: the
    events between its resourceVersion and the server's horizon are
    unrecoverable."""

    code = 410
    reason = "Expired"


class TooManyRequests(ApiError):
    """429 — the apiserver is shedding load (apimachinery's
    StatusReasonTooManyRequests; API Priority and Fairness rejections,
    max-inflight overflow).  Carries the server's ``Retry-After`` hint in
    ``retry_after_s`` (None when the server sent none): retry loops must
    wait AT LEAST that long — but still through the shared full-jitter
    :class:`tpudra.backoff.Backoff`, so a storm of 429'd clients does not
    march back in lockstep at exactly the hinted second."""

    code = 429
    reason = "TooManyRequests"

    def __init__(self, message: str = "", retry_after_s: "float | None" = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ServiceUnavailable(ApiError):
    """503 — the apiserver (or what fronts it) cannot serve at all right
    now: rolling restart, etcd quorum loss, a dead load-balancer backend.
    The shape a full outage window presents to every client.  May carry a
    ``Retry-After`` hint like 429."""

    code = 503
    reason = "ServiceUnavailable"

    def __init__(self, message: str = "", retry_after_s: "float | None" = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class InternalError(ApiError):
    """500 — the server blew up mid-request (apimachinery's
    StatusReasonInternalError).  Distinct from the ApiError base only so
    injected 500 storms and parsed statuses round-trip a stable reason."""

    code = 500
    reason = "InternalError"


_BY_REASON = {
    cls.reason: cls
    for cls in (
        NotFound, AlreadyExists, Conflict, Invalid, BadRequest, Forbidden,
        Expired, Timeout, TooManyRequests, ServiceUnavailable,
    )
}


def from_status(status: dict, http_code: int) -> ApiError:
    reason = status.get("reason", "")
    message = status.get("message", "")
    cls = _BY_REASON.get(reason)
    if cls is None:
        cls = {
            404: NotFound,
            409: Conflict,
            422: Invalid,
            400: BadRequest,
            403: Forbidden,
            410: Expired,
            429: TooManyRequests,
            500: InternalError,
            503: ServiceUnavailable,
            504: Timeout,
        }.get(http_code, ApiError)
    err = cls(message)
    if cls is ApiError and http_code:
        # Untyped failure (unmapped reason AND code — 401, 413, ...):
        # carry the REAL transport code.  The class default (500) would
        # make is_retryable() blind-retry permanent failures through the
        # whole backoff schedule.
        err.code = http_code
    return err


#: Codes a client may retry blindly (after backoff): the request failed for
#: server-side capacity/availability reasons, not because of anything about
#: the request itself.  409 Conflict is deliberately absent — retrying a
#: conflicted write without re-reading re-submits stale state.
RETRYABLE_CODES = frozenset({429, 500, 503, 504})


def is_retryable(exc: BaseException) -> bool:
    """True when ``exc`` is an ApiError a retry loop should simply retry
    (through the shared backoff policy) rather than surface."""
    return isinstance(exc, ApiError) and exc.code in RETRYABLE_CODES


def retry_after_of(exc: BaseException) -> "float | None":
    """The server's Retry-After hint carried by ``exc`` (429/503), or None.
    Callers take ``max(backoff_delay, retry_after_of(e) or 0)`` — the hint
    is a FLOOR under the jittered delay, never a replacement for it."""
    ra = getattr(exc, "retry_after_s", None)
    if ra is None:
        return None
    try:
        ra = float(ra)
    except (TypeError, ValueError):
        return None
    return ra if ra >= 0 else None


def parse_retry_after(value: "str | None") -> "float | None":
    """Parse an HTTP ``Retry-After`` header value: delta-seconds per RFC
    9110 (the only form apiservers emit).  HTTP-date values, garbage, and
    non-finite floats ("inf", "1e999" — which would turn every delay
    floor into a forever-sleep) return None — a hint too mangled to trust
    is no hint."""
    if not value:
        return None
    value = value.strip()
    try:
        seconds = float(value)
    except ValueError:
        return None
    import math

    if not math.isfinite(seconds) or seconds < 0:
        return None
    return seconds

"""API error model mirroring k8s apimachinery's StatusError semantics."""

from __future__ import annotations


class ApiError(Exception):
    code = 500
    reason = "InternalError"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason

    def to_status(self) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Status",
            "status": "Failure",
            "message": self.message,
            "reason": self.reason,
            "code": self.code,
        }


class NotFound(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExists(ApiError):
    code = 409
    reason = "AlreadyExists"


class Conflict(ApiError):
    code = 409
    reason = "Conflict"


class Invalid(ApiError):
    code = 422
    reason = "Invalid"


class BadRequest(ApiError):
    code = 400
    reason = "BadRequest"


class Forbidden(ApiError):
    code = 403
    reason = "Forbidden"


class Timeout(ApiError):
    """504 — the request outlived its deadline (apimachinery's
    StatusReasonTimeout).  Raised by the fake when an injected latency
    spike would exceed the ambient ``kube.deadline`` budget and by the
    real client on a socket timeout: a bind-path apiserver call fails
    FAST and retryably instead of wedging past its caller's gRPC
    deadline."""

    code = 504
    reason = "Timeout"


class Expired(ApiError):
    """410 Gone — the requested resourceVersion is older than the server's
    retained watch history (apimachinery's StatusReasonExpired).  A watch
    client answers it with a fresh LIST + watch, never a blind retry: the
    events between its resourceVersion and the server's horizon are
    unrecoverable."""

    code = 410
    reason = "Expired"


_BY_REASON = {
    cls.reason: cls
    for cls in (
        NotFound, AlreadyExists, Conflict, Invalid, BadRequest, Forbidden,
        Expired, Timeout,
    )
}


def from_status(status: dict, http_code: int) -> ApiError:
    reason = status.get("reason", "")
    message = status.get("message", "")
    cls = _BY_REASON.get(reason)
    if cls is None:
        cls = {
            404: NotFound,
            409: Conflict,
            422: Invalid,
            400: BadRequest,
            403: Forbidden,
            410: Expired,
            504: Timeout,
        }.get(http_code, ApiError)
    return cls(message)

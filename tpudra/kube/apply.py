"""Create-or-update helpers shared by the node plugins."""

from __future__ import annotations

import logging

from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import Conflict, NotFound

logger = logging.getLogger(__name__)


def apply_resource_slice(kube: KubeAPI, obj: dict, attempts: int = 3) -> bool:
    """Create the slice, or update it carrying the live resourceVersion;
    retries conflicts by re-reading.  Returns False if conflicts persist
    (the caller's next publish supersedes the stale slice anyway)."""
    name = obj["metadata"]["name"]
    for _ in range(attempts):
        try:
            existing = kube.get(gvr.RESOURCE_SLICES, name)
        except NotFound:
            kube.create(gvr.RESOURCE_SLICES, obj)
            return True
        obj["metadata"]["resourceVersion"] = existing["metadata"].get("resourceVersion")
        try:
            kube.update(gvr.RESOURCE_SLICES, obj)
            return True
        except Conflict:
            continue
    logger.warning("giving up on ResourceSlice %s after repeated conflicts", name)
    return False

"""Create-or-update helpers shared by the node plugins."""

from __future__ import annotations

import logging
from typing import Optional

from tpudra.kube import gvr
from tpudra.kube.client import KubeAPI
from tpudra.kube.errors import Conflict, NotFound

logger = logging.getLogger(__name__)


def next_pool_generation(kube: KubeAPI, node_name: str, pool_name: str) -> int:
    """Seed a publisher's pool generation from the highest generation already
    live for this pool, so a restarted driver's fresh slices outrank any
    leftovers from the previous process (DRA consumers trust the highest
    generation seen for a pool; starting back at 1 would let a stale slice
    shadow the real device set)."""
    highest = 0
    try:
        existing = kube.list(
            gvr.RESOURCE_SLICES, field_selector=f"spec.nodeName={node_name}"
        )
    except Exception:  # noqa: BLE001 — publication must not die on list
        logger.warning(
            "could not list live slices to seed pool %s generation; "
            "starting at 1 — stale higher-generation slices may shadow "
            "fresh publishes until overtaken",
            pool_name,
            exc_info=True,
        )
        return 1
    for item in existing.get("items", []):
        pool = item.get("spec", {}).get("pool", {})
        if pool.get("name") == pool_name:
            highest = max(highest, int(pool.get("generation", 0)))
    return highest + 1


def delete_stale_slices(
    kube: KubeAPI, node_name: str, name_prefix: str, keep: set[str]
) -> None:
    """Remove slices this node published in a previous shape (naming or
    chunking changes across an upgrade) — orphans would keep advertising
    duplicate devices.  Shared by both node plugins."""
    try:
        existing = kube.list(
            gvr.RESOURCE_SLICES, field_selector=f"spec.nodeName={node_name}"
        )
    except Exception:  # noqa: BLE001 — publication must not die on list
        return
    for item in existing.get("items", []):
        name = item.get("metadata", {}).get("name", "")
        if name.startswith(name_prefix) and name not in keep:
            try:
                kube.delete(gvr.RESOURCE_SLICES, name)
            except NotFound:
                pass


def publish_slices(
    kube: KubeAPI, slices: list[dict], node_name: str, name_prefix: str
) -> None:
    """Apply a freshly built slice set, then GC slices from a previous shape.
    The shared tail of both node plugins' publish paths."""
    keep = {s["metadata"]["name"] for s in slices}
    for s in slices:
        apply_resource_slice(kube, s)
    delete_stale_slices(kube, node_name, name_prefix, keep)


class BulkSlicePublisher:
    """Coalesces many nodes' slice publications into one apiserver pass.

    The per-node path costs ~3 requests per node (GET per slice, CREATE/
    UPDATE, plus a LIST for stale-GC), and every LIST scans the cluster's
    whole slice set — O(nodes²) work to bring an N-node cluster up.  When
    hundreds of drivers share a process (tpudra/sim/cluster.py), ONE LIST
    seeds a name→resourceVersion map that answers every node's existence
    check and stale-GC; each slice then costs exactly one write.  Pass an
    instance as ``Driver.publish_resources(applier=...)``.

    Single-writer assumption (the harness IS the only publisher for its
    nodes): a concurrent writer surfaces as Conflict, which falls back to
    the read-modify ``apply_resource_slice`` path for that slice only.
    """

    def __init__(self, kube: KubeAPI):
        self._kube = kube
        self._rv: Optional[dict[str, str]] = None  # name -> resourceVersion

    def _seed(self) -> dict[str, str]:
        if self._rv is None:
            listing = self._kube.list(gvr.RESOURCE_SLICES)
            self._rv = {
                item["metadata"]["name"]: item["metadata"].get("resourceVersion", "")
                for item in listing.get("items", [])
            }
        return self._rv

    def __call__(
        self, slices: list[dict], node_name: str, name_prefix: str
    ) -> None:
        rv = self._seed()
        keep = {s["metadata"]["name"] for s in slices}
        for s in slices:
            name = s["metadata"]["name"]
            if name not in rv:
                created = self._kube.create(gvr.RESOURCE_SLICES, s)
                rv[name] = created["metadata"].get("resourceVersion", "")
                continue
            s["metadata"]["resourceVersion"] = rv[name]
            try:
                updated = self._kube.update(gvr.RESOURCE_SLICES, s)
                rv[name] = updated["metadata"].get("resourceVersion", "")
            except (Conflict, NotFound):
                # Someone else wrote — or deleted — this slice since the
                # seed: per-slice fallback re-reads (re-creating a deleted
                # slice), and the seeded entry is refreshed so the next
                # pass is clean again.  One stale slice must not abort the
                # other N-1 nodes' publications.
                s["metadata"].pop("resourceVersion", None)
                apply_resource_slice(self._kube, s)
                try:
                    live = self._kube.get(gvr.RESOURCE_SLICES, name)
                    rv[name] = live["metadata"].get("resourceVersion", "")
                except NotFound:
                    rv.pop(name, None)
        for name in [n for n in rv if n.startswith(name_prefix) and n not in keep]:
            try:
                self._kube.delete(gvr.RESOURCE_SLICES, name)
            except NotFound:
                pass
            rv.pop(name, None)


def apply_resource_slice(kube: KubeAPI, obj: dict, attempts: int = 3) -> bool:
    """Create the slice, or update it carrying the live resourceVersion;
    retries conflicts by re-reading.  Returns False if conflicts persist
    (the caller's next publish supersedes the stale slice anyway)."""
    name = obj["metadata"]["name"]
    for _ in range(attempts):
        try:
            existing = kube.get(gvr.RESOURCE_SLICES, name)
        except NotFound:
            kube.create(gvr.RESOURCE_SLICES, obj)
            return True
        obj["metadata"]["resourceVersion"] = existing["metadata"].get("resourceVersion")
        try:
            kube.update(gvr.RESOURCE_SLICES, obj)
            return True
        except Conflict:
            continue
    logger.warning("giving up on ResourceSlice %s after repeated conflicts", name)
    return False

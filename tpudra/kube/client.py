"""Minimal Kubernetes REST client.

Replaces the reference's generated clientsets + client-go (pkg/flags/kubeclient.go:33-118)
with a thin dynamic client: every driver component talks to the apiserver
through the ``KubeAPI`` protocol, implemented here over HTTP(S) and by
kube/fake.py in memory.  Auth: in-cluster service account, kubeconfig bearer
token/client cert, or anonymous (test server).
"""

from __future__ import annotations

import json
import os
import ssl
import threading
import urllib.parse
import urllib.request
from typing import Iterator, Optional, Protocol

import yaml

from tpudra.kube import deadline, errors
from tpudra.kube.gvr import GVR

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeAPI(Protocol):
    """The API surface shared by KubeClient and FakeKube."""

    def get(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> dict: ...

    def list(
        self,
        gvr: GVR,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> dict: ...

    def create(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict: ...

    def update(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict: ...

    def update_status(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict: ...

    def patch(
        self, gvr: GVR, name: str, patch: dict, namespace: Optional[str] = None
    ) -> dict: ...

    def delete(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> None: ...

    def watch(
        self,
        gvr: GVR,
        namespace: Optional[str] = None,
        resource_version: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
        stop: Optional[threading.Event] = None,
    ) -> Iterator[dict]: ...


class KubeClient:
    def __init__(
        self,
        server: str,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
        timeout: float = 30.0,
        qps: float = 0.0,
        burst: int = 0,
    ):
        self._server = server.rstrip("/")
        self._token = token
        self._timeout = timeout
        # Client-side QPS/burst (kubeclient.go:33-118 analog), reusing the
        # workqueue's reservation bucket (FIFO-fair: each caller sleeps
        # out its own reservation).  Default unlimited: tests and the fake
        # server need no throttle; the binaries pass the KUBE_API_QPS/
        # KUBE_API_BURST flag values (tpudra/flags.py make_kube_client).
        self._limiter = None
        if qps > 0:
            from tpudra.workqueue import TokenBucket

            self._limiter = TokenBucket(qps, max(burst, 1))
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if server.startswith("https"):
            if insecure:
                self._ssl_ctx = ssl._create_unverified_context()
            else:
                self._ssl_ctx = ssl.create_default_context(cafile=ca_file)

    # -- construction helpers ----------------------------------------------

    @classmethod
    def in_cluster(cls, qps: float = 0.0, burst: int = 0) -> "KubeClient":
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(SERVICE_ACCOUNT_DIR, "token")) as f:
            token = f.read().strip()
        return cls(
            f"https://{host}:{port}",
            token=token,
            ca_file=os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt"),
            qps=qps,
            burst=burst,
        )

    @classmethod
    def from_kubeconfig(
        cls,
        path: Optional[str] = None,
        context: Optional[str] = None,
        qps: float = 0.0,
        burst: int = 0,
    ) -> "KubeClient":
        path = path or os.environ.get("KUBECONFIG", os.path.expanduser("~/.kube/config"))
        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = context or cfg.get("current-context")
        ctx = next(c["context"] for c in cfg["contexts"] if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in cfg["clusters"] if c["name"] == ctx["cluster"])
        user = next(u["user"] for u in cfg["users"] if u["name"] == ctx["user"])
        token = user.get("token")
        return cls(
            cluster["server"],
            token=token,
            ca_file=cluster.get("certificate-authority"),
            insecure=cluster.get("insecure-skip-tls-verify", False),
            qps=qps,
            burst=burst,
        )

    @classmethod
    def auto(cls, qps: float = 0.0, burst: int = 0) -> "KubeClient":
        """In-cluster when available, else kubeconfig; KUBE_API_SERVER
        overrides both (test harness)."""
        override = os.environ.get("KUBE_API_SERVER")
        if override:
            return cls(override, qps=qps, burst=burst)
        if os.path.exists(os.path.join(SERVICE_ACCOUNT_DIR, "token")):
            return cls.in_cluster(qps=qps, burst=burst)
        return cls.from_kubeconfig(qps=qps, burst=burst)

    # -- HTTP ---------------------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        query: Optional[dict] = None,
        body: Optional[dict] = None,
        stream: bool = False,
        timeout: Optional[float] = None,
    ):
        # One token per request (streamed watch events are free — the
        # token paid for the watch's establishment, matching client-go).
        if self._limiter is not None:
            wait = self._limiter.reserve()
            if wait > 0:
                import time

                time.sleep(wait)
        url = self._server + path
        if query:
            url += "?" + urllib.parse.urlencode({k: v for k, v in query.items() if v})
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            content_type = (
                "application/merge-patch+json" if method == "PATCH" else "application/json"
            )
            req.add_header("Content-Type", content_type)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        # Ambient deadline (kube/deadline.py): the socket timeout never
        # exceeds the caller's remaining budget, and an exhausted budget
        # fails typed-and-fast instead of opening a doomed connection.
        # Watches opt out via their explicit hour-long stream timeout
        # (the deadline covers request verbs, not the push channel).
        effective = timeout or self._timeout
        if not stream:
            effective = deadline.clamp(effective)
        try:
            resp = urllib.request.urlopen(
                req, timeout=effective, context=self._ssl_ctx
            )
        except urllib.error.HTTPError as e:
            payload = e.read()
            try:
                status = json.loads(payload)
            except (ValueError, TypeError):
                status = {"message": payload.decode(errors="replace")}
            err = errors.from_status(status, e.code)
            # A 429/503's Retry-After hint travels on the typed error so
            # retry loops can floor their backoff on it — clamped to the
            # caller's remaining ambient deadline: a hint that outlives
            # the budget is an instruction to fail, not to wait.
            retry_after = errors.parse_retry_after(e.headers.get("Retry-After"))
            if retry_after is not None and hasattr(err, "retry_after_s"):
                rem = deadline.remaining()
                if rem is not None:
                    retry_after = min(retry_after, max(0.0, rem))
                err.retry_after_s = retry_after
            raise err from None
        except TimeoutError as e:
            raise errors.Timeout(
                f"{method} {path}: no response within {effective:.1f}s"
            ) from e
        except urllib.error.URLError as e:
            # HTTPError was handled above (it subclasses URLError); what is
            # left is transport-level.  socket timeouts become the typed
            # deadline fault; everything else keeps its original shape.
            if isinstance(getattr(e, "reason", None), TimeoutError):
                raise errors.Timeout(
                    f"{method} {path}: no response within {effective:.1f}s"
                ) from e
            raise
        if stream:
            return resp
        try:
            with resp:
                payload = resp.read()
        except TimeoutError as e:
            # The server stalled mid-body (headers landed, the read timed
            # out): same typed fault as a connect/headers timeout, or the
            # retryable-504 contract would leak a raw TimeoutError.
            raise errors.Timeout(
                f"{method} {path}: response body stalled past {effective:.1f}s"
            ) from e
        return json.loads(payload) if payload else None

    # -- KubeAPI ------------------------------------------------------------

    def get(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> dict:
        return self._request("GET", gvr.path(namespace, name))

    def list(
        self,
        gvr: GVR,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
    ) -> dict:
        return self._request(
            "GET",
            gvr.path(namespace),
            query={"labelSelector": label_selector, "fieldSelector": field_selector},
        )

    def create(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict:
        ns = obj.get("metadata", {}).get("namespace") or namespace
        return self._request("POST", gvr.path(ns), body=obj)

    def update(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict:
        meta = obj["metadata"]
        ns = meta.get("namespace") or namespace
        return self._request("PUT", gvr.path(ns, meta["name"]), body=obj)

    def update_status(self, gvr: GVR, obj: dict, namespace: Optional[str] = None) -> dict:
        meta = obj["metadata"]
        ns = meta.get("namespace") or namespace
        return self._request("PUT", gvr.path(ns, meta["name"]) + "/status", body=obj)

    def patch(
        self, gvr: GVR, name: str, patch: dict, namespace: Optional[str] = None
    ) -> dict:
        return self._request("PATCH", gvr.path(namespace, name), body=patch)

    def delete(self, gvr: GVR, name: str, namespace: Optional[str] = None) -> None:
        self._request("DELETE", gvr.path(namespace, name))

    def watch(
        self,
        gvr: GVR,
        namespace: Optional[str] = None,
        resource_version: Optional[str] = None,
        label_selector: Optional[str] = None,
        field_selector: Optional[str] = None,
        stop: Optional[threading.Event] = None,
    ) -> Iterator[dict]:
        resp = self._request(
            "GET",
            gvr.path(namespace),
            query={
                "watch": "true",
                "resourceVersion": resource_version,
                "labelSelector": label_selector,
                "fieldSelector": field_selector,
            },
            stream=True,
            timeout=3600.0,
        )
        with resp:
            for line in resp:
                if stop is not None and stop.is_set():
                    return
                line = line.strip()
                if line:
                    yield json.loads(line)

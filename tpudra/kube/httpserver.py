"""HTTP frontend serving a FakeKube over the Kubernetes REST wire format.

This is the in-process replacement for the reference's kind-cluster test
harness (demo/clusters/kind): the real KubeClient talks real HTTP to this
server, so client, controllers, and plugins are all exercised over the same
wire protocol they use in production — without a cluster.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpudra.kube import errors
from tpudra.kube.fake import FakeKube
from tpudra.kube.gvr import by_path


def _parse_path(path: str):
    """Return (gvr, namespace, name, subresource) or raise BadRequest."""
    parts = [p for p in path.split("/") if p]
    # /api/v1/... (core) or /apis/<group>/<version>/...
    if not parts:
        raise errors.BadRequest("empty path")
    if parts[0] == "api" and len(parts) >= 2:
        group, version = "", parts[1]
        rest = parts[2:]
    elif parts[0] == "apis" and len(parts) >= 3:
        group, version = parts[1], parts[2]
        rest = parts[3:]
    else:
        raise errors.BadRequest(f"unrecognized path {path!r}")
    namespace = None
    # "/namespaces/<ns>/<resource>..." is a namespace prefix; a bare
    # "/namespaces[/<name>]" is the cluster-scoped Namespace resource.
    if len(rest) >= 3 and rest[0] == "namespaces":
        namespace = rest[1]
        rest = rest[2:]
    if not rest:
        raise errors.BadRequest(f"no resource in path {path!r}")
    resource, rest = rest[0], rest[1:]
    name = rest[0] if rest else None
    subresource = rest[1] if len(rest) > 1 else None
    gvr = by_path(group, version, resource)
    if gvr is None:
        raise errors.NotFound(f"the server could not find resource {resource!r} in {group}/{version}")
    return gvr, namespace, name, subresource


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    fake: FakeKube = None  # set by serve()

    def log_message(self, *args):  # silence request logging
        pass

    def _send_json(
        self, code: int, obj: dict, extra_headers: dict | None = None
    ) -> None:
        payload = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _send_error(self, e: errors.ApiError) -> None:
        # 429/503 carry the server's backpressure hint the way a real
        # apiserver does — as a Retry-After header, so KubeClient's parse
        # path is exercised end-to-end over this frontend.
        headers = None
        retry_after = getattr(e, "retry_after_s", None)
        if retry_after is not None:
            headers = {"Retry-After": f"{float(retry_after):g}"}
        self._send_json(e.code, e.to_status(), extra_headers=headers)

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b""
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except ValueError as e:
            raise errors.BadRequest(f"invalid JSON body: {e}") from None

    def _dispatch(self, method: str) -> None:
        parsed = urllib.parse.urlparse(self.path)
        query = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        try:
            gvr, namespace, name, subresource = _parse_path(parsed.path)
            if method == "GET" and name is None and query.get("watch") == "true":
                self._serve_watch(gvr, namespace, query)
                return
            if method == "GET" and name is None:
                out = self.fake.list(
                    gvr,
                    namespace,
                    label_selector=query.get("labelSelector"),
                    field_selector=query.get("fieldSelector"),
                )
            elif method == "GET":
                out = self.fake.get(gvr, name, namespace)
            elif method == "POST":
                out = self.fake.create(gvr, self._body(), namespace)
            elif method == "PUT" and subresource == "status":
                out = self.fake.update_status(gvr, self._body(), namespace)
            elif method == "PUT":
                out = self.fake.update(gvr, self._body(), namespace)
            elif method == "PATCH":
                out = self.fake.patch(gvr, name, self._body(), namespace)
            elif method == "DELETE":
                self.fake.delete(gvr, name, namespace)
                out = {"apiVersion": "v1", "kind": "Status", "status": "Success"}
            else:
                raise errors.BadRequest(f"unsupported method {method}")
            self._send_json(200, out)
        except errors.ApiError as e:
            self._send_error(e)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _serve_watch(self, gvr, namespace, query) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def write_chunk(data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        stop = threading.Event()
        try:
            for event in self.fake.watch(
                gvr,
                namespace,
                resource_version=query.get("resourceVersion"),
                label_selector=query.get("labelSelector"),
                field_selector=query.get("fieldSelector"),
                stop=stop,
            ):
                write_chunk(json.dumps(event).encode() + b"\n")
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            stop.set()
        try:
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_PUT(self):
        self._dispatch("PUT")

    def do_PATCH(self):
        self._dispatch("PATCH")

    def do_DELETE(self):
        self._dispatch("DELETE")


class FakeKubeServer:
    """Serve a FakeKube over HTTP on localhost; use as a context manager."""

    def __init__(
        self,
        fake: FakeKube | None = None,
        port: int = 0,
        latency_s: float = 0.0,
    ):
        self.fake = fake or FakeKube()
        if latency_s:
            # Injected apiserver RTT for bind-path A/B runs — the HTTP
            # server threads inherit the fake's per-request sleep, so the
            # real client experiences the latency over the wire too.
            self.fake.set_latency(latency_s)
        handler = type("BoundHandler", (_Handler,), {"fake": self.fake})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def start(self) -> "FakeKubeServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "FakeKubeServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

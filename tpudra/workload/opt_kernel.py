"""Fused single-pass AdamW update (pallas).

The tree-map AdamW (model.adamw_bf16_moments) runs as several XLA-fused
loops per leaf — moment updates, the update rule, the parameter add — and
BASELINE.md's step decomposition measures the whole optimizer phase at
~18 ms on the 472M flagship, ~520 GB/s effective HBM against the chip's
~819: the separate passes re-read params/grads/moments.  This kernel does
the entire update in ONE sweep per leaf: read (p, g, m, v), write
(p', m', v'), with the moment arithmetic in f32 and moments stored bf16,
exactly matching the tree-map semantics bit-for-bit in f32 math.

Ideal traffic at the flagship: (4+4+2+2) read + (4+2+2) write = 20 B per
param → ~9.4 GB/step → ~11.5 ms at peak; whether the fusion actually
recovers the gap is measured, not assumed — bench.py extras.ab.opt_fused
records the A/B every round, and the default (ModelConfig.opt_impl)
follows the measurement.

Measured on v5e (round 4, same-session baseline): the fused path LOSES —
418.7 ms / 60.2% MFU vs the tree-map's 379.4 ms / 66.4% at the flagship
config, i.e. the kernel costs ~39 ms where the whole XLA-fused optimizer
phase costs ~18.  XLA already fuses the tree-map update into few
near-peak passes; this kernel's per-leaf launches and pad/reshape copies
outweigh the single-sweep saving, and the one knob that could amortize
them (bigger blocks) exceeds the 16 MB VMEM budget at 512 rows.  So
``opt_impl="tree"`` stays the default; the kernel remains as the
measured-and-rejected alternative, re-measured each round like ce_fused.

Leaves are flattened to [rows, 1024] lane-aligned blocks; sizes that
don't divide pad with zeros (pad lanes compute 0/eps = 0 updates and are
sliced away).  Aliasing maps the padded p/m/v inputs onto the outputs so
jit-donated buffers update in place.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

COLS = 1024
BLOCK_ROWS = 256  # 512-row blocks double-buffer past the 16 MB VMEM budget
# (in 6 MB + out 4 MB per block, x2 pipelining) and fail Mosaic compile


def _kernel(c_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref, vo_ref,
            *, lr, b1, b2, eps, wd):
    g = g_ref[...].astype(jnp.float32)
    # bf16-round the moments BEFORE the update rule reads them — the
    # tree-map path stores then re-reads them, so parity requires the
    # rounded values, not the transient f32 ones.
    m16 = (b1 * m_ref[...].astype(jnp.float32) + (1.0 - b1) * g).astype(
        jnp.bfloat16
    )
    v16 = (b2 * v_ref[...].astype(jnp.float32) + (1.0 - b2) * g * g).astype(
        jnp.bfloat16
    )
    c1 = c_ref[0, 0]
    c2 = c_ref[0, 1]
    mhat = m16.astype(jnp.float32) / c1
    vhat = v16.astype(jnp.float32) / c2
    p = p_ref[...]
    po_ref[...] = p - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * p)
    mo_ref[...] = m16
    vo_ref[...] = v16


def _pad2d(x, rows):
    n = x.size
    flat = x.reshape(-1)
    total = rows * COLS
    if total != n:
        flat = jnp.pad(flat, (0, total - n))
    return flat.reshape(rows, COLS)


def _leaf_update(p, g, m, v, c12, *, lr, b1, b2, eps, wd, interpret):
    # No jit here: the caller (train_step) is the jit boundary, and the
    # input_output_aliases below give the in-place behavior under it.
    from jax.experimental import pallas as pl

    shape, dtype = p.shape, p.dtype
    n = p.size
    rows = -(-n // COLS)
    rows = -(-rows // BLOCK_ROWS) * BLOCK_ROWS
    p2 = _pad2d(p.astype(jnp.float32), rows)
    g2 = _pad2d(g.astype(jnp.float32), rows)
    m2 = _pad2d(m, rows)
    v2 = _pad2d(v, rows)
    blk = lambda: pl.BlockSpec(  # noqa: E731 — dtypes live in out_shape
        (BLOCK_ROWS, COLS), lambda i: (i, 0)
    )
    po, mo, vo = pl.pallas_call(
        functools.partial(_kernel, lr=lr, b1=b1, b2=b2, eps=eps, wd=wd),
        grid=(rows // BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec((1, 2), lambda i: (0, 0)),  # c1, c2
            blk(), blk(), blk(), blk(),
        ],
        out_specs=[blk(), blk(), blk()],
        out_shape=[
            jax.ShapeDtypeStruct((rows, COLS), jnp.float32),
            jax.ShapeDtypeStruct((rows, COLS), jnp.bfloat16),
            jax.ShapeDtypeStruct((rows, COLS), jnp.bfloat16),
        ],
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(c12, p2, g2, m2, v2)
    if rows * COLS == n:
        unpad = lambda x: x.reshape(shape)  # noqa: E731 — reshape only
    else:
        unpad = lambda x: x.reshape(-1)[:n].reshape(shape)  # noqa: E731
    return unpad(po).astype(dtype), unpad(mo), unpad(vo)


def fused_adamw(learning_rate: float, b1=0.9, b2=0.999, eps=1e-8, wd=1e-4):
    """(init, apply) with the same state as model.adamw_bf16_moments
    ((mu, nu, count), both moments bf16) but a one-sweep apply that
    returns NEW PARAMS directly (the add is part of the fusion).

    apply(params, grads, state) -> (new_params, new_state).
    """

    def init(params):
        zeros16 = lambda p: jnp.zeros_like(p, dtype=jnp.bfloat16)  # noqa: E731
        return (
            jax.tree.map(zeros16, params),
            jax.tree.map(zeros16, params),
            jnp.zeros((), jnp.int32),
        )

    def apply(params, grads, state):
        mu, nu, count = state
        count = count + 1
        cf = count.astype(jnp.float32)
        c12 = jnp.stack([1.0 - b1**cf, 1.0 - b2**cf]).reshape(1, 2)
        interpret = jax.default_backend() != "tpu"
        flat, treedef = jax.tree.flatten(params)
        fm = jax.tree.flatten(mu)[0]
        fv = jax.tree.flatten(nu)[0]
        fg = jax.tree.flatten(grads)[0]
        outs = [
            _leaf_update(
                p, g, m, v, c12,
                lr=learning_rate, b1=b1, b2=b2, eps=eps, wd=wd,
                interpret=interpret,
            )
            for p, g, m, v in zip(flat, fg, fm, fv)
        ]
        new_p = jax.tree.unflatten(treedef, [o[0] for o in outs])
        new_m = jax.tree.unflatten(treedef, [o[1] for o in outs])
        new_v = jax.tree.unflatten(treedef, [o[2] for o in outs])
        return new_p, (new_m, new_v, count)

    return init, apply

"""ICI collective bandwidth benchmarks.

The TPU-native analog of the reference's ComputeDomain e2e workloads — the
"nickelpie" NCCL send/recv/broadcast test asserting ``RESULT bandwidth: X
GB/s`` and the nvbandwidth multinode memcpy assertion
(tests/bats/test_cd_mnnvl_workload.bats:18-52).  Instead of NCCL binaries,
these are jitted XLA collectives over a ``Mesh``:

- psum:           all-reduce — the BASELINE.json "JAX psum GB/s" metric
- all_gather:     payload replication along an axis
- ppermute:       neighbor ring shift — raw single-link ICI bandwidth
- reduce_scatter: the all-reduce half that ends sharded (psum_scatter) —
                  the ZeRO/optimizer-sharding primitive
- all_to_all:     full shuffle along an axis — the MoE expert-dispatch
                  primitive (workload/moe.py routes through GSPMD, but the
                  wire pattern XLA emits is this)

Each benchmark is written with ``shard_map`` so the collective is explicit
(not left to sharding propagation) and compiled once; timing loops run the
compiled executable with donated buffers to avoid realloc noise.

Bus bandwidth convention matches nccl-tests: all-reduce moves
``2*(n-1)/n * bytes`` per device, all-gather/permute ``(n-1)/n * bytes`` and
``bytes`` respectively.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial


@dataclass
class BenchResult:
    op: str
    payload_bytes: int
    n_devices: int
    seconds_per_op: float
    algo_gbps: float  # payload / time
    bus_gbps: float  # nccl-tests bus-bandwidth convention

    def line(self) -> str:
        # The e2e suite greps this (the RESULT-bandwidth assertion analog).
        return f"RESULT bandwidth: {self.bus_gbps:.2f} GB/s op={self.op} n={self.n_devices}"


def _time_compiled(fn, args, iters: int, warmup: int = 2) -> float:
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _mk_operand(mesh, axis: str, elems_per_device: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    x = jnp.arange(n * elems_per_device, dtype=jnp.bfloat16).reshape(n, elems_per_device)
    return jax.device_put(x, NamedSharding(mesh, P(axis, None)))


def bench_psum(mesh, axis: str = "data", mib_per_device: int = 64, iters: int = 10) -> BenchResult:
    import jax
    import jax.numpy as jnp
    from tpudra.workload.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    elems = mib_per_device * 2**20 // 2  # bfloat16
    x = _mk_operand(mesh, axis, elems)

    @partial(
        shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None)
    )
    def allreduce(block):
        return jax.lax.psum(block, axis_name=axis) * jnp.bfloat16(1.0 / n)

    fn = jax.jit(allreduce)
    dt = _time_compiled(fn, (x,), iters)
    payload = elems * 2
    return BenchResult(
        op="psum",
        payload_bytes=payload,
        n_devices=n,
        seconds_per_op=dt,
        algo_gbps=payload / dt / 1e9,
        bus_gbps=(2 * (n - 1) / n) * payload / dt / 1e9,
    )


def bench_all_gather(mesh, axis: str = "data", mib_per_device: int = 64, iters: int = 10) -> BenchResult:
    import jax
    from tpudra.workload.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    elems = mib_per_device * 2**20 // 2
    x = _mk_operand(mesh, axis, elems)

    # check_vma off: the output IS replicated (every device holds the full
    # gather) but the static checker cannot infer that through the reshape.
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(None, None),
        check_vma=False,
    )
    def gather(block):
        return jax.lax.all_gather(block, axis_name=axis, axis=0).reshape(n, -1)

    fn = jax.jit(gather)
    dt = _time_compiled(fn, (x,), iters)
    payload = elems * 2 * n  # each device materializes the full array
    return BenchResult(
        op="all_gather",
        payload_bytes=payload,
        n_devices=n,
        seconds_per_op=dt,
        algo_gbps=payload / dt / 1e9,
        bus_gbps=((n - 1) / n) * payload / dt / 1e9,
    )


def bench_ppermute_ring(mesh, axis: str = "data", mib_per_device: int = 64, iters: int = 10) -> BenchResult:
    """Every device sends its whole block to the next ring neighbor — the
    closest analog to a raw point-to-point ICI link measurement."""
    import jax
    from tpudra.workload.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    elems = mib_per_device * 2**20 // 2
    x = _mk_operand(mesh, axis, elems)
    perm = [(i, (i + 1) % n) for i in range(n)]

    @partial(shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None))
    def shift(block):
        return jax.lax.ppermute(block, axis_name=axis, perm=perm)

    fn = jax.jit(shift)
    dt = _time_compiled(fn, (x,), iters)
    payload = elems * 2
    return BenchResult(
        op="ppermute_ring",
        payload_bytes=payload,
        n_devices=n,
        seconds_per_op=dt,
        algo_gbps=payload / dt / 1e9,
        bus_gbps=payload / dt / 1e9,
    )


def bench_reduce_scatter(mesh, axis: str = "data", mib_per_device: int = 64, iters: int = 10) -> BenchResult:
    """psum_scatter: the reduce-scatter half of a ring all-reduce — each
    device ends with its 1/n shard of the sum (the gradient/optimizer
    sharding primitive)."""
    import jax
    from tpudra.workload.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    elems = max(n, mib_per_device * 2**20 // 2 // n * n)  # divisible by n
    x = _mk_operand(mesh, axis, elems)

    @partial(shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None))
    def rs(block):
        # tiled: the (1, elems) block scatters to (1, elems/n) of the sum.
        return jax.lax.psum_scatter(block, axis_name=axis, scatter_dimension=1, tiled=True)

    fn = jax.jit(rs)
    dt = _time_compiled(fn, (x,), iters)
    payload = elems * 2  # input bytes per device (nccl-tests data-size convention)
    return BenchResult(
        op="reduce_scatter",
        payload_bytes=payload,
        n_devices=n,
        seconds_per_op=dt,
        algo_gbps=payload / dt / 1e9,
        bus_gbps=((n - 1) / n) * payload / dt / 1e9,
    )


def bench_all_to_all(mesh, axis: str = "data", mib_per_device: int = 64, iters: int = 10) -> BenchResult:
    """Full shuffle: every device sends a distinct 1/n chunk to every other
    device — the MoE dispatch/combine wire pattern."""
    import jax
    from tpudra.workload.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    elems = max(n, mib_per_device * 2**20 // 2 // n * n)
    x = _mk_operand(mesh, axis, elems)

    @partial(
        shard_map, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None, None)
    )
    def a2a(block):
        # (1, n, k): chunk j goes to device j; received chunks concat on 0.
        return jax.lax.all_to_all(
            block.reshape(1, n, -1), axis_name=axis, split_axis=1, concat_axis=0
        )

    fn = jax.jit(a2a)
    dt = _time_compiled(fn, (x,), iters)
    payload = elems * 2
    return BenchResult(
        op="all_to_all",
        payload_bytes=payload,
        n_devices=n,
        seconds_per_op=dt,
        algo_gbps=payload / dt / 1e9,
        bus_gbps=((n - 1) / n) * payload / dt / 1e9,
    )


ALL_BENCHES = {
    "psum": bench_psum,
    "all_gather": bench_all_gather,
    "ppermute_ring": bench_ppermute_ring,
    "reduce_scatter": bench_reduce_scatter,
    "all_to_all": bench_all_to_all,
}


def verify_collectives(mesh, axis: str = "data") -> list[str]:
    """Numerical parity for every collective ALL_BENCHES measures, against
    a local numpy reference on small exact-integer operands — the dryrun's
    multi-pattern correctness sweep (the nvbandwidth multi-pattern analog,
    reference test_cd_mnnvl_workload.bats:40-52; bandwidth is published
    only from real ICI, correctness is asserted everywhere).  Returns the
    verified op names in ALL_BENCHES order."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from tpudra.workload.jaxcompat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    k = 4
    elems = n * k
    x = np.arange(n * elems, dtype=np.float32).reshape(n, elems)
    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(axis, None)))
    sm = partial(shard_map, mesh=mesh, in_specs=P(axis, None))
    verified: list[str] = []

    out = jax.jit(sm(lambda b: jax.lax.psum(b, axis) / n, out_specs=P(axis, None)))(xs)
    np.testing.assert_allclose(
        np.asarray(out), np.broadcast_to(x.mean(0), (n, elems)), rtol=1e-6
    )
    verified.append("psum")

    out = jax.jit(
        sm(
            lambda b: jax.lax.all_gather(b, axis_name=axis, axis=0).reshape(n, -1),
            out_specs=P(None, None),
            check_vma=False,
        )
    )(xs)
    np.testing.assert_array_equal(np.asarray(out), x)
    verified.append("all_gather")

    perm = [(i, (i + 1) % n) for i in range(n)]
    out = jax.jit(
        sm(lambda b: jax.lax.ppermute(b, axis_name=axis, perm=perm), out_specs=P(axis, None))
    )(xs)
    np.testing.assert_array_equal(np.asarray(out), np.roll(x, 1, axis=0))
    verified.append("ppermute_ring")

    out = jax.jit(
        sm(
            lambda b: jax.lax.psum_scatter(b, axis_name=axis, scatter_dimension=1, tiled=True),
            out_specs=P(axis, None),
        )
    )(xs)
    np.testing.assert_allclose(
        np.asarray(out), x.sum(0).reshape(n, elems // n), rtol=1e-6
    )
    verified.append("reduce_scatter")

    out = jax.jit(
        sm(
            lambda b: jax.lax.all_to_all(
                b.reshape(1, n, -1), axis_name=axis, split_axis=1, concat_axis=0
            ),
            out_specs=P(axis, None, None),
        )
    )(xs)
    # Device i receives chunk i of every device j: out[i, j] = x[j]'s chunk i.
    np.testing.assert_array_equal(
        np.asarray(out).reshape(n, n, k), x.reshape(n, n, k).transpose(1, 0, 2)
    )
    verified.append("all_to_all")

    assert list(ALL_BENCHES) == verified, (list(ALL_BENCHES), verified)
    return verified


def run_all(mesh, axis: str = "data", mib_per_device: int = 8, iters: int = 5):
    return [fn(mesh, axis=axis, mib_per_device=mib_per_device, iters=iters) for fn in ALL_BENCHES.values()]

"""Ring attention: causal attention over a sequence sharded across devices.

The long-context workload for claimed slices.  The reference validates
multi-node domains with NCCL bandwidth runs; the TPU build's stronger claim
is that a *sequence-parallel* computation — where no device ever holds the
full sequence — runs across the granted topology.  This is the standard ring
schedule (Liu et al., "Ring Attention with Blockwise Transformers"; public
JAX implementations follow the same shape):

- q, k, v are sharded along the sequence axis over the mesh's ``sp`` axis;
- each step, every device computes blockwise attention of its local q
  against the k/v block currently resident, then rotates k/v one hop around
  the ring with ``lax.ppermute`` — after ``n`` steps every q block has seen
  every k/v block while only ever storing one block at a time;
- softmax is accumulated online (flash-attention style running max /
  denominator), so the full score matrix never materializes;
- on TPU the ppermute rides neighbor ICI links, overlapping with the
  block matmul (XLA schedules the collective-permute concurrently with
  compute when they are independent).

Causality: with q block index i and k block index j, block pairs j > i are
fully masked (their contribution is skipped numerically), j == i uses the
local causal triangle, j < i attends fully.
"""

from __future__ import annotations

from functools import partial


def ring_self_attention(q, k, v, axis_name: str, causal: bool = True,
                        layout: str = "bshd"):
    """Blockwise-ring causal attention; call INSIDE shard_map with q/k/v
    holding this device's sequence block — ``layout`` "bshd"
    ([B, s_block, H, D], the standalone-kernel convention) or "bhsd"
    ([B, H, s_block, D], the model layer's native head-major layout, which
    avoids any transpose at the shard_map boundary)."""
    import jax.numpy as jnp
    from jax import lax

    if layout == "bshd":
        B, s, H, D = q.shape
        qk_eq, pv_eq = "bqhd,bkhd->bhqk", "bhqk,bkhd->bhqd"
    elif layout == "bhsd":
        B, H, s, D = q.shape
        qk_eq, pv_eq = "bhqd,bhkd->bhqk", "bhqk,bhkd->bhqd"
    else:
        raise ValueError(f"layout must be bshd|bhsd, got {layout!r}")
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, step_idx):
        k_blk, v_blk, acc, m, l = carry
        # k_blk currently holds block j = (my_idx - step_idx) mod n.
        j = (my_idx - step_idx) % n
        q_off = my_idx * s
        k_off = j * s

        scale = D ** -0.5
        scores = jnp.einsum(qk_eq, q, k_blk).astype(jnp.float32) * scale
        if causal:
            q_pos = q_off + jnp.arange(s)[:, None]
            k_pos = k_off + jnp.arange(s)[None, :]
            scores = jnp.where((q_pos >= k_pos)[None, None], scores, -jnp.inf)

        blk_max = jnp.max(scores, axis=-1)  # [B,H,sq]
        m_new = jnp.maximum(m, blk_max)
        # Fully-masked rows keep m_new == m == -inf; exp(-inf - -inf) is nan,
        # so guard the shift.
        shift = jnp.where(jnp.isneginf(m_new), 0.0, m - m_new)
        blk_shift = jnp.where(jnp.isneginf(m_new)[..., None], -jnp.inf, scores - m_new[..., None])
        p = jnp.exp(blk_shift)  # [B,H,sq,sk]
        acc = acc * jnp.exp(shift)[..., None] + jnp.einsum(
            pv_eq, p, v_blk.astype(jnp.float32)
        )
        l = l * jnp.exp(shift) + jnp.sum(p, axis=-1)
        m = m_new

        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, acc, m, l), None

    # pcast-to-varying: the accumulators are per-device values varying over the ring
    # axis; without the annotation the scan carry types disagree (the body's
    # outputs pick up {V:sp} from q/k/v).
    from tpudra.workload.jaxcompat import pcast

    acc0 = pcast(jnp.zeros((B, H, s, D), jnp.float32), (axis_name,), to='varying')
    m0 = pcast(jnp.full((B, H, s), -jnp.inf, jnp.float32), (axis_name,), to='varying')
    l0 = pcast(jnp.zeros((B, H, s), jnp.float32), (axis_name,), to='varying')
    (k_f, v_f, acc, m, l), _ = lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(n)
    )
    del k_f, v_f
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B,H,sq,D]
    if layout == "bshd":
        out = out.transpose(0, 2, 1, 3)  # [B,sq,H,D]
    return out.astype(q.dtype)


def make_sharded_ring_attention(
    mesh,
    axis_name: str = "sp",
    causal: bool = True,
    layout: str = "bshd",
    manual_only: bool = False,
    jit: bool = True,
):
    """Ring attention with the sequence dim sharded over ``axis_name``;
    batch stays replicated across the other axes (compose with dp by
    sharding B in the caller's specs).  ``manual_only`` leaves every mesh
    axis except ``axis_name`` GSPMD-automatic (the model-composition mode:
    dp/tp partitioning continues through the manual region); ``jit=False``
    returns the bare shard_map for embedding inside a larger program."""
    import jax
    from tpudra.workload.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    seq_dim = 1 if layout == "bshd" else 2
    spec = P(*(axis_name if d == seq_dim else None for d in range(4)))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **(
            {"axis_names": frozenset({axis_name}), "check_vma": False}
            if manual_only
            else {}
        ),
    )
    def fn(q, k, v):
        return ring_self_attention(
            q, k, v, axis_name=axis_name, causal=causal, layout=layout
        )

    return jax.jit(fn) if jit else fn


def dense_reference(q, k, v, causal: bool = True):
    """Unsharded attention for correctness checks."""
    import jax
    import jax.numpy as jnp

    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bhqd", probs, v.astype(jnp.float32))
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_loss_fn(params, tokens, cfg, mesh, sp_axis: str = "sp"):
    """The flagship loss with the attention core replaced by ring
    attention over ``sp_axis`` — the sequence-parallel composition: every
    projection/FFN/CE einsum stays GSPMD-partitioned over the mesh's
    other axes, while inside each layer the attention runs the manual
    ring schedule (only ``sp_axis`` is a manual shard_map axis; dp/tp
    remain automatic, mirroring pipeline.py's partial-manual pattern).

    Per-device sequence memory is S/n for k/v — the model-level form of
    this module's standalone kernel, so a grant whose sequence outgrows
    one chip's HBM still trains (SURVEY §5 long-context).
    """
    if sp_axis not in mesh.shape:
        raise ValueError(f"mesh {dict(mesh.shape)} has no {sp_axis!r} axis")
    if tokens.shape[1] % mesh.shape[sp_axis]:
        raise ValueError(
            f"sequence {tokens.shape[1]} does not shard over {sp_axis!r} "
            f"of size {mesh.shape[sp_axis]}"
        )
    from tpudra.workload import model as m

    # _layer hands attention in its native head-major [B, H, S, hd]; the
    # bhsd kernel layout keeps the shard_map boundary transpose-free.
    attn_fn = make_sharded_ring_attention(
        mesh, axis_name=sp_axis, layout="bhsd", manual_only=True, jit=False
    )
    return m.loss_fn(params, tokens, cfg, attn_fn=attn_fn)

"""Ring attention: causal attention over a sequence sharded across devices.

The long-context workload for claimed slices.  The reference validates
multi-node domains with NCCL bandwidth runs; the TPU build's stronger claim
is that a *sequence-parallel* computation — where no device ever holds the
full sequence — runs across the granted topology.  This is the standard ring
schedule (Liu et al., "Ring Attention with Blockwise Transformers"; public
JAX implementations follow the same shape):

- q, k, v are sharded along the sequence axis over the mesh's ``sp`` axis;
- each step, every device computes blockwise attention of its local q
  against the k/v block currently resident, then rotates k/v one hop around
  the ring with ``lax.ppermute`` — after ``n`` steps every q block has seen
  every k/v block while only ever storing one block at a time;
- softmax is accumulated online (flash-attention style running max /
  denominator), so the full score matrix never materializes;
- on TPU the ppermute rides neighbor ICI links, overlapping with the
  block matmul (XLA schedules the collective-permute concurrently with
  compute when they are independent).

Causality: with q block index i and k block index j, block pairs j > i are
fully masked (their contribution is skipped numerically), j == i uses the
local causal triangle, j < i attends fully.
"""

from __future__ import annotations

from functools import partial


def ring_self_attention(q, k, v, axis_name: str, causal: bool = True):
    """Blockwise-ring causal attention; call INSIDE shard_map with q/k/v
    holding this device's sequence block [B, s_block, H, D]."""
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, s, H, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, step_idx):
        k_blk, v_blk, acc, m, l = carry
        # k_blk currently holds block j = (my_idx - step_idx) mod n.
        j = (my_idx - step_idx) % n
        q_off = my_idx * s
        k_off = j * s

        scale = D ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
        if causal:
            q_pos = q_off + jnp.arange(s)[:, None]
            k_pos = k_off + jnp.arange(s)[None, :]
            scores = jnp.where((q_pos >= k_pos)[None, None], scores, -jnp.inf)

        blk_max = jnp.max(scores, axis=-1)  # [B,H,sq]
        m_new = jnp.maximum(m, blk_max)
        # Fully-masked rows keep m_new == m == -inf; exp(-inf - -inf) is nan,
        # so guard the shift.
        shift = jnp.where(jnp.isneginf(m_new), 0.0, m - m_new)
        blk_shift = jnp.where(jnp.isneginf(m_new)[..., None], -jnp.inf, scores - m_new[..., None])
        p = jnp.exp(blk_shift)  # [B,H,sq,sk]
        acc = acc * jnp.exp(shift)[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        l = l * jnp.exp(shift) + jnp.sum(p, axis=-1)
        m = m_new

        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, acc, m, l), None

    # pcast-to-varying: the accumulators are per-device values varying over the ring
    # axis; without the annotation the scan carry types disagree (the body's
    # outputs pick up {V:sp} from q/k/v).
    acc0 = lax.pcast(jnp.zeros((B, H, s, D), jnp.float32), (axis_name,), to='varying')
    m0 = lax.pcast(jnp.full((B, H, s), -jnp.inf, jnp.float32), (axis_name,), to='varying')
    l0 = lax.pcast(jnp.zeros((B, H, s), jnp.float32), (axis_name,), to='varying')
    (k_f, v_f, acc, m, l), _ = lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(n)
    )
    del k_f, v_f
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B,H,sq,D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B,sq,H,D]


def make_sharded_ring_attention(mesh, axis_name: str = "sp", causal: bool = True):
    """Jitted [B, S, H, D] ring attention with S sharded over ``axis_name``;
    batch stays replicated across the other axes (compose with dp by
    sharding B in the caller's specs)."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    def fn(q, k, v):
        return ring_self_attention(q, k, v, axis_name=axis_name, causal=causal)

    return jax.jit(fn)


def dense_reference(q, k, v, causal: bool = True):
    """Unsharded attention for correctness checks."""
    import jax
    import jax.numpy as jnp

    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bhqd", probs, v.astype(jnp.float32))
    return out.transpose(0, 2, 1, 3).astype(q.dtype)

"""Workload-side library: what runs *inside* a container that consumed a claim.

The reference has no workload library — its demo pods run raw CUDA/NCCL
binaries (demo/specs/, tests/bats/test_cd_mnnvl_workload.bats).  The TPU build
ships one because the contract is richer: the driver injects env
(TPU_VISIBLE_DEVICES, TPUDRA_CHIP_COORDS, TPUDRA_CLIQUE_ID, ...) describing
exactly the silicon granted, and this package turns that into a
``jax.sharding.Mesh`` plus ready-made SPMD workloads:

- envspec:     claim env → device set / mesh construction
- collectives: ICI bandwidth benchmarks (psum / all-gather / ppermute ring) —
  the analog of the reference's nickelpie/nvbandwidth e2e assertions
- model:       a flagship SPMD transformer train step (dp/tp/sp sharded)
  proving a claimed slice is usable end-to-end
"""

from tpudra.workload.envspec import ClaimEnv, mesh_from_devices

__all__ = ["ClaimEnv", "mesh_from_devices"]

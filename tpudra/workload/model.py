"""Flagship SPMD workload: a causal-transformer train step over a claim mesh.

The reference validates a multi-node grant by running NCCL benchmarks; the
TPU build validates it by *training*, because the real acceptance test for a
claimed slice is "does SPMD compile and step across the granted topology".
This module is a deliberately small, pure-JAX (no framework) decoder:

- bfloat16 matmuls (MXU-shaped, dims multiples of 128 at real sizes) with
  float32 accumulation and float32 master params
- layers stacked and iterated with ``lax.scan`` — one trace regardless of
  depth, no Python-loop unrolling
- GSPMD sharding: params tp-sharded (Megatron layout: column-parallel in,
  row-parallel out), batch dp-sharded, activations seq-sharded (sp) outside
  the attention core — XLA inserts the all-gathers/reduce-scatters on ICI
- remat on the layer body trades FLOPs for HBM

Used by __graft_entry__ (single-chip forward + multi-chip dryrun) and by the
ComputeDomain e2e workload.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_seq: int = 128

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(rng, cfg: ModelConfig):
    import jax
    import jax.numpy as jnp

    k_emb, k_layers, k_out = jax.random.split(rng, 3)

    def dense(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(jnp.float32)

    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    ks = jax.random.split(k_layers, 6)
    s = D ** -0.5
    return {
        "embed": dense(k_emb, (cfg.vocab, D), 0.02),
        "pos": dense(k_out, (cfg.max_seq, D), 0.02),
        # Stacked per-layer params, leading axis = layer (scan carries it).
        "layers": {
            "wqkv": dense(ks[0], (L, D, 3 * D), s),
            "wo": dense(ks[1], (L, D, D), s),
            "w1": dense(ks[2], (L, D, F), s),
            "w2": dense(ks[3], (L, F, D), F ** -0.5),
            "ln1": jnp.ones((L, D), jnp.float32),
            "ln2": jnp.ones((L, D), jnp.float32),
        },
        "ln_f": jnp.ones((D,), jnp.float32),
    }


def _rmsnorm(x, scale):
    import jax
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * inv * scale).astype(x.dtype)


def _layer(cfg: ModelConfig, x, layer_params):
    """One decoder block in bfloat16; x: [B, S, D]."""
    import jax
    import jax.numpy as jnp

    B, S, D = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    p = layer_params

    h = _rmsnorm(x, p["ln1"])
    qkv = jnp.einsum("bsd,de->bse", h, p["wqkv"].astype(jnp.bfloat16))
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, S, H, hd).transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (hd ** -0.5)
    mask = jnp.tril(jnp.ones((S, S), bool))
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(jnp.bfloat16)
    attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    attn = attn.transpose(0, 2, 1, 3).reshape(B, S, D)
    x = x + jnp.einsum("bsd,de->bse", attn, p["wo"].astype(jnp.bfloat16))

    h = _rmsnorm(x, p["ln2"])
    h = jnp.einsum("bsd,df->bsf", h, p["w1"].astype(jnp.bfloat16))
    h = jax.nn.gelu(h)
    h = jnp.einsum("bsf,fd->bsd", h, p["w2"].astype(jnp.bfloat16))
    return x + h


def forward(params, tokens, cfg: ModelConfig):
    """tokens [B, S] int32 → logits [B, S, V] float32."""
    import jax
    import jax.numpy as jnp

    B, S = tokens.shape
    x = params["embed"][tokens].astype(jnp.bfloat16)
    x = x + params["pos"][:S].astype(jnp.bfloat16)[None]

    layer_body = partial(_layer, cfg)
    # Selective remat: keep matmul outputs (MXU work is the expensive part to
    # recompute), rematerialize the cheap elementwise/softmax ops — measured
    # ~1.2x step-time win over full remat on v5e at equal memory headroom.
    layer_body = jax.checkpoint(
        layer_body, policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    )

    def step(x, layer_params):
        return layer_body(x, layer_params), None

    x, _ = jax.lax.scan(step, x, params["layers"])
    x = _rmsnorm(x, params["ln_f"])
    # Logits matmul on the MXU in bfloat16 with float32 accumulation — an
    # f32 matmul here runs off the MXU fast path and costs ~10% of the step.
    logits = jnp.einsum(
        "bsd,vd->bsv",
        x,
        params["embed"].astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    return logits


def loss_fn(params, tokens, cfg: ModelConfig):
    import jax
    import jax.numpy as jnp

    logits = forward(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def make_train_step(cfg: ModelConfig, learning_rate: float = 1e-3):
    """Returns (init_opt_state, train_step) using optax adamw."""
    import jax
    import optax

    tx = optax.adamw(learning_rate)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return tx.init, train_step


# -- sharding layout ---------------------------------------------------------


def param_specs(cfg: ModelConfig):
    """Megatron-style tensor-parallel layout as PartitionSpecs.

    Column-parallel (output dim on tp): wqkv, w1, embed's model dim.
    Row-parallel (input dim on tp): wo, w2.  Norms replicated.
    """
    from jax.sharding import PartitionSpec as P

    return {
        "embed": P(None, "tp"),
        "pos": P(None, "tp"),
        "layers": {
            "wqkv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "w1": P(None, None, "tp"),
            "w2": P(None, "tp", None),
            "ln1": P(None, None),
            "ln2": P(None, None),
        },
        "ln_f": P(None),
    }


def batch_spec():
    """Batch dp-sharded, sequence sp-sharded: long-context inputs split
    across the sp axis so no single device holds the whole sequence."""
    from jax.sharding import PartitionSpec as P

    return P("dp", "sp")


def shard_params(params, mesh, cfg: ModelConfig):
    import jax
    from jax.sharding import NamedSharding

    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: not isinstance(x, dict),
    )

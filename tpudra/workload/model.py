"""Flagship SPMD workload: a causal-transformer train step over a claim mesh.

The reference validates a multi-node grant by running NCCL benchmarks; the
TPU build validates it by *training*, because the real acceptance test for a
claimed slice is "does SPMD compile and step across the granted topology".
This module is a deliberately small, pure-JAX (no framework) decoder:

- bfloat16 matmuls (MXU-shaped, dims multiples of 128 at real sizes) with
  float32 accumulation and float32 master params
- layers stacked and iterated with ``lax.scan`` — one trace regardless of
  depth, no Python-loop unrolling
- GSPMD sharding: params tp-sharded (Megatron layout: column-parallel in,
  row-parallel out), batch dp-sharded, activations seq-sharded (sp) outside
  the attention core — XLA inserts the all-gathers/reduce-scatters on ICI
- remat on the layer body trades FLOPs for HBM

Perf decisions, each A/B-measured on a real v5e chip (472M params, batch 16,
seq 1024; cumulatively 41% → ~66% MFU — the headline and the A/B legs are
re-measured into every round's BENCH_r{N}.json by bench.py, extras.tpu/.ab):

- **transpose-free projections**: qkv is one einsum straight into
  ``[3, B, H, S, hd]`` and the output projection contracts ``[H, hd]``
  directly, so no [B,S,H,hd]→[B,H,S,hd] transposes hit HBM (+3.2% MFU)
- **chunked logsumexp cross-entropy**: logits are produced per sequence
  chunk inside a scan and reduced to ``logsumexp - target_logit``
  immediately, so the separate full ``[B, S, V]`` log-softmax tensor of
  the textbook formulation never exists.  (The backward still holds the
  stacked per-chunk logits residuals — remat on the chunk would bound
  that to one chunk but measured 2% MFU slower, so we spend the memory.)
- **bf16 Adam moments** (f32 master params): halves optimizer-state reads/
  writes per step and frees 2.9 GB for the 472M model (+4.5%)
- **bf16 attention scores matmul, cast to f32 after** (naive path): the
  MXU's native bf16 output + a vector cast beats asking the matmul for f32
  output (-5% if done the other way); softmax runs in f32 for stability
- **tuned pallas splash attention on TPU** (``attention="auto"``): the
  splash kernel with 1024-wide blocks and the fused backward beats the
  fused naive chain at every runnable length — ~66% vs ~52% MFU at seq
  1024 (bench.py extras.ab.attention_naive) — and is the only path past
  the HBM cliff (seq 8192 at ~72% batch 2, 16384 at ~79% MFU batch 1,
  extras.long_context/.long_context_16k; naive cannot compile there).
  Both pallas kernels lose to naive at their DEFAULT block sizes; the
  tuning is the feature.  A block sweep at seq 1024 (512/1024 q×kv
  combinations) is within noise of 1024×1024 — the default stands

Used by __graft_entry__ (single-chip forward + multi-chip dryrun) and by the
ComputeDomain e2e workload.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_seq: int = 128
    # Sequence-chunk width for the cross-entropy head; 512 measured best on
    # v5e (128 and full-width are both slower).  Short sequences fall into
    # the tail path automatically.
    ce_chunk: int = 512
    # CE head implementation: "chunked" (scan over sequence chunks, logits
    # kept as bwd residuals) or "fused" (pallas online-softmax over vocab
    # blocks, no logits in HBM, recompute backward — ce_kernel.py).
    # Measured on v5e (bench.py extras.ab.ce_fused): fused loses ~2 MFU
    # pts at the flagship config and is par at batch 24 / seq 16384 — the
    # recompute FLOPs outweigh the freed residual on this chip, so
    # chunked stays the default; fused is for memory-constrained configs.
    ce_impl: str = "chunked"
    # Optimizer implementation: "tree" (XLA-fused tree-map AdamW, the
    # measured default) or "fused" (one-sweep pallas kernel reading
    # p/g/m/v and writing p'/m'/v' per block — opt_kernel.py).  The A/B is
    # re-measured every round (bench.py extras.ab.opt_fused); the default
    # follows the measurement.
    opt_impl: str = "tree"
    # Attention core: "auto" | "naive" | "flash"/"splash".  Measured on
    # v5e (472M params; artifacts in BENCH_r{N}.json extras.ab): the
    # pallas splash kernel with 1024-wide blocks and its fused backward
    # beats XLA's fused naive chain at every length it can run — ~66% vs
    # ~52% MFU at seq 1024 — and past the HBM cliff (seq > ~2048) it is
    # the only path that compiles at all (~72% MFU at 8192, ~79% at
    # 16384).  Both pallas kernels LOSE to naive at their default block
    # sizes — the tuning is the feature.  "auto" picks the kernel for
    # single-device TPU programs whose block shapes divide the sequence
    # and whose head_dim is MXU-aligned; meshes, CPU, and odd lengths
    # take the naive path.
    attention: str = "auto"
    # Splash-attention block sizes (0 = the tuned default, min(1024, S)).
    # Exposed so bench.py can sweep them on real hardware; both must divide
    # the sequence length.
    attn_block_q: int = 0
    attn_block_kv: int = 0
    # Rematerialization policy for the layer scan body:
    #   "dots"  — keep matmul outputs, recompute elementwise/softmax
    #             (checkpoint_dots_with_no_batch_dims; the measured default)
    #   "full"  — save nothing, recompute everything (lowest memory)
    #   "none"  — no remat: save all residuals (fastest when memory allows)
    remat: str = "dots"
    # Sparse (Switch-MoE) FFN: 0 = dense.  With E experts each layer's FFN
    # becomes top-1-routed (workload/moe.py math); the expert axis shards
    # over the tp mesh axis in param_specs, and loss_fn adds
    # moe_aux_weight * the mean load-balancing loss.
    num_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Activation/matmul compute dtype: "bf16" (the MXU path, default) or
    # "f32".  f32 exists for numerics debugging and for virtual-CPU-mesh
    # validation of partial-manual (pipeline × GSPMD-auto tp) programs —
    # XLA's CPU AllReducePromotion pass aborts on the bf16 all-reduces
    # those emit in the backward; real TPU meshes keep bf16.
    compute_dtype: str = "bf16"

    def __post_init__(self):
        if self.attention not in ("auto", "naive", "flash", "splash"):
            raise ValueError(
                f"attention must be auto|naive|flash|splash, got {self.attention!r}"
            )
        if self.remat not in ("dots", "full", "none"):
            raise ValueError(f"remat must be dots|full|none, got {self.remat!r}")
        if self.num_experts < 0:
            raise ValueError(f"num_experts must be >= 0, got {self.num_experts}")
        if self.compute_dtype not in ("bf16", "f32"):
            raise ValueError(
                f"compute_dtype must be bf16|f32, got {self.compute_dtype!r}"
            )
        if self.ce_impl not in ("chunked", "fused"):
            raise ValueError(f"ce_impl must be chunked|fused, got {self.ce_impl!r}")
        if self.opt_impl not in ("tree", "fused"):
            raise ValueError(f"opt_impl must be tree|fused, got {self.opt_impl!r}")
        for name in ("attn_block_q", "attn_block_kv"):
            blk = getattr(self, name)
            if blk and (blk % 128 or self.max_seq % blk):
                # Fail here, not as an opaque Mosaic block-shape error mid
                # sweep: splash blocks must be lane-aligned and divide S.
                raise ValueError(
                    f"{name}={blk} must be a multiple of 128 dividing "
                    f"max_seq={self.max_seq}"
                )
        if self.d_model % self.n_heads:
            raise ValueError(
                f"d_model {self.d_model} not divisible by n_heads {self.n_heads}"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def act_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.compute_dtype == "bf16" else jnp.float32

    def use_flash_attention(self, seq_len: int) -> bool:
        if self.attention in ("flash", "splash"):  # both name the pallas path
            return True
        if self.attention == "naive":
            return False
        import jax

        if jax.default_backend() != "tpu":
            return False
        # Multi-device GSPMD cannot partition a pallas call — XLA would
        # replicate it and gather the activations around the kernel.  On
        # meshes, naive attention (whose einsums XLA partitions natively)
        # and ring attention own the problem.  The config cannot see the
        # program's sharding, so auto is conservative: any process with
        # multiple visible devices takes naive.  A deliberately
        # single-device program on a multi-chip host (bench.py does this)
        # or per-shard code under shard_map should pass
        # attention="splash" explicitly.
        if jax.device_count() != 1:
            return False
        if self.head_dim % 128 != 0:
            return False
        # Block shapes must divide the sequence: either the tuned
        # 1024-wide blocks fit, or the sequence itself is a small
        # 128-multiple that becomes the block.
        return seq_len % 1024 == 0 or (seq_len <= 512 and seq_len % 128 == 0)


def init_params(rng, cfg: ModelConfig):
    import jax
    import jax.numpy as jnp

    k_emb, k_layers, k_out = jax.random.split(rng, 3)

    def dense(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(jnp.float32)

    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    H, hd = cfg.n_heads, cfg.head_dim
    ks = jax.random.split(k_layers, 6)
    s = D ** -0.5
    return {
        "embed": dense(k_emb, (cfg.vocab, D), 0.02),
        "pos": dense(k_out, (cfg.max_seq, D), 0.02),
        # Stacked per-layer params, leading axis = layer (scan carries it).
        # Attention weights keep an explicit head axis — the tp sharding
        # lives on H, so the in-layer reshapes only ever split *unsharded*
        # axes and GSPMD propagation never has to reshard a weight.
        "layers": {
            "wqkv": dense(ks[0], (L, D, H, 3 * hd), s),
            "wo": dense(ks[1], (L, H, hd, D), s),
            **(
                {
                    "router": dense(ks[4], (L, D, cfg.num_experts), s),
                    "w1": dense(ks[2], (L, cfg.num_experts, D, F), s),
                    "w2": dense(ks[3], (L, cfg.num_experts, F, D), F ** -0.5),
                }
                if cfg.num_experts
                else {
                    "w1": dense(ks[2], (L, D, F), s),
                    "w2": dense(ks[3], (L, F, D), F ** -0.5),
                }
            ),
            "ln1": jnp.ones((L, D), jnp.float32),
            "ln2": jnp.ones((L, D), jnp.float32),
        },
        "ln_f": jnp.ones((D,), jnp.float32),
    }


def _rmsnorm(x, scale):
    import jax
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * inv * scale).astype(x.dtype)


def _layer(cfg: ModelConfig, x, layer_params, attn_fn=None):
    """One decoder block in bfloat16; x: [B, S, D].

    Projections are transpose-free: qkv lands directly in [3, B, H, S, hd]
    and the output projection contracts the [H, hd] pair, so the layer
    never pays HBM traffic for head-axis transposes (+3% MFU on v5e).

    ``attn_fn`` overrides the attention core: (q, k, v) each [B, H, S, hd]
    → [B, H, S, hd].  Ring attention plugs in here
    (ringattention.ring_loss_fn) — sequence-parallel attention composed
    with the otherwise-GSPMD model.
    """
    import jax
    import jax.numpy as jnp

    B, S, D = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    p = layer_params

    h = _rmsnorm(x, p["ln1"])
    # [D, H, 3hd] → [D, H, 3, hd]: splits only the unsharded minor axis
    # (tp shards H), so the reshape is GSPMD-transparent.
    wqkv = p["wqkv"].astype(cfg.act_dtype).reshape(D, H, 3, hd)
    qkv = jnp.einsum("bsd,dhte->tbhse", h, wqkv)
    q, k, v = qkv[0], qkv[1], qkv[2]
    if attn_fn is not None:
        attn = attn_fn(q, k, v).astype(cfg.act_dtype)
    elif cfg.use_flash_attention(S):
        # Pallas splash kernel (flash-attention family, fused backward):
        # never materializes the [B,H,S,S] scores — faster than the fused
        # naive chain at every runnable length and the only path past the
        # HBM cliff (~seq 2048).  Measured on v5e vs the plain flash
        # kernel: 66.3% vs 62.2% MFU at seq 1024, 71.6% vs 64.7% at 8192;
        # block sizes 1024/1024 with the fused backward, clamped to S.
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as _sk,
            splash_attention_mask as _sm,
        )

        mask = _sm.MultiHeadMask([_sm.CausalMask((S, S)) for _ in range(H)])
        blk_q = cfg.attn_block_q or min(1024, S)
        blk_kv = cfg.attn_block_kv or min(1024, S)
        blocks = _sk.BlockSizes(
            block_q=blk_q, block_kv=blk_kv,
            block_q_dkv=blk_q, block_kv_dkv=blk_kv,
            use_fused_bwd_kernel=True,
        )
        kernel = _sk.make_splash_mha(
            mask=mask, head_shards=1, q_seq_shards=1, block_sizes=blocks
        )
        attn = jax.vmap(kernel)(q * (hd ** -0.5), k, v).astype(cfg.act_dtype)
    else:
        # bf16 matmul + cast: the MXU's native bf16 output plus a vector
        # cast measures ~5% MFU faster than preferred_element_type=f32
        # here; softmax still runs in f32 for stability.
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (hd ** -0.5)
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.act_dtype)
        attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    x = x + jnp.einsum("bhqd,hde->bqe", attn, p["wo"].astype(cfg.act_dtype))

    h = _rmsnorm(x, p["ln2"])
    if cfg.num_experts:
        from tpudra.workload.moe import MoEConfig, moe_ffn

        mcfg = MoEConfig(
            d_model=D,
            d_ff=cfg.d_ff,
            num_experts=cfg.num_experts,
            capacity_factor=cfg.moe_capacity_factor,
            compute_dtype=cfg.compute_dtype,
        )
        ffn, aux = moe_ffn(
            {"router": p["router"], "w1": p["w1"], "w2": p["w2"]}, h, mcfg
        )
        return x + ffn, aux
    h = jnp.einsum("bsd,df->bsf", h, p["w1"].astype(cfg.act_dtype))
    h = jax.nn.gelu(h)
    h = jnp.einsum("bsf,fd->bsd", h, p["w2"].astype(cfg.act_dtype))
    return x + h, jnp.zeros((), jnp.float32)


def embed_tokens(params, tokens, cfg: ModelConfig):
    """tokens [B, S] int32 → embedded inputs [B, S, D] in cfg.act_dtype
    (shared by the dense and pipelined backbones)."""
    S = tokens.shape[1]
    x = params["embed"][tokens].astype(cfg.act_dtype)
    return x + params["pos"][:S].astype(cfg.act_dtype)[None]


def remat_layer_body(cfg: ModelConfig, attn_fn=None):
    """The per-layer body with cfg.remat applied — the single place both
    the dense scan and the pipeline stages get their (possibly
    checkpointed) layer function.

    Selective remat ("dots"): keep matmul outputs (MXU work is the
    expensive part to recompute), rematerialize the cheap elementwise/
    softmax ops — ~66% vs ~61% MFU against full remat on v5e at the
    flagship config (bench.py extras.ab.remat_full re-measures this every
    round).  "none" does not compile at the flagship batch (HBM OOM).
    """
    import jax

    layer_body = partial(_layer, cfg, attn_fn=attn_fn)
    if cfg.remat == "dots":
        return jax.checkpoint(
            layer_body,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        )
    if cfg.remat == "full":
        return jax.checkpoint(layer_body)
    return layer_body


def backbone_and_aux(params, tokens, cfg: ModelConfig, attn_fn=None):
    """tokens [B, S] int32 → (hidden states [B, S, D] bf16, mean per-layer
    MoE aux loss — zero for dense models)."""
    import jax
    import jax.numpy as jnp

    x = embed_tokens(params, tokens, cfg)
    # The layer body's (carry, aux) return is exactly scan's contract.
    x, auxs = jax.lax.scan(remat_layer_body(cfg, attn_fn), x, params["layers"])
    return _rmsnorm(x, params["ln_f"]), jnp.mean(auxs)


def backbone(params, tokens, cfg: ModelConfig):
    """tokens [B, S] int32 → final hidden states [B, S, D] bf16."""
    return backbone_and_aux(params, tokens, cfg)[0]


def forward(params, tokens, cfg: ModelConfig):
    """tokens [B, S] int32 → logits [B, S, V] float32."""
    import jax.numpy as jnp

    x = backbone(params, tokens, cfg)
    # Logits matmul on the MXU in bfloat16 with float32 accumulation — an
    # f32 matmul here runs off the MXU fast path and costs ~10% of the step.
    return jnp.einsum(
        "bsd,vd->bsv",
        x,
        params["embed"].astype(cfg.act_dtype),
        preferred_element_type=jnp.float32,
    )


def loss_fn(params, tokens, cfg: ModelConfig, attn_fn=None):
    """Next-token NLL over tokens [B, S].

    The whole sequence goes through the backbone (power-of-two S keeps every
    kernel block-aligned); the shift happens at the loss.  The CE head is
    chunked: per chunk, logits → ``logsumexp - target_logit``, accumulated
    in a scan.  Forward never materializes a full [B, S, V] logits or
    log-softmax tensor; the backward keeps the stacked per-chunk logits
    residuals (a ``jax.checkpoint`` here would bound that to one chunk,
    measured 2% MFU slower — deliberately not taken).
    """
    x, aux = backbone_and_aux(params, tokens, cfg, attn_fn)
    loss = ce_head(params, x, tokens, cfg)
    if cfg.num_experts:
        loss = loss + cfg.moe_aux_weight * aux
    return loss


def ce_head(params, x, tokens, cfg: ModelConfig):
    """The chunked cross-entropy head over hidden states [B, S, D] — shared
    by the dense and pipelined (workload/pipeline.py) loss paths."""
    import jax
    import jax.numpy as jnp

    emb = params["embed"].astype(cfg.act_dtype)
    xs, targets = x[:, :-1], tokens[:, 1:]
    B, Sm1, D = xs.shape

    if cfg.ce_impl == "fused":
        from tpudra.workload.ce_kernel import fused_ce_mean

        return fused_ce_mean(
            xs.reshape(B * Sm1, D),
            params["embed"],
            targets.reshape(-1).astype(jnp.int32),
            interpret=jax.default_backend() != "tpu",
        )

    def ce_sum(xc, tc):
        logits = jnp.einsum(
            "bcd,vd->bcv", xc, emb, preferred_element_type=jnp.float32
        )
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        return (lse - tgt).sum()

    chunk = cfg.ce_chunk
    n = Sm1 // chunk
    total = jnp.zeros((), jnp.float32)
    if n:
        xs_c = xs[:, : n * chunk].reshape(B, n, chunk, D).swapaxes(0, 1)
        tg_c = targets[:, : n * chunk].reshape(B, n, chunk).swapaxes(0, 1)

        def ce_chunk(acc, xt):
            return acc + ce_sum(*xt), None

        total, _ = jax.lax.scan(ce_chunk, total, (xs_c, tg_c))
    if Sm1 % chunk:
        total = total + ce_sum(xs[:, n * chunk :], targets[:, n * chunk :])
    return total / (B * Sm1)


def adamw_bf16_moments(learning_rate: float, b1=0.9, b2=0.999, eps=1e-8, wd=1e-4):
    """AdamW with both moments stored in bfloat16 (f32 master params).

    Moment arithmetic happens in f32 and is rounded back to bf16 — frees
    2.9 GB of HBM for the 472M-param bench model vs f32 moments and halves
    optimizer-state memory traffic per step (+4.5% MFU measured on v5e).
    Returns (init, update) with the optax transform contract.
    """
    import jax
    import jax.numpy as jnp

    def init(params):
        zeros16 = lambda p: jnp.zeros_like(p, dtype=jnp.bfloat16)  # noqa: E731
        return (
            jax.tree.map(zeros16, params),
            jax.tree.map(zeros16, params),
            jnp.zeros((), jnp.int32),
        )

    def update(grads, state, params):
        mu, nu, count = state
        count = count + 1
        mu = jax.tree.map(
            lambda m, g: (b1 * m.astype(jnp.float32) + (1 - b1) * g).astype(jnp.bfloat16),
            mu, grads,
        )
        nu = jax.tree.map(
            lambda v, g: (b2 * v.astype(jnp.float32) + (1 - b2) * g * g).astype(jnp.bfloat16),
            nu, grads,
        )
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)

        def upd(p, m, v):
            mhat = m.astype(jnp.float32) / c1
            vhat = v.astype(jnp.float32) / c2
            return -learning_rate * (mhat / (jnp.sqrt(vhat) + eps) + wd * p)

        updates = jax.tree.map(upd, params, mu, nu)
        return updates, (mu, nu, count)

    return init, update


def make_train_step(cfg: ModelConfig, learning_rate: float = 1e-3):
    """Returns (init_opt_state, train_step)."""
    import jax

    if cfg.opt_impl == "fused":
        from tpudra.workload.opt_kernel import fused_adamw

        finit, fapply = fused_adamw(learning_rate)

        def train_step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
            params, opt_state = fapply(params, grads, opt_state)
            return params, opt_state, loss

        return finit, train_step

    init, update = adamw_bf16_moments(learning_rate)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
        updates, opt_state = update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return init, train_step


# -- sharding layout ---------------------------------------------------------


def param_specs(cfg: ModelConfig):
    """Megatron-style tensor-parallel layout as PartitionSpecs.

    Column-parallel (output dim on tp): wqkv, w1, embed's model dim.
    Row-parallel (input dim on tp): wo, w2.  Norms replicated.  MoE models
    shard the expert axis over tp instead (expert parallelism; tp must
    divide num_experts), router replicated.
    """
    from jax.sharding import PartitionSpec as P

    if cfg.num_experts:
        ffn = {
            "router": P(None, None, None),
            "w1": P(None, "tp", None, None),
            "w2": P(None, "tp", None, None),
        }
    else:
        ffn = {"w1": P(None, None, "tp"), "w2": P(None, "tp", None)}
    return {
        "embed": P(None, "tp"),
        "pos": P(None, "tp"),
        "layers": {
            # Attention weights shard the head axis (tp must divide H);
            # the per-head [3hd] / [hd] minors stay whole on each device.
            "wqkv": P(None, None, "tp", None),
            "wo": P(None, "tp", None, None),
            **ffn,
            "ln1": P(None, None),
            "ln2": P(None, None),
        },
        "ln_f": P(None),
    }


def batch_spec():
    """Batch dp-sharded, sequence sp-sharded: long-context inputs split
    across the sp axis so no single device holds the whole sequence."""
    from jax.sharding import PartitionSpec as P

    return P("dp", "sp")


def shard_params(params, mesh, cfg: ModelConfig):
    import jax
    from jax.sharding import NamedSharding

    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: not isinstance(x, dict),
    )

"""Pipeline parallelism (GPipe-style) over the flagship model's layer stack.

TPU-native pipelining: the layer stack's leading (scan) axis is sharded over
a ``pp`` mesh axis with ``shard_map``, microbatches flow stage-to-stage
through ``lax.ppermute`` over ICI, and the whole schedule lives inside one
``lax.scan`` so XLA sees a single compiled loop (no per-tick dispatch).
Backward works by construction — ``ppermute`` has a transpose rule, so
``jax.grad`` through the scheduled scan yields the standard GPipe backward
with gradient accumulation across microbatches.

Design notes (vs a CUDA-style pipeline runtime):
- No send/recv rank programs or stream juggling: every stage executes the
  same SPMD program; ``lax.axis_index("pp")`` picks this device's layer
  chunk and its role in the rotation.
- The schedule is the classic (num_micro + num_stages - 1)-tick loop; the
  bubble fraction is (S-1)/(M+S-1), so callers pick M >= S.
- Stage outputs are gathered with a masked ``psum`` at the end, which also
  gives the transpose a well-defined replication point.

Verified numerically against the dense (non-pipelined) backbone in
tests/test_workload.py::TestPipelineParallel.
"""

from __future__ import annotations

from functools import partial

from tpudra.workload.model import (
    ModelConfig,
    _rmsnorm,
    embed_tokens,
    remat_layer_body,
)


def split_layers(params: dict, num_stages: int) -> dict:
    """Reshape the stacked layer params [L, ...] into [pp, L/pp, ...] so the
    leading axis shards over the pipeline mesh axis."""
    import jax

    def reshape(a):
        L = a.shape[0]
        if L % num_stages:
            raise ValueError(f"{L} layers do not split into {num_stages} stages")
        return a.reshape(num_stages, L // num_stages, *a.shape[1:])

    return jax.tree.map(reshape, params)


def pipelined_backbone(
    params: dict,
    tokens,
    cfg: ModelConfig,
    mesh,
    num_microbatches: int,
    pp_axis: str = "pp",
    dp_axis: str | None = "dp",
):
    """tokens [B, S] → (hidden states [B, S, D], mean MoE aux loss),
    layer stack pipelined.

    ``params`` is the ordinary model param tree; the layer chunk each stage
    holds is carved out inside shard_map.  Embedding and the final norm run
    replicated (they are a sliver of the FLOPs).  The aux scalar is zero
    for dense models.
    """
    import jax
    import jax.numpy as jnp
    from tpudra.workload.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    B, S = tokens.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} does not split into {M} microbatches")
    if pp_axis not in mesh.shape:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has no {pp_axis!r} axis for the pipeline"
        )
    if dp_axis:
        # Validate up front in the same style as the shape checks above —
        # a violation otherwise surfaces as an opaque shard_map/GSPMD
        # sharding error deep inside XLA.
        if dp_axis not in mesh.shape:
            raise ValueError(
                f"mesh {dict(mesh.shape)} has no {dp_axis!r} axis; pass "
                "dp_axis=None to run without data parallelism"
            )
        if (B // M) % mesh.shape[dp_axis]:
            raise ValueError(
                f"microbatch size {B // M} does not split over the "
                f"{dp_axis!r} axis of size {mesh.shape[dp_axis]}"
            )
    num_stages = mesh.shape[pp_axis]

    x = embed_tokens(params, tokens, cfg)
    xs = x.reshape(M, B // M, S, -1)

    stage_layers = split_layers(params["layers"], num_stages)
    # Same (possibly checkpointed) layer body as the dense scan: GPipe
    # leans on remat to bound per-microbatch activation memory.
    layer_body = remat_layer_body(cfg)

    micro_spec = P(None, dp_axis) if dp_axis else P()
    layers_spec = jax.tree.map(lambda _: P(pp_axis), stage_layers)

    # Manual axes: only the pipeline schedule (pp) and the microbatch
    # split (dp) are hand-scheduled.  Every OTHER mesh axis (tp carrying
    # the Megatron/expert sharding) stays GSPMD-automatic INSIDE the stage
    # body — XLA partitions the per-stage einsums over tp and inserts the
    # ICI collectives, composing 3D dp×pp×tp (+ep on tp) in one program.
    manual = frozenset({pp_axis} | ({dp_axis} if dp_axis else set()))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(layers_spec, micro_spec),
        out_specs=(micro_spec, P()),
        axis_names=manual,
        check_vma=False,
    )
    def run(layers, xs):
        # layers leading dim is 1 on each shard: this stage's chunk.
        layers = jax.tree.map(lambda a: a[0], layers)
        stage = jax.lax.axis_index(pp_axis)
        npp = jax.lax.psum(1, pp_axis)

        def stage_fn(x):
            def step(x, lp):
                return layer_body(x, lp)

            x, auxs = jax.lax.scan(step, x, layers)
            return x, jnp.mean(auxs)

        perm = [(i, (i + 1) % npp) for i in range(npp)]
        buf = jnp.zeros_like(xs[0])
        ys = jnp.zeros_like(xs)
        aux_acc = jnp.zeros((), jnp.float32)

        def tick(carry, t):
            buf, ys, aux_acc = carry
            # Stage 0 feeds microbatch t (while in range); later stages
            # consume what the previous stage pushed last tick.
            feed = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            inp = jnp.where(stage == 0, feed, buf)
            out, aux = stage_fn(inp)
            # Stage s computes real microbatches only for s <= t < s+M;
            # warmup/drain ticks run on garbage and must not pollute aux.
            valid = (t >= stage) & (t < stage + M)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            # The last stage finishes microbatch t-(npp-1) this tick.
            widx = t - (npp - 1)
            updated = jax.lax.dynamic_update_index_in_dim(
                ys, out, jnp.clip(widx, 0, M - 1), 0
            )
            write = (stage == npp - 1) & (widx >= 0) & (widx < M)
            ys = jnp.where(write, updated, ys)
            buf = jax.lax.ppermute(out, pp_axis, perm)
            return (buf, ys, aux_acc), None

        (buf, ys, aux_acc), _ = jax.lax.scan(
            tick, (buf, ys, aux_acc), jnp.arange(M + npp - 1)
        )
        # Only the last stage holds real outputs; masked psum replicates
        # them across the pp axis (and anchors the transpose rule).  The
        # psum runs in f32 when the mesh has GSPMD-auto axes: XLA's CPU
        # AllReducePromotion pass aborts on the bf16 all-reduce it emits
        # for partial-manual collectives (crash in CloneAllReduce), and on
        # TPU the one-per-step f32 gather is noise.
        if len(manual) < len(mesh.shape):
            ys = jax.lax.psum(
                jnp.where(stage == npp - 1, ys, 0).astype(jnp.float32), pp_axis
            ).astype(ys.dtype)
        else:
            ys = jax.lax.psum(jnp.where(stage == npp - 1, ys, 0), pp_axis)
        # Every stage contributed M per-microbatch means of its own layer
        # chunk: the psum over stages followed by / (npp * M) is the mean
        # over all (layer, microbatch) pairs — matching the dense path's
        # jnp.mean over layers of full-batch means (equal-size microbatches).
        aux = jax.lax.psum(aux_acc, pp_axis) / (npp * M)
        if dp_axis:
            aux = jax.lax.pmean(aux, dp_axis)
        return ys, aux

    ys, aux = run(stage_layers, xs)
    x = ys.reshape(B, S, -1)
    return _rmsnorm(x, params["ln_f"]), aux


def pipelined_loss_fn(
    params, tokens, cfg: ModelConfig, mesh, num_microbatches: int,
    pp_axis: str = "pp", dp_axis: str | None = "dp",
):
    """Next-token cross-entropy through the pipelined backbone — the
    pipelined twin of model.loss_fn (same math, same head).

    For MoE configs the load-balancing aux is computed per microbatch and
    averaged (the standard data-parallel MoE behavior); it differs from
    the dense full-batch aux by the routing variance across microbatches,
    while the routed token computation itself is identical per token.
    """
    from tpudra.workload.model import ce_head

    x, aux = pipelined_backbone(
        params, tokens, cfg, mesh, num_microbatches, pp_axis, dp_axis
    )
    loss = ce_head(params, x, tokens, cfg)
    if cfg.num_experts:
        loss = loss + cfg.moe_aux_weight * aux
    return loss

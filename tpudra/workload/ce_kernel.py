"""Fused cross-entropy head: a pallas online-softmax kernel.

The chunked CE head (model.ce_head) never materializes the full [N, V]
log-softmax, but its backward keeps the stacked per-chunk f32 logits as
residuals — ~2 GB at the flagship config — and the logsumexp runs as
separate HBM passes over them.  This kernel computes, in one pass over
vocab blocks on the MXU, each token's ``logsumexp(x @ E^T)`` and its
target logit WITHOUT ever writing logits to HBM (the classic
flash-attention-style online max/sum recurrence, applied to the LM head).

Backward recomputes block logits from (x, E, lse) — the custom_vjp costs
one extra logits matmul (8·N·D·V total FLOPs vs the chunked path's 6) in
exchange for dropping the 2 GB residual and its traffic; whether that
trades profitably is measured, not assumed (bench.py extras.ab.ce_fused —
adopted as default only if it wins on hardware).

Shapes: x [N, D] (activation dtype), emb [V, D], targets [N] int32.
N is padded to the row-block size internally; V and D must already be
multiples of 128 (true for every config in this repo: vocab 32768,
d_model ≥ 1024).
"""

from __future__ import annotations

import functools

import jax  # module-level: custom_vjp decorates at import time

BLOCK_N = 512
BLOCK_V = 512  # bv=1024 with double-buffered [bv, D] blocks exceeds the
# 16 MB scoped-VMEM budget at D=2048 (compiles to a catastrophic spill)


def _fwd_kernel(x_ref, emb_ref, tgt_ref, lse_ref, tlog_ref, m_scr, s_scr, t_scr):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    bv = emb_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        s_scr[:] = jnp.zeros(s_scr.shape, jnp.float32)
        t_scr[:] = jnp.zeros(t_scr.shape, jnp.float32)

    # [bn, D] x [bv, D]^T on the MXU, f32 accumulation.
    logits = jax.lax.dot_general(
        x_ref[:], emb_ref[:],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    s_scr[:] = s_scr[:] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(logits - m_new), axis=1, keepdims=True
    )
    m_scr[:] = m_new

    # Target logit: pick it out of this block when the target falls here.
    local = tgt_ref[:] - j * bv  # [bn, 1] int32
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    picked = jnp.sum(
        jnp.where(col == local, logits, 0.0), axis=1, keepdims=True
    )
    t_scr[:] = t_scr[:] + jnp.where(
        (local >= 0) & (local < bv), picked, 0.0
    )

    @pl.when(j == pl.num_programs(1) - 1)
    def _emit():
        lse_ref[:] = m_scr[:] + jnp.log(s_scr[:])
        tlog_ref[:] = t_scr[:]


def _pick_block(total: int, pref: int, align: int) -> int:
    """Largest align-multiple block <= pref that divides total — a grid of
    total // block floors, so a non-dividing block would silently SKIP the
    tail (wrong loss, wrong grads, no error)."""
    for b in range(min(pref, total), 0, -align):
        if total % b == 0:
            return b
    raise ValueError(
        f"no {align}-aligned block divides {total} (pad the dimension to a "
        f"multiple of {align} first)"
    )


def _fwd_pallas(x, emb, targets2d, interpret=False):
    """x [Np, D], emb [V, D], targets2d [Np, 1] → (lse [Np,1], tgt [Np,1]).
    Np must be 8-aligned (fused_ce_mean pads); V must be 128-aligned."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tpudra.workload import jaxcompat

    Np, D = x.shape
    V = emb.shape[0]
    bn = _pick_block(Np, BLOCK_N, 8)
    bv = _pick_block(V, BLOCK_V, 128) if V >= 128 else V
    grid = (Np // bn, V // bv)
    return pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, D), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, D), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Np, 1), jnp.float32),
            jax.ShapeDtypeStruct((Np, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
        ],
        compiler_params=jaxcompat.pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x, emb, targets2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_ce_sum(x, emb, targets, n_valid: int, interpret: bool = False):
    """Sum over the first ``n_valid`` rows of ``logsumexp - target_logit``.

    x [Np, D] activation-dtype, emb [V, D], targets [Np] int32 (pad rows'
    targets are ignored).  Callers divide by token count for the mean and
    must pass an 8-aligned Np (fused_ce_mean pads; a direct caller with an
    odd row count gets a ValueError from the block picker, never a
    silently truncated sum).
    """
    loss, _ = _fused_fwd(x, emb, targets, n_valid, interpret)
    return loss


def _fused_fwd(x, emb, targets, n_valid, interpret):
    import jax.numpy as jnp

    lse, tlog = _fwd_pallas(x, emb.astype(x.dtype), targets[:, None], interpret)
    valid = (jnp.arange(x.shape[0]) < n_valid)[:, None]
    loss = jnp.sum(jnp.where(valid, lse - tlog, 0.0))
    return loss, (x, emb, targets, lse)


BWD_CHUNK = 4096


def _fused_bwd(n_valid, interpret, res, g):
    """Recompute block logits; d_logits = g·(softmax − onehot) on valid
    rows.  Chunked over row blocks inside a scan: the softmax
    intermediate exists only at [chunk, V] (0.5 GB f32 at the flagship
    config vs 2.1 GB unchunked — the unchunked form OOMs the whole train
    step at compile time), with dEmb accumulated across chunks in f32."""
    import jax.numpy as jnp

    x, emb, targets, lse = res
    e_act = emb.astype(x.dtype)
    Np, D = x.shape
    V = emb.shape[0]
    # Largest 8-aligned chunk dividing Np (Np arrives 8-aligned from the
    # forward): a naive "fall back to unchunked on odd sizes" would build
    # the very multi-GB softmax this chunking exists to avoid.
    bn = _pick_block(Np, BWD_CHUNK, 8) if Np % 8 == 0 else Np
    n_chunks = Np // bn
    vocab_iota = jnp.arange(V, dtype=targets.dtype)[None, :]
    row_iota = jnp.arange(Np)

    def chunk_grads(xc, tc, lsec, validc):
        logits = jnp.einsum(
            "nd,vd->nv", xc, e_act, preferred_element_type=jnp.float32
        )
        p = jnp.exp(logits - lsec)
        # onehot via a fused iota-compare (an explicit one_hot would
        # materialize the whole [chunk, V] f32 mask separately).
        d = (
            jnp.where(validc, p - (vocab_iota == tc[:, None]), 0.0) * g
        ).astype(xc.dtype)
        dxc = jnp.einsum("nv,vd->nd", d, e_act).astype(xc.dtype)
        dembc = jnp.einsum("nv,nd->vd", d, xc, preferred_element_type=jnp.float32)
        return dxc, dembc

    if n_chunks == 1:
        valid = (row_iota < n_valid)[:, None]
        dx, demb = chunk_grads(x, targets, lse, valid)
        return dx, demb.astype(emb.dtype), None

    xs = x.reshape(n_chunks, bn, D)
    ts = targets.reshape(n_chunks, bn)
    ls = lse.reshape(n_chunks, bn, 1)
    vs = (row_iota < n_valid).reshape(n_chunks, bn)[..., None]

    def step(demb_acc, inp):
        xc, tc, lsec, validc = inp
        dxc, dembc = chunk_grads(xc, tc, lsec, validc)
        return demb_acc + dembc, dxc

    demb, dxs = jax.lax.scan(
        step, jnp.zeros((V, D), jnp.float32), (xs, ts, ls, vs)
    )
    return dxs.reshape(Np, D), demb.astype(emb.dtype), None


fused_ce_sum.defvjp(_fused_fwd, _fused_bwd)


def fused_ce_mean(x2d, emb, targets1d, interpret: bool = False):
    """Mean next-token CE over x2d [N, D] / targets1d [N] — pads N up to
    the row block and masks the pad rows out of the sum."""
    import jax.numpy as jnp

    N, D = x2d.shape
    # Row block: the tuned size for real workloads; small (test) inputs
    # round up to a sublane-aligned single block.
    bn = BLOCK_N if N >= BLOCK_N else -(-N // 8) * 8
    Np = -(-N // bn) * bn
    if Np != N:
        x2d = jnp.pad(x2d, ((0, Np - N), (0, 0)))
        targets1d = jnp.pad(targets1d, (0, Np - N))
    return fused_ce_sum(x2d, emb, targets1d, N, interpret) / N

"""Claim environment → JAX mesh.

Parses the env the TPU plugin's CDI spec injects (plugin/cdi.py chip_edits +
device_state._write_cdi_spec) and the slice-level env the ComputeDomain
daemon config injects, and builds the ``jax.sharding.Mesh`` a workload should
run under.  This is the TPU answer to "the pod sees exactly the granted
devices": on GPUs the runtime hides device nodes; on TPU the visibility env
(TPU_VISIBLE_DEVICES) plus ICI coordinates do the same job, and the mesh
shape follows the granted topology rather than a hardcoded world size.
"""

from __future__ import annotations

import logging
import os
from contextlib import contextmanager as _contextmanager
from dataclasses import dataclass, field
from typing import Optional

logger = logging.getLogger(__name__)


@dataclass
class ClaimEnv:
    """Everything the driver told this container about its grant."""

    visible_devices: list[int] = field(default_factory=list)
    coords: list[tuple[int, int, int]] = field(default_factory=list)
    clique_id: str = ""
    generation: str = ""
    # "name=profile@core_start,hbm_start" per granted partition.
    partitions: dict[str, str] = field(default_factory=dict)
    # ComputeDomain slice env (set by the CD daemon config, not per-chip).
    domain_uid: str = ""
    channel_ids: list[int] = field(default_factory=list)
    num_hosts: int = 1
    host_index: int = 0
    coordinator: str = ""  # host:port for jax.distributed DCN rendezvous
    # Per-domain shared dir (host path mounted into both the workload and
    # the daemon pods): host 0 registers its live coordinator endpoint here
    # for the daemon's proxy to forward to.
    cd_dir: str = ""
    # Multi-process sharing (MPS analog): the per-claim control daemon's
    # pipe directory, injected by the plugin's CDI edits.
    mp_pipe_dir: str = ""
    # Trace context of the bind that granted this claim (tpudra/trace.py
    # TPUDRA_TRACEPARENT): worker ranks open child spans of the member
    # bind, completing the controller→plugin→rank chain.  "" = untraced.
    traceparent: str = ""
    # Slice geometry from the grant (cdplugin/libtpuenv.slice_env): the
    # full ICI mesh of the slice and this host's block origin within it.
    # () = not granted (single-host chip claims carry no slice env).
    mesh_shape: tuple = ()
    host_coords: tuple = ()
    # The libtpu worker-bootstrap contract (cdplugin/libtpuenv.py): the env
    # libtpu itself reads to form the ICI mesh on a multi-host slice —
    # orthogonal to the JAX-level rendezvous above.
    worker_id: int = -1  # -1 = not granted (single-host / no CD)
    worker_hostnames: list[str] = field(default_factory=list)
    skip_mds_query: bool = False
    host_bounds: str = ""  # "x,y,z" host grid of the slice
    chips_per_host_bounds: str = ""  # "x,y,z" chip block per host

    @classmethod
    def from_environ(cls, env: Optional[dict] = None) -> "ClaimEnv":
        env = dict(os.environ if env is None else env)
        out = cls()
        vis = env.get("TPU_VISIBLE_DEVICES", "")
        if vis:
            out.visible_devices = [int(x) for x in vis.split(",") if x != ""]
        for xyz in env.get("TPUDRA_CHIP_COORDS", "").split(";"):
            if xyz:
                x, y, z = (int(v) for v in xyz.split(","))
                out.coords.append((x, y, z))
        out.clique_id = env.get("TPUDRA_CLIQUE_ID", "")
        out.generation = env.get("TPUDRA_GENERATION", "")
        for desc in env.get("TPUDRA_PARTITIONS", "").split(";"):
            if desc and "=" in desc:
                name, spec = desc.split("=", 1)
                out.partitions[name] = spec
        out.domain_uid = env.get("TPUDRA_DOMAIN_UID", "")
        chans = env.get("TPUDRA_DOMAIN_CHANNELS", "")
        if chans:
            out.channel_ids = [int(x) for x in chans.split(",") if x != ""]
        out.num_hosts = int(env.get("TPUDRA_NUM_HOSTS", "1") or "1")
        out.host_index = int(env.get("TPUDRA_HOST_INDEX", "0") or "0")
        out.coordinator = env.get("TPUDRA_COORDINATOR", "")
        out.cd_dir = env.get("TPUDRA_CD_DIR", "")
        for attr, key in (
            ("mesh_shape", "TPUDRA_MESH_SHAPE"),
            ("host_coords", "TPUDRA_HOST_COORDS"),
        ):
            raw = env.get(key, "")
            if raw:
                try:
                    setattr(out, attr, tuple(int(v) for v in raw.split(",")))
                except ValueError:
                    pass  # garbled → "not granted", like worker_id below
        out.mp_pipe_dir = env.get("TPUDRA_MP_PIPE_DIRECTORY", "")
        out.traceparent = env.get("TPUDRA_TRACEPARENT", "")
        try:
            out.worker_id = int(env.get("TPU_WORKER_ID", ""))
        except ValueError:
            out.worker_id = -1  # absent or garbled → "not granted"
        hostnames = env.get("TPU_WORKER_HOSTNAMES", "")
        out.worker_hostnames = [h for h in hostnames.split(",") if h]
        out.skip_mds_query = env.get("TPU_SKIP_MDS_QUERY", "").lower() in (
            "true", "1",
        )
        out.host_bounds = env.get("TPU_HOST_BOUNDS", "")
        out.chips_per_host_bounds = env.get("TPU_CHIPS_PER_HOST_BOUNDS", "")
        return out

    @property
    def mesh_bounds(self) -> tuple[int, int, int]:
        """Bounding box of the granted chips in ICI coordinates — the natural
        physical mesh shape when the grant is a contiguous block."""
        if not self.coords:
            return (0, 0, 0)
        xs, ys, zs = zip(*self.coords)
        return (
            max(xs) - min(xs) + 1,
            max(ys) - min(ys) + 1,
            max(zs) - min(zs) + 1,
        )

    def libtpu_env(self) -> dict[str, str]:
        """The worker-bootstrap env libtpu reads to form the ICI mesh
        (cdplugin/libtpuenv.py docstring has the full contract).  Empty for
        grants that never carried it (single-host chip claims)."""
        out: dict[str, str] = {}
        if self.worker_id >= 0:
            out["TPU_WORKER_ID"] = str(self.worker_id)
        if self.worker_hostnames:
            out["TPU_WORKER_HOSTNAMES"] = ",".join(self.worker_hostnames)
        if self.skip_mds_query:
            out["TPU_SKIP_MDS_QUERY"] = "true"
        if self.host_bounds:
            out["TPU_HOST_BOUNDS"] = self.host_bounds
        if self.chips_per_host_bounds:
            out["TPU_CHIPS_PER_HOST_BOUNDS"] = self.chips_per_host_bounds
        return out

    def apply_libtpu_env(self) -> dict[str, str]:
        """Materialize the contract into ``os.environ`` and return it.

        Call BEFORE importing jax: libtpu is a C library that reads the
        real process env at load time, so values parsed from anywhere else
        (a constructed env dict, a settings file) must be exported before
        the first jax import loads it.  In a CDI-wired container this is a
        no-op re-export of what the runtime already injected; it exists for
        processes that assemble their env by hand (launchers, tests, the
        cluster sim's pod runtime).
        """
        env = self.libtpu_env()
        os.environ.update(env)
        return env

    def initialize_distributed(self) -> None:
        """Join the slice-wide runtime across hosts of a ComputeDomain.

        Multi-host grants carry coordinator/host-count env (written by the CD
        daemon settings); jax.distributed rides DCN for rendezvous while the
        compiled collectives ride ICI.

        TPUDRA_COORDINATOR names the index-0 *daemon* (a stable DNS name) —
        but jax.distributed's coordinator service is bound by *this* process
        when it is host 0, inside its own pod.  So host 0 binds locally,
        publishes its real ``ip:port`` into the shared per-domain dir
        (TPUDRA_CD_DIR), and the daemon's CoordinatorProxy forwards peers
        dialing the stable name to the registered endpoint
        (cddaemon/coordproxy.py)."""
        if self.num_hosts <= 1 or not self.coordinator:
            return
        import jax

        address = self.coordinator
        _, _, port = self.coordinator.rpartition(":")
        if self.host_index == 0 and port.isdigit():
            # A portless coordinator value passes through verbatim (jax
            # reports the malformed address clearly); only a well-formed
            # grant triggers the local-bind + registration path.
            ip = _local_ip()
            if not ip:
                raise RuntimeError(
                    "host 0 has no routable IPv4 address to bind the "
                    "coordinator on — cannot register a loopback address "
                    "(the daemon proxy would forward to itself); IPv6-only "
                    "pod networks need hostNetwork or an explicit "
                    "coordinator service"
                )
            address = f"{ip}:{port}"
            if self.cd_dir:
                from tpudra.cddaemon.coordproxy import write_registration

                try:
                    write_registration(self.cd_dir, ip, int(port))
                except OSError as e:
                    # Crash loudly WITH the diagnosis: a silent skip here
                    # strands every peer in a 300 s connect timeout.
                    raise RuntimeError(
                        "host 0 could not register its coordinator in "
                        f"{self.cd_dir}: {e} — peers dialing "
                        f"{self.coordinator} will hang; check the domain "
                        "dir mount and its permissions"
                    ) from e
            elif _is_daemon_dns_name(self.coordinator):
                # Peers will dial the daemon's proxy, which forwards to the
                # registration this process has nowhere to write — the same
                # outcome as a failed registration (every peer hangs for
                # jax's full 300 s timeout), so fail the same way: loudly,
                # with the diagnosis.  A direct-address coordinator (an IP
                # or reachable hostname, e.g. hand-built launcher env)
                # needs no registration and passes through.
                raise RuntimeError(
                    "host 0 has a daemon-proxied coordinator grant "
                    f"({self.coordinator}) but no TPUDRA_CD_DIR to "
                    "register its endpoint in — peers dialing the proxy "
                    "would hang; this grant predates the domain-dir mount "
                    "(re-prepare the claim with a current driver) or the "
                    "env was stripped"
                )
        # Flip the gloo knob ONLY once every validation above has passed
        # and the distributed client is really being created: the config
        # is process-global, and on jaxlib builds whose gloo factory
        # requires a live distributed client, a knob set on an early-exit
        # path (a grant that fails validation) would poison every later
        # single-process backend init in the process — the exact failure
        # that took out 30 tests in tests/test_workload.py.
        _enable_cpu_collectives(jax)
        jax.distributed.initialize(
            coordinator_address=address,
            num_processes=self.num_hosts,
            process_id=self.host_index,
        )

    @_contextmanager
    def attach_multiprocess(self):
        """Register with the claim's multi-process control daemon and yield
        the granted limits (the CUDA-MPS-client analog: chip UUIDs,
        active-TensorCore percentage, pinned-HBM budgets).

        DETACH happens on exit.  No-op (yields None) when the grant carries
        no multi-process sharing.
        """
        if not self.mp_pipe_dir:
            yield None
            return
        import json
        import socket as _socket
        import uuid as _uuid

        from tpudra.mpdaemon import query

        # Unique per client: consumer containers of one claim live in
        # separate PID namespaces, so a bare pid would collide in the
        # broker's client set (two containers can both be pid 7).
        me = f"{_socket.gethostname()}-{os.getpid()}-{_uuid.uuid4().hex[:8]}"
        resp = query(self.mp_pipe_dir, f"ATTACH {me}")
        if not resp.startswith("OK "):
            raise RuntimeError(f"mp control daemon refused attach: {resp}")
        try:
            yield json.loads(resp[3:])
        finally:
            try:
                query(self.mp_pipe_dir, f"DETACH {me}")
            except OSError:
                pass  # daemon went away; nothing to release


    @property
    def slice_device_count(self) -> int:
        """Chips in the granted slice, from the mesh-shape grant env — the
        number ``jax.devices()`` must report once the slice-wide runtime is
        up (the multi-host harness's "pod sees exactly the granted
        topology" assertion).  0 when the grant carried no slice env."""
        if not self.mesh_shape:
            return 0
        n = 1
        for v in self.mesh_shape:
            n *= v
        return n


def _enable_cpu_collectives(jax) -> None:
    """Multi-process collectives on the CPU backend need an explicit
    cross-process implementation (gloo); without it every cross-process
    jit is rejected with "Multiprocess computations aren't implemented on
    the CPU backend" — the failure that held test_cd_collective.bats in a
    600 s timeout.  Real TPU processes never take this branch, and jax
    builds without the knob (or with CPU collectives already default) are
    left alone."""
    import os as _os

    platforms = _os.environ.get("JAX_PLATFORMS", "")
    try:
        configured = jax.config.jax_platforms or ""
    except AttributeError:
        configured = ""
    if "cpu" not in (platforms, configured):
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — knob absent: newer jax defaults it
        logger.info("jax_cpu_collectives_implementation knob unavailable")


def _is_daemon_dns_name(coordinator: str) -> bool:
    """True when the coordinator address names a compute-domain daemon's
    stable DNS name (the proxy-relayed rendezvous path) rather than a
    directly reachable host."""
    from tpudra.cddaemon.dnsnames import DNS_NAME_FORMAT

    prefix = DNS_NAME_FORMAT.split("%")[0]
    return coordinator.partition(":")[0].startswith(prefix)


def _local_ip() -> str:
    """This pod's routable IP: a connected UDP socket's local address
    (no packet is sent; works without DNS for the pod's own hostname).
    Returns "" when no IPv4 route exists — callers must treat that as an
    error, NOT fall back to loopback: registering 127.0.0.1 would point
    the daemon's coordinator proxy at itself (its own netns), and each
    forwarded connection would re-enter the proxy in a self-connect loop."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return ""
    finally:
        s.close()


def mesh_from_devices(
    axis_names: tuple[str, ...] = ("data",),
    axis_shape: Optional[tuple[int, ...]] = None,
    devices=None,
):
    """Build a Mesh over the claim's devices.

    Default: one flat axis over everything granted.  ``axis_shape`` factors
    the device count into named axes (dp/tp/sp/...); the order follows
    jax.devices() order, which libtpu guarantees matches ICI adjacency for
    the innermost axis — so put the bandwidth-hungry axis (tp) last.
    """
    import jax
    import numpy as np

    devices = list(jax.devices() if devices is None else devices)
    if axis_shape is None:
        axis_shape = (len(devices),)
        if len(axis_names) != 1:
            raise ValueError("axis_shape required for multi-axis meshes")
    n = int(np.prod(axis_shape))
    if n != len(devices):
        raise ValueError(f"axis_shape {axis_shape} != {len(devices)} devices")
    arr = np.asarray(devices).reshape(axis_shape)
    return jax.sharding.Mesh(arr, axis_names)


def factor_devices(n: int, axes: int = 3) -> tuple[int, ...]:
    """Factor a device count into a balanced shape, largest factor last
    (innermost = ICI-nearest).  8 → (2, 2, 2); 4 → (1, 2, 2); 1 → (1, 1, 1)."""
    shape = [1] * axes
    i = axes - 1
    remaining = n
    while remaining > 1:
        for f in (2, 3, 5, 7):
            if remaining % f == 0:
                shape[i] = shape[i] * f
                remaining //= f
                break
        else:
            shape[i] *= remaining
            remaining = 1
        i = (i - 1) if i > 0 else axes - 1
    shape.sort()
    return tuple(shape)

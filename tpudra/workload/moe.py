"""Mixture-of-experts FFN with expert parallelism, the XLA way.

A Switch-style top-1 MoE layer in the Mesh-TensorFlow dispatch/combine
formulation: routing builds a [tokens, experts, capacity] dispatch tensor,
expert FFNs run batched over the expert axis, and a combine einsum gathers
outputs back to token order.

Expert parallelism is NOT hand-written communication: the math is dense
einsums, and sharding the expert axis of the weights over an ``ep`` mesh
axis makes GSPMD partition the expert FFN FLOPs and insert the dispatch/
combine collectives over ICI (its cost model picks all-to-all or
gather/reduce combinations by shape) — the TPU-native equivalent of the
reference ecosystem's NCCL all-to-all expert dispatch.  ``expert_specs``
gives the PartitionSpecs; tests/test_workload.py verifies the ep-sharded
program matches the single-device result bit-for-bit, that the per-shard
expert computation really is E/ep-sized, and that cross-device
collectives are present in the compiled HLO.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class MoEConfig:
    d_model: int = 128
    d_ff: int = 256
    num_experts: int = 4
    # capacity = capacity_factor * tokens / num_experts, rounded up to a
    # multiple of 8 (TPU lane alignment); overflowing tokens are dropped
    # (their residual passes through), the standard Switch behavior.
    capacity_factor: float = 1.25
    # Expert-compute dtype: "bf16" (default) or "f32" — see
    # model.ModelConfig.compute_dtype for when f32 is the right call.
    compute_dtype: str = "bf16"

    @property
    def act_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.compute_dtype == "bf16" else jnp.float32

    def capacity(self, num_tokens: int) -> int:
        import math

        cap = math.ceil(self.capacity_factor * num_tokens / self.num_experts)
        return max(8, -(-cap // 8) * 8)


def init_moe_params(rng, cfg: MoEConfig):
    import jax
    import jax.numpy as jnp

    kr, k1, k2 = jax.random.split(rng, 3)
    E, D, F = cfg.num_experts, cfg.d_model, cfg.d_ff
    return {
        "router": jax.random.normal(kr, (D, E), jnp.float32) * (D ** -0.5),
        "w1": jax.random.normal(k1, (E, D, F), jnp.float32) * (D ** -0.5),
        "w2": jax.random.normal(k2, (E, F, D), jnp.float32) * (F ** -0.5),
    }


def expert_specs(ep_axis: str = "ep"):
    """PartitionSpecs sharding the expert axis (router replicated)."""
    from jax.sharding import PartitionSpec as P

    return {"router": P(), "w1": P(ep_axis), "w2": P(ep_axis)}


def moe_ffn(params, x, cfg: MoEConfig):
    """x [B, S, D] -> [B, S, D]; top-1 routed expert FFN + aux load loss.

    Returns (y, aux) where aux is the Switch load-balancing loss
    (mean fraction * mean router prob per expert, scaled by E).
    """
    import jax
    import jax.numpy as jnp

    B, S, D = x.shape
    E = cfg.num_experts
    T = B * S
    C = cfg.capacity(T)
    tokens = x.reshape(T, D)

    logits = tokens.astype(jnp.float32) @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
    expert = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]  # [T]

    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # [T, E]
    # Position of each token within its expert's queue; >= C drops.
    position = (jnp.cumsum(onehot, axis=0) - onehot) * onehot  # [T, E]
    keep = (position < C) * onehot  # [T, E]
    pos_onehot = jax.nn.one_hot(
        position.sum(axis=-1).astype(jnp.int32), C, dtype=jnp.float32
    )  # [T, C]
    dispatch = keep[:, :, None] * pos_onehot[:, None, :]  # [T, E, C]
    combine = dispatch * gate[:, None, None]  # [T, E, C]

    # Dispatch → per-expert FFN → combine.  With w1/w2 (and therefore the
    # [E, C, D] intermediates) sharded over ep, these einsums are where
    # GSPMD places the all-to-alls.  The expert compute path runs in
    # bfloat16 like the dense FFN (router/softmax/aux stay f32): the
    # dispatch/combine tensors are 0/1 masks and gates, exactly
    # representable / tolerably rounded in bf16.
    act = cfg.act_dtype
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(act), tokens.astype(act))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w1"].astype(act)))
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w2"].astype(act))
    y = jnp.einsum(
        "tec,ecd->td", combine.astype(act), expert_out,
        preferred_element_type=jnp.float32,
    )

    # Switch aux loss: encourages uniform routing.
    frac_tokens = onehot.mean(axis=0)  # fraction routed per expert
    frac_probs = probs.mean(axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)

    return y.reshape(B, S, D).astype(x.dtype), aux


def shard_moe_params(params, mesh, ep_axis: str = "ep"):
    import jax
    from jax.sharding import NamedSharding

    specs = expert_specs(ep_axis)
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }

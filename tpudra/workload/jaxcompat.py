"""Version-compat shims for the handful of jax surfaces that moved.

The workload kernels target current jax, but the boxes this repo runs on
pin a range of versions whose public spellings drifted:

- ``shard_map`` graduated from ``jax.experimental.shard_map`` to
  ``jax.shard_map``;
- pallas-TPU's compiler-params dataclass was renamed
  ``TPUCompilerParams`` → ``CompilerParams``.

Each shim resolves the CURRENT spelling first and falls back to the older
one, so the same kernel source runs on both — the tomllib/tomli treatment
from the manifest tests, applied to jax.  When a surface exists under
neither spelling, the probe helpers below give pytest a truthful skip
reason instead of letting collection explode.
"""

from __future__ import annotations

from typing import Optional


def _resolve_shard_map():
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map as fn  # noqa: F811

    return fn


def shard_map(*args, **kwargs):
    """``jax.shard_map`` where it exists, else the experimental spelling
    with the renamed keywords translated (late-bound per call so importing
    this module never imports jax):

    - ``check_vma`` (current) ↔ ``check_rep`` (experimental);
    - ``axis_names`` (current: the MANUAL axes) ↔ ``auto`` (experimental:
      its complement over the mesh's axes).
    """
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn(*args, **kwargs)
    from jax.experimental.shard_map import shard_map as old

    kwargs = dict(kwargs)
    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    axis_names = kwargs.pop("axis_names", None)
    if axis_names is not None:
        mesh = kwargs.get("mesh") or (args[1] if len(args) > 1 else None)
        if mesh is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                # The experimental port's `auto=` mode miscompiles the
                # partial-manual composition (PartitionId-under-SPMD,
                # out-spec errors) — the exact reason
                # missing_capability('shard_map-partial-manual') skips it.
                # Refuse loudly rather than translate to wrong results.
                raise NotImplementedError(
                    "partial-manual shard_map (axis_names a strict subset "
                    "of the mesh axes) needs native jax.shard_map; this "
                    "jax build has only the experimental port, whose "
                    "auto= mode miscompiles the composition"
                )
    return old(*args, **kwargs)


def pcast(x, axis_names, to: str = "varying"):
    """``lax.pcast`` where it exists.  Pre-varying-types jax has no
    manual-axis type system, so there is nothing to annotate — the value
    IS already per-device — and the identity is the faithful translation,
    not an approximation."""
    from jax import lax

    fn = getattr(lax, "pcast", None)
    if fn is not None:
        return fn(x, axis_names, to=to)
    return x


def pallas_tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (current) or ``pltpu.TPUCompilerParams``
    (older jaxlib), constructed with the given fields."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return cls(**kwargs)


def missing_capability(name: str) -> Optional[str]:
    """None when ``name`` is available on this box's jax, else a skip
    reason naming what is missing (the pytest guard the workload tests
    use so an incompatible jax build skips-with-reason instead of
    failing tier-1)."""
    try:
        if name == "shard_map":
            _resolve_shard_map()
        elif name == "shard_map-partial-manual":
            # Mixed auto/manual composition (a manual ring axis inside a
            # GSPMD-partitioned program) needs the NATIVE jax.shard_map
            # with the varying-types system (lax.pcast): the experimental
            # port's `auto=` mode miscompiles it (PartitionId-under-SPMD,
            # out-spec errors), so translation would be a lie — skip.
            import jax
            from jax import lax

            if getattr(jax, "shard_map", None) is None or not hasattr(
                lax, "pcast"
            ):
                return (
                    "partial-manual shard_map composition needs native "
                    "jax.shard_map + lax.pcast (this jax build has only "
                    "the experimental port)"
                )
        elif name == "pallas-tpu":
            from jax.experimental.pallas import tpu as pltpu

            if not (
                hasattr(pltpu, "CompilerParams")
                or hasattr(pltpu, "TPUCompilerParams")
            ):
                return "pallas-tpu has no CompilerParams/TPUCompilerParams"
        else:
            return f"unknown capability probe {name!r}"
    except Exception as e:  # noqa: BLE001 — the reason IS the product
        return f"{name} unavailable on this jax build: {type(e).__name__}: {e}"
    return None

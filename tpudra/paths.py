"""Runtime file-resolution shared by components that read non-package data
(daemon templates, native library).

Resolution order everywhere: explicit environment override → in-repo path
(dev checkout) → system install location (what the container image ships).
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def template_path(name: str) -> str:
    """Locate a runtime-rendered template (templates/*.tmpl.yaml)."""
    env_dir = os.environ.get("TPUDRA_TEMPLATES_DIR")
    if env_dir:
        return os.path.join(env_dir, name)
    repo = os.path.join(_REPO_ROOT, "templates", name)
    if os.path.exists(repo):
        return repo
    return os.path.join("/templates", name)

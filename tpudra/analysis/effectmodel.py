"""tpudra-effectgraph: the whole-program WAL crash-consistency model.

Where lockmodel.py proves ordering facts about *locks*, this module proves
ordering facts about the repo's actual survival contract: every hardware /
disk / daemon side effect on the bind path is dominated by a durable intent
record, every record kind that can land in the checkpoint has a recovery
handler, every controller commit goes through the leadership fence, and
cross-family mutators touch record families in the canonical stripe order
(the pre-flight for ROADMAP item 1's striped checkpoint).

Built on the same shared parse pass and call graph as the lock analysis:

1. **Record-kind classification** — every ``cp.prepared_claims[KEY]``
   write/pop/read is classified into a record family by its key shape:
   constant prefixes (``partition/``, ``gang/``, ``gangmeta/term``), the
   well-known constructors (``partrec.record_uid``, ``_guid``,
   ``make_record``), uid-ish variable names, or an explicit
   ``# tpudra-wal: kind=NAME <why>`` annotation.  Unclassifiable keys are
   excluded from the ordering/commit sets rather than guessed.

2. **Commit-kind extraction** — a ``*.mutate(fn, ...)`` call on a
   checkpoint-ish receiver is a *commit site*; its kinds are the
   transitive classified touches of the resolved mutator closure
   (nested defs, lambdas, called helpers like ``_start_one``, and
   function-valued parameters such as the gang fence funnel's ``fn``).

3. **Interprocedural effect walk** — from every call-graph root,
   statements are walked in order carrying the running *journaled* set;
   commits add kinds, registered effect calls check them.  The walk is
   linear (order-sensitive, path-insensitive): a commit lexically earlier
   on ANY branch counts, which over-approximates domination the same way
   every static rule here errs toward silence on conditional paths — the
   runtime witness (tpudra/walwitness.py) is the cross-check for the
   missed-violation direction, exactly like the lock witness.

Rule families (all anchored at real sites, all suppressible the standard
way):

- ``WAL-INTENT-BEFORE-EFFECT`` — a registered side effect reachable with
  no journaled intent record of its matching kind dominating it.
- ``WAL-RECOVERY-EXHAUSTIVE`` — two-sided: every record kind committed
  anywhere has a ``# tpudra-wal: recovers=KIND`` handler, and every
  declared handler matches a kind actually committed (dead handlers and
  orphan kinds are both findings; unknown kind names too).
- ``FENCE-DOMINATES-COMMIT`` — a checkpoint commit site in controller
  code whose enclosing function never consults the ``gangmeta/term``
  fence record (the static form of the runtime StaleLeader refusal).
- ``STRIPE-ORDER`` — a mutator scope that first-touches record families
  out of the canonical ``gangmeta < gang < claim < partition`` order.

Annotations (comment on the line, or alone on the line above):

    # tpudra-wal: kind=NAME <reason>          — classify this record key
    # tpudra-wal: recovers=KIND[,KIND] <reason> — this function is the
    #     recovery-sweep handler for KIND (its subtree treats KIND as
    #     journaled: recovery acts from checkpoint truth)
    # tpudra-wal: nonrecoverable <reason>     — this effect (or every
    #     effect in this function) deliberately runs without a journaled
    #     intent record; the reason must say why convergence still holds
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Optional

from tpudra.analysis import astutil
from tpudra.analysis.callgraph import CallGraph, FunctionInfo
from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.lockmodel import _rel
from tpudra.walwitness import record_kind

#: Canonical stripe order — the family-lock acquisition order the striped
#: checkpoint (ROADMAP item 1) will enforce at runtime.  gangmeta first:
#: the fence outranks everything it fences.  gang before claim before
#: partition mirrors ownership: a gang spans claims, a claim spans its
#: partitions — acquiring owners before leaves keeps cross-stripe commits
#: deadlock-free by construction.
STRIPE_FAMILIES = ("gangmeta", "gang", "claim", "partition")
_STRIPE_INDEX = {k: i for i, k in enumerate(STRIPE_FAMILIES)}

#: Receiver names that denote a CheckpointManager for ``.mutate`` commit
#: detection (name-heuristic, like every classification in astutil).
_CP_RECEIVERS = frozenset({"_cp", "cp", "cpw", "cp_mgr", "checkpoints", "checkpoint"})

#: Well-known uid-constructor names (plugin/partitions.py, controller/gang.py).
_KEY_CALL_KINDS = {"record_uid": "partition", "_guid": "gang"}
_PREFIX_NAME_KINDS = {
    "GANG_UID_PREFIX": "gang",
    "GANG_META_UID": "gangmeta",
    "PARTITION_RECORD_PREFIX": "partition",
}
#: RHS constructor hints: ``partrec.make_record(...)`` builds a partition
#: record, ``self._record(...)`` a gang record (gang.py's only record ctor).
_VALUE_CALL_KINDS = {"make_record": "partition", "_record": "gang"}

_MAX_CLOSURE_DEPTH = 6
_MAX_WALK_DEPTH = 14

_WAL_ANNOTATION_RE = re.compile(r"#\s*tpudra-wal:\s*(?P<body>.+)")
_WAL_KV_RE = re.compile(r"^(kind|recovers)=(\S+)$")


# ---------------------------------------------------------------- annotations


@dataclass
class WalDirective:
    kind: Optional[str] = None
    recovers: tuple[str, ...] = ()
    nonrecoverable: bool = False
    line: int = 0


class WalAnnotations:
    """``# tpudra-wal: ...`` directives of one file, by line (a directive
    alone on its line also covers the next, like lint suppressions and
    lock annotations)."""

    def __init__(self, source: str):
        self.by_line: dict[int, WalDirective] = {}
        try:
            tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _WAL_ANNOTATION_RE.search(tok.string)
                if not m:
                    continue
                directive = WalDirective(line=tok.start[0])
                for word in m.group("body").split():
                    kv = _WAL_KV_RE.match(word)
                    if kv:
                        if kv.group(1) == "kind":
                            directive.kind = kv.group(2)
                        else:
                            directive.recovers = tuple(kv.group(2).split(","))
                    elif word == "nonrecoverable":
                        directive.nonrecoverable = True
                    else:
                        break  # free-text reason starts
                line = tok.start[0]
                self.by_line[line] = directive
                if tok.line.strip().startswith("#"):
                    self.by_line.setdefault(line + 1, directive)
        except tokenize.TokenError:
            pass

    def at(self, line: int) -> Optional[WalDirective]:
        return self.by_line.get(line)


# -------------------------------------------------------------- effect specs


@dataclass(frozen=True)
class EffectSpec:
    """One registered irreversible-ish side effect: the call shape that
    identifies it and the record kind whose durable intent must dominate
    it.  Teardown counterparts (delete_claim_spec_file, daemon.stop,
    vfio unconfigure) are deliberately NOT registered: they are
    convergent-by-design idempotent cleanup the recovery sweep re-runs
    freely — only effects that *create* state the checkpoint must cover
    need a dominating intent record."""

    effect_id: str  # stable id, shared with tpudra/walwitness.py hooks
    attr: str  # called attribute name
    receivers: frozenset  # receiver terminal-name hints
    requires: str  # record kind that must be journaled first


EFFECTS: tuple[EffectSpec, ...] = (
    EffectSpec(
        "partition:create", "create_partition",
        frozenset({"_lib", "lib", "devicelib"}), "partition",
    ),
    EffectSpec(
        "partition:destroy", "delete_partition",
        frozenset({"_lib", "lib", "devicelib"}), "partition",
    ),
    EffectSpec(
        "cdi:spec-write", "create_claim_spec_file",
        frozenset({"_cdi", "cdi"}), "claim",
    ),
    EffectSpec(
        "daemon:start", "new_daemon", frozenset({"_mp", "mp"}), "claim",
    ),
    EffectSpec(
        "timeslice:set", "set_timeslice", frozenset({"_ts", "ts"}), "claim",
    ),
    EffectSpec(
        "vfio:configure", "configure", frozenset({"_vfio", "vfio"}), "claim",
    ),
    EffectSpec(
        "gang:bind", "bind", frozenset({"_binder", "binder"}), "gang",
    ),
)

_EFFECT_BY_ATTR: dict[str, list[EffectSpec]] = {}
for _spec in EFFECTS:
    _EFFECT_BY_ATTR.setdefault(_spec.attr, []).append(_spec)


# ------------------------------------------------------------------- results


@dataclass
class WriteSite:
    path: str
    line: int
    kind: Optional[str]
    is_pop: bool = False
    nonrecoverable: bool = False


@dataclass
class CommitSite:
    path: str
    line: int
    qualname: str  # enclosing top-level function
    kinds: set = field(default_factory=set)  # touched (read or written)
    written: set = field(default_factory=set)
    fenced: bool = False
    in_controller: bool = False


@dataclass
class EffectSite:
    spec: EffectSpec
    path: str
    line: int
    chain: str = ""  # root → ... call chain of the first walk reaching it
    journaled_ok: bool = False
    nonrecoverable: bool = False
    reached: bool = False


@dataclass
class KindInfo:
    kind: str
    written_at: list = field(default_factory=list)  # [(path, line)]
    handlers: list = field(default_factory=list)  # [(path, line, qualname)]


@dataclass
class EffectGraphResult:
    kinds: dict  # kind → KindInfo
    effects: list  # [EffectSite], sorted
    commits: list  # [CommitSite], sorted
    findings: list  # [Finding]

    def effect_ids(self) -> set:
        """Effect ids with at least one static call site — the model's
        universe for the witness merge (a witnessed id outside it is a
        model gap)."""
        return {e.spec.effect_id for e in self.effects}

    def required_kind(self, effect_id: str) -> Optional[str]:
        for spec in EFFECTS:
            if spec.effect_id == effect_id:
                return spec.requires
        return None


# ------------------------------------------------------------------ analysis


@dataclass
class _Callable:
    """One walkable callable: a top-level function/method, or a nested
    def / lambda whose ``ctx`` is the enclosing FunctionInfo (for self/
    import resolution and finding anchors)."""

    node: ast.AST  # FunctionDef | Lambda
    ctx: FunctionInfo
    label: str


def _ordered_calls(node: ast.AST):
    """Call nodes in document order, not descending into nested function /
    class / lambda bodies (those run when called, not here)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(
            child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        if isinstance(child, ast.Call):
            yield child
        yield from _ordered_calls(child)


def _nested_defs(node: ast.AST) -> dict:
    """name → FunctionDef for every def nested anywhere under ``node``
    (first definition wins; shadowing nested defs would be a lint smell
    anyway)."""
    out: dict[str, ast.FunctionDef] = {}
    for sub in ast.walk(node):
        if sub is node:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(sub.name, sub)
    return out


def _short(qualname: str) -> str:
    mod, _, rest = qualname.partition(":")
    return rest or mod


class EffectAnalysis:
    def __init__(self, modules: list, graph: Optional[CallGraph] = None):
        self.modules = modules
        self.graph = graph or CallGraph(modules)
        self.annotations = {m.path: WalAnnotations(m.source) for m in modules}
        self.findings: list[Finding] = []
        self.effect_sites: dict[tuple, EffectSite] = {}  # (path, line, id)
        self.commit_sites: dict[tuple, CommitSite] = {}  # (path, line)
        self.kind_writes: dict[str, list] = {}  # kind → [(path, line, nonrec)]
        self.handlers: dict[str, list] = {}  # kind → [(path, line, qualname)]
        self._scan_cache: dict[int, tuple] = {}
        #: memo key → frozenset of kinds the walk ADDED to its caller's
        #: journaled set (replayed on memo hits; a bare visited-set would
        #: lose a callee's commits for every caller after the first).
        self._walk_memo: dict = {}
        self._violations: dict[tuple, Finding] = {}
        self._walked_nested: set = set()

    # -- annotation helpers -------------------------------------------------

    def _ann(self, path: str, line: int) -> Optional[WalDirective]:
        ann = self.annotations.get(path)
        return ann.at(line) if ann is not None else None

    def _check_known_kinds(self, d: WalDirective, path: str) -> None:
        for name in ((d.kind,) if d.kind else ()) + d.recovers:
            if name not in _STRIPE_INDEX:
                self.findings.append(
                    Finding(
                        path, d.line, 0, "WAL-RECOVERY-EXHAUSTIVE",
                        f"annotation names unknown record kind {name!r} — "
                        f"known kinds: {', '.join(STRIPE_FAMILIES)}",
                    )
                )

    # -- key classification -------------------------------------------------

    def _classify_name(self, name: str) -> Optional[str]:
        low = name.lower()
        if low == "gang_meta_uid":
            return "gangmeta"
        if low.startswith("rec") or "record" in low:
            return None  # record-uid locals carry any family; annotate
        if low == "guid" or "gang" in low:
            return "gang"
        if low == "uid" or low.endswith("uid"):
            return "claim"
        return None

    def _classify_expr(self, e: ast.AST) -> Optional[str]:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            return record_kind(e.value)
        if isinstance(e, ast.Name):
            kind = _PREFIX_NAME_KINDS.get(e.id)
            return kind or self._classify_name(e.id)
        if isinstance(e, ast.Attribute):
            kind = _PREFIX_NAME_KINDS.get(e.attr)
            return kind or self._classify_name(e.attr)
        if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
            left = e.left
            if isinstance(left, ast.Constant) and isinstance(left.value, str):
                return record_kind(left.value + "x")
            if isinstance(left, (ast.Name, ast.Attribute)):
                name = left.id if isinstance(left, ast.Name) else left.attr
                if name in _PREFIX_NAME_KINDS:
                    return _PREFIX_NAME_KINDS[name]
        if isinstance(e, ast.Call):
            return _KEY_CALL_KINDS.get(astutil.call_name(e))
        return None

    def _classify_write(
        self, key: ast.AST, value: Optional[ast.AST], path: str, line: int
    ) -> Optional[str]:
        d = self._ann(path, line)
        if d is not None and d.kind:
            return d.kind
        kind = self._classify_expr(key)
        if kind is not None:
            return kind
        if isinstance(value, ast.Call):
            kind = _VALUE_CALL_KINDS.get(astutil.call_name(value))
            if kind is not None:
                return kind
            for kw in value.keywords:
                if kw.arg == "uid":
                    return self._classify_expr(kw.value)
        return None

    # -- scope scanning -----------------------------------------------------

    @staticmethod
    def _prepared_claims_recv(node: ast.AST) -> bool:
        return isinstance(node, ast.Attribute) and node.attr == "prepared_claims"

    def _scan_scope(self, cal: _Callable) -> tuple:
        """(writes, touches, nested) of one callable body, shallow.

        writes: WriteSite per classified-or-not assignment/pop;
        touches: [(kind, line)] including plain reads (a ``.get(key)`` in
        a mutator closure is a touched claim — the delta derivation emits
        a record for it, so it journals intent exactly like an assign);
        nested: name → FunctionDef."""
        key = id(cal.node)
        cached = self._scan_cache.get(key)
        if cached is not None:
            return cached
        writes: list[WriteSite] = []
        touches: list[tuple] = []
        body = cal.node.body if isinstance(cal.node.body, list) else [cal.node.body]
        for sub in astutil.walk_body_shallow(body):
            key_node = value_node = None
            is_pop = False
            is_read = False
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Subscript)
                and self._prepared_claims_recv(sub.targets[0].value)
            ):
                key_node, value_node = sub.targets[0].slice, sub.value
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("pop", "get", "setdefault")
                and self._prepared_claims_recv(sub.func.value)
                and sub.args
            ):
                key_node = sub.args[0]
                is_pop = sub.func.attr == "pop"
                is_read = sub.func.attr == "get"
            elif (
                isinstance(sub, ast.Subscript)
                and isinstance(sub.ctx, ast.Load)
                and self._prepared_claims_recv(sub.value)
            ):
                key_node, is_read = sub.slice, True
            if key_node is None:
                continue
            line = sub.lineno
            kind = self._classify_write(key_node, value_node, cal.ctx.path, line)
            d = self._ann(cal.ctx.path, line)
            nonrec = d.nonrecoverable if d is not None else False
            if kind is not None:
                touches.append((kind, line))
            if not is_read:
                writes.append(
                    WriteSite(cal.ctx.path, line, kind, is_pop, nonrec)
                )
        result = (writes, touches, _nested_defs(cal.node))
        self._scan_cache[key] = result
        return result

    # -- callable resolution ------------------------------------------------

    def _as_callable(
        self, expr: ast.AST, cal: _Callable, bindings: dict
    ) -> Optional[_Callable]:
        if isinstance(expr, ast.Lambda):
            return _Callable(expr, cal.ctx, f"{cal.label}.<lambda>")
        if isinstance(expr, ast.Name):
            bound = bindings.get(expr.id)
            if bound is not None:
                return bound
            _, _, nested = self._scan_scope(cal)
            node = nested.get(expr.id)
            if node is not None:
                return _Callable(node, cal.ctx, f"{cal.label}.{expr.id}")
            fn = self.graph.module_function(cal.ctx.module, expr.id)
            if fn is not None:
                return _Callable(fn.node, fn, _short(fn.qualname))
            return None
        if isinstance(expr, ast.Attribute):
            # A method *reference* (``self.state.run_prepare_effects``):
            # resolve by unique name, the same fallback the call resolver
            # uses for untyped receivers.
            fn = self.graph.unique_method(expr.attr)
            if fn is not None:
                return _Callable(fn.node, fn, _short(fn.qualname))
        return None

    def _bind_args(
        self, call: ast.Call, fn: FunctionInfo, cal: _Callable, bindings: dict
    ) -> dict:
        """Function-valued actual args bound to the callee's parameter
        names — how the gang fence funnel's ``fn`` and the driver's
        effects-phase dispatch resolve."""
        params = [a.arg for a in fn.node.args.args]
        if fn.class_name and params and params[0] in ("self", "cls"):
            params = params[1:]
        out: dict[str, _Callable] = {}
        for i, actual in enumerate(call.args):
            if i >= len(params):
                break
            c = self._as_callable(actual, cal, bindings)
            if c is not None:
                out[params[i]] = c
        for kw in call.keywords:
            if kw.arg and kw.arg in params:
                c = self._as_callable(kw.value, cal, bindings)
                if c is not None:
                    out[kw.arg] = c
        return out

    def _call_targets(
        self, call: ast.Call, cal: _Callable, bindings: dict
    ) -> list:
        """[(callable, child_bindings)] a call may land on."""
        out = []
        func = call.func
        if isinstance(func, ast.Name):
            c = self._as_callable(func, cal, bindings)
            if c is not None:
                if isinstance(c.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # Nested defs close over the enclosing bindings (the
                    # fence funnel's ``fenced`` calls its free ``fn``).
                    child = dict(bindings) if c.ctx is cal.ctx else {}
                else:
                    child = {}
                out.append((c, child))
            return out
        if not isinstance(func, ast.Attribute):
            return out
        fn = self.graph.resolve_call(call, cal.ctx)
        if fn is not None:
            out.append(
                (
                    _Callable(fn.node, fn, _short(fn.qualname)),
                    self._bind_args(call, fn, cal, bindings),
                )
            )
        if func.attr == "_run_effects" and len(call.args) >= 2:
            # Driver._run_effects(items, self.state.run_X_effects, ...):
            # the second arg is invoked per item on worker threads — the
            # reference must be walked as a direct call or the effects
            # phase would look unreachable (and become a journal-less
            # root).  Mirrors lockmodel's effect-target collection.
            c = self._as_callable(call.args[1], cal, bindings)
            if c is not None:
                out.append((c, {}))
        return out

    # -- commit handling ----------------------------------------------------

    @staticmethod
    def _is_commit(call: ast.Call) -> bool:
        return (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "mutate"
            and astutil.terminal_name(call.func.value) in _CP_RECEIVERS
        )

    def _closure_kinds(
        self, cal: _Callable, bindings: dict, depth: int, visited: set
    ) -> tuple:
        """(written, touched, write_sites) of a mutator closure, following
        nested calls and bound function parameters."""
        key = id(cal.node)
        if key in visited or depth > _MAX_CLOSURE_DEPTH:
            return set(), set(), []
        visited = visited | {key}
        writes, touches, _ = self._scan_scope(cal)
        written = {w.kind for w in writes if w.kind is not None}
        touched = {k for k, _ in touches} | written
        sites = list(writes)
        for call in _ordered_calls(cal.node):
            if self._is_commit(call):
                continue  # a nested commit journals for itself
            for target, child in self._call_targets(call, cal, bindings):
                w, t, s = self._closure_kinds(target, child, depth + 1, visited)
                written |= w
                touched |= t
                sites.extend(s)
        return written, touched, sites

    def _commit_kinds(
        self, call: ast.Call, cal: _Callable, bindings: dict
    ) -> tuple:
        arg = call.args[0] if call.args else None
        if arg is None:
            return set(), set(), []
        c = self._as_callable(arg, cal, bindings)
        if c is None:
            return set(), set(), []
        return self._closure_kinds(c, bindings, 0, set())

    def _fence_checked(self, fn: FunctionInfo) -> bool:
        for sub in ast.walk(fn.node):
            if isinstance(sub, ast.Name) and sub.id == "GANG_META_UID":
                return True
            if isinstance(sub, ast.Attribute) and sub.attr == "GANG_META_UID":
                return True
            if (
                isinstance(sub, ast.Constant)
                and isinstance(sub.value, str)
                and sub.value == "gangmeta/term"
            ):
                return True
        return False

    @staticmethod
    def _in_controller(path: str) -> bool:
        rel = _rel(path)
        return "controller" in rel.replace(os.sep, "/").split("/") or (
            "controller" in os.path.basename(path)
        )

    def _note_commit(
        self, call: ast.Call, cal: _Callable, bindings: dict
    ) -> set:
        written, touched, sites = self._commit_kinds(call, cal, bindings)
        key = (cal.ctx.path, call.lineno)
        site = self.commit_sites.get(key)
        if site is None:
            site = CommitSite(
                path=cal.ctx.path,
                line=call.lineno,
                qualname=cal.ctx.qualname,
                fenced=self._fence_checked(cal.ctx),
                in_controller=self._in_controller(cal.ctx.path),
            )
            self.commit_sites[key] = site
        site.kinds |= touched
        site.written |= written
        for w in sites:
            if w.kind is not None and not w.is_pop:
                self.kind_writes.setdefault(w.kind, []).append(
                    (w.path, w.line, w.nonrecoverable)
                )
        return touched

    # -- the interprocedural walk -------------------------------------------

    def _def_directive(self, cal: _Callable) -> Optional[WalDirective]:
        if isinstance(cal.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return self._ann(cal.ctx.path, cal.node.lineno)
        return None

    def _walk(
        self,
        cal: _Callable,
        bindings: dict,
        journaled: set,
        stack: tuple,
        chain: str,
    ) -> None:
        key = id(cal.node)
        if key in stack or len(stack) > _MAX_WALK_DEPTH:
            return
        memo = (
            key,
            frozenset(journaled),
            tuple(sorted((k, id(v.node)) for k, v in bindings.items())),
        )
        cached = self._walk_memo.get(memo)
        if cached is not None:
            # A callee's commits journal for its caller's later calls too —
            # replay what the first walk from this entry state added.
            journaled |= cached
            return
        self._walk_memo[memo] = frozenset()  # in-progress: cycles add nothing
        self._walked_nested.add(key)
        stack = stack + (key,)
        entered = set(journaled)
        for call in _ordered_calls(cal.node):
            if self._is_commit(call):
                journaled |= self._note_commit(call, cal, bindings)
                continue
            self._check_effect(call, cal, journaled, chain)
            for target, child in self._call_targets(call, cal, bindings):
                d = self._def_directive(target)
                if d is not None and d.nonrecoverable:
                    continue  # acknowledged journal-less subtree
                if d is not None and d.recovers:
                    # Recovery acts from checkpoint truth: within the
                    # handler's subtree its kinds ARE journaled — but the
                    # assumption must not leak back to the caller.
                    self._walk(
                        target, child, journaled | set(d.recovers),
                        stack, chain + " → " + target.label,
                    )
                else:
                    self._walk(
                        target, child, journaled,
                        stack, chain + " → " + target.label,
                    )
        self._walk_memo[memo] = frozenset(journaled - entered)

    def _check_effect(
        self, call: ast.Call, cal: _Callable, journaled: set, chain: str
    ) -> None:
        if not isinstance(call.func, ast.Attribute):
            return
        specs = _EFFECT_BY_ATTR.get(call.func.attr)
        if not specs:
            return
        recv = astutil.terminal_name(call.func.value)
        for spec in specs:
            if recv not in spec.receivers:
                continue
            skey = (cal.ctx.path, call.lineno, spec.effect_id)
            site = self.effect_sites.get(skey)
            if site is None:
                site = EffectSite(spec, cal.ctx.path, call.lineno)
                self.effect_sites[skey] = site
            d = self._ann(cal.ctx.path, call.lineno)
            if d is not None and d.nonrecoverable:
                site.nonrecoverable = True
                site.reached = True
                continue
            if spec.requires in journaled:
                site.journaled_ok = True
                if not site.reached:
                    site.chain = chain
                site.reached = True
                continue
            site.reached = True
            vkey = (cal.ctx.path, call.lineno, spec.effect_id)
            if vkey not in self._violations:
                site.chain = chain
                self._violations[vkey] = Finding(
                    cal.ctx.path, call.lineno, call.col_offset,
                    "WAL-INTENT-BEFORE-EFFECT",
                    f"effect '{spec.effect_id}' can run with no journaled "
                    f"'{spec.requires}' intent record dominating it "
                    f"(path: {chain}) — commit the intent (cp.mutate) "
                    "before the side effect, or annotate the site "
                    "'# tpudra-wal: nonrecoverable <why convergence holds>'",
                )

    # -- lexical passes -----------------------------------------------------

    def _collect_handlers_and_stripe(self) -> None:
        for m in self.modules:
            ann = self.annotations[m.path]
            checked: set = set()
            for d in ann.by_line.values():
                # A comment-only directive registers on two lines (its own
                # and the next); validate each directive object once.
                if id(d) not in checked:
                    checked.add(id(d))
                    self._check_known_kinds(d, m.path)
            mod_fns = [
                fn for fn in self.graph.functions.values() if fn.path == m.path
            ]
            seen_nodes: set = set()
            for fn in mod_fns:
                for node in ast.walk(fn.node):
                    if not isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    if id(node) in seen_nodes:
                        continue
                    seen_nodes.add(id(node))
                    d = ann.at(node.lineno)
                    if d is not None and d.recovers:
                        for kind in d.recovers:
                            if kind in _STRIPE_INDEX:
                                self.handlers.setdefault(kind, []).append(
                                    (m.path, node.lineno, node.name)
                                )
                    self._check_stripe_order(
                        _Callable(node, fn, node.name)
                    )

    def _check_stripe_order(self, cal: _Callable) -> None:
        writes, _, _ = self._scan_scope(cal)
        max_idx = -1
        max_kind = ""
        flagged = False
        seen: set = set()
        for w in sorted(writes, key=lambda w: w.line):
            if w.kind is None or w.kind in seen:
                continue
            seen.add(w.kind)
            idx = _STRIPE_INDEX[w.kind]
            if idx < max_idx and not flagged:
                flagged = True
                self.findings.append(
                    Finding(
                        w.path, w.line, 0, "STRIPE-ORDER",
                        f"mutator first-touches record family '{w.kind}' "
                        f"after '{max_kind}' — cross-family mutators must "
                        "touch stripe families in the canonical order "
                        f"{' < '.join(STRIPE_FAMILIES)} (docs/effect-graph.md) "
                        "so the striped checkpoint can lock families "
                        "deadlock-free",
                    )
                )
            if idx > max_idx:
                max_idx, max_kind = idx, w.kind
        return

    # -- roots --------------------------------------------------------------

    def _roots(self) -> list:
        called: set = set()
        for fn in self.graph.functions.values():
            cal = _Callable(fn.node, fn, _short(fn.qualname))
            for call in _ordered_calls(fn.node):
                for target, _ in self._call_targets(call, cal, {}):
                    if isinstance(
                        target.node, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and target.ctx is not fn:
                        called.add(id(target.node))
        return [
            fn
            for fn in self.graph.functions.values()
            if id(fn.node) not in called
        ]

    # -- rule finalization --------------------------------------------------

    def _finalize_recovery(self) -> None:
        for kind in sorted(self.kind_writes):
            sites = sorted(set(self.kind_writes[kind]))
            if kind in self.handlers:
                continue
            live = [s for s in sites if not s[2]]  # not nonrecoverable
            if not live:
                continue
            path, line, _ = live[0]
            others = len(live) - 1
            suffix = f" (and {others} other site(s))" if others else ""
            self.findings.append(
                Finding(
                    path, line, 0, "WAL-RECOVERY-EXHAUSTIVE",
                    f"record kind '{kind}' is committed here{suffix} but no "
                    "recovery sweep declares '# tpudra-wal: "
                    f"recovers={kind} <why>' — a crash after this commit "
                    "leaves a record nothing converges",
                )
            )
        for kind in sorted(self.handlers):
            if kind in self.kind_writes:
                continue
            for path, line, name in sorted(self.handlers[kind]):
                self.findings.append(
                    Finding(
                        path, line, 0, "WAL-RECOVERY-EXHAUSTIVE",
                        f"dead recovery handler: {name} declares "
                        f"recovers={kind} but no commit site ever writes a "
                        f"'{kind}' record — drop the annotation or wire the "
                        "writer",
                    )
                )

    def _finalize_fence(self) -> None:
        for site in self.commit_sites.values():
            if site.in_controller and not site.fenced:
                self.findings.append(
                    Finding(
                        site.path, site.line, 0, "FENCE-DOMINATES-COMMIT",
                        f"checkpoint commit in controller code "
                        f"({_short(site.qualname)}) is not dominated by a "
                        "gangmeta/term fence check — route it through the "
                        "fenced funnel (GangReservationManager._mutate) so "
                        "a stale leader's write is refused inside the WAL "
                        "transaction",
                    )
                )

    def run(self) -> EffectGraphResult:
        self._collect_handlers_and_stripe()
        for fn in sorted(self._roots(), key=lambda f: f.qualname):
            self._walk(
                _Callable(fn.node, fn, _short(fn.qualname)),
                {}, set(), (), _short(fn.qualname),
            )
        # Nested defs nobody invoked (registered callbacks, thread targets):
        # walk each as its own journal-less root so their effects are not
        # silently unmodeled.
        for fn in sorted(self.graph.functions.values(), key=lambda f: f.qualname):
            for name, node in sorted(_nested_defs(fn.node).items()):
                if id(node) in self._walked_nested:
                    continue
                self._walk(
                    _Callable(node, fn, f"{_short(fn.qualname)}.{name}"),
                    {}, set(), (), f"{_short(fn.qualname)}.{name}",
                )
        self.findings.extend(self._violations.values())
        self._finalize_recovery()
        self._finalize_fence()
        kinds = {}
        for kind in STRIPE_FAMILIES:
            info = KindInfo(kind)
            info.written_at = sorted(
                {(p, line) for p, line, _ in self.kind_writes.get(kind, [])}
            )
            info.handlers = sorted(self.handlers.get(kind, []))
            kinds[kind] = info
        return EffectGraphResult(
            kinds=kinds,
            effects=sorted(
                self.effect_sites.values(),
                key=lambda e: (e.spec.effect_id, e.path, e.line),
            ),
            commits=sorted(
                self.commit_sites.values(), key=lambda c: (c.path, c.line)
            ),
            findings=sorted(self.findings),
        )


def analyze_effects(
    modules: list, graph: Optional[CallGraph] = None
) -> EffectGraphResult:
    return EffectAnalysis(modules, graph).run()

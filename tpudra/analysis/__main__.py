"""CLI for tpudra-lint + tpudra-lockgraph: ``python -m tpudra.analysis``.

One shared parse pass feeds both the per-module lint rules and the
whole-program lock analysis.  Extra modes:

- ``--lockgraph``: only the lock rules (the ``make lockgraph`` lane);
- ``--witness LOG``: merge a runtime witness log (tpudra/lockwitness.py)
  into the static graph — witnessed cycles and model gaps fail;
- ``--emit-dot [PATH]``: regenerate docs/lock-order.md from the model.

Exit status: 0 clean, 1 findings (or a failed witness merge), 2 usage/
internal error — the contract ``hack/lint.sh`` and ``make lint``/`
``make lockgraph`` build on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpudra.analysis.engine import DEFAULT_ROOTS, lint_modules, parse_paths


def _repo_root() -> str:
    """The directory holding the ``tpudra`` package — so the default roots
    resolve no matter where the command is invoked from."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpudra.analysis",
        description="tpudra-lint: driver-specific AST invariant checks "
        "(docs/static-analysis.md)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=f"files/directories to lint (default: {', '.join(DEFAULT_ROOTS)} "
        "under the repo root)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print rule IDs and exit"
    )
    parser.add_argument(
        "--lockgraph",
        action="store_true",
        help="run only the whole-program lock rules (LOCK-CYCLE, "
        "BLOCK-UNDER-LOCK-IP, FLOCK-INVERSION)",
    )
    parser.add_argument(
        "--witness",
        metavar="LOG",
        help="merge a TPUDRA_LOCK_WITNESS jsonl log into the static lock "
        "graph: witnessed cycles / model gaps fail, unwitnessed static "
        "edges are reported as coverage",
    )
    parser.add_argument(
        "--emit-dot",
        nargs="?",
        const="docs/lock-order.md",
        metavar="PATH",
        help="regenerate the lock-order document (default docs/lock-order.md) "
        "from the static graph and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        from tpudra.analysis.rules import all_rules

        for rule in all_rules():
            print(f"{rule.rule_id}: {rule.description}")
        print(
            "SUPPRESS-REASON: every '# tpudra-lint: disable=...' states a "
            "reason (engine-level check)"
        )
        return 0

    if args.witness is not None or args.emit_dot is not None:
        # Graph modes operate on the tpudra package's static model; the
        # lint-mode arguments have no meaning there — reject rather than
        # silently ignore them.
        rejected = [
            name
            for name, present in (
                ("--json", args.json),
                ("--lockgraph", args.lockgraph),
                ("paths", bool(args.paths)),
            )
            if present
        ]
        if rejected:
            print(
                "tpudra-lockgraph: --witness/--emit-dot cannot be combined "
                f"with {', '.join(rejected)}",
                file=sys.stderr,
            )
            return 2
        return _graph_mode(args)

    paths = args.paths
    if not paths:
        root = _repo_root()
        paths = [
            p for p in (os.path.join(root, r) for r in DEFAULT_ROOTS)
            if os.path.exists(p)
        ]
        if not paths:
            print("tpudra-lint: no default roots found; pass paths", file=sys.stderr)
            return 2
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"tpudra-lint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    rules = None
    if args.lockgraph:
        from tpudra.analysis.rules import lockgraph_rules

        rules = lockgraph_rules()
    modules, parse_findings = parse_paths(paths)
    findings = lint_modules(modules, parse_findings, rules=rules)
    if args.json:
        print(
            json.dumps(
                [
                    {
                        "path": f.path,
                        "line": f.line,
                        "col": f.col,
                        "rule": f.rule_id,
                        "message": f.message,
                    }
                    for f in findings
                ],
                indent=2,
            )
        )
    else:
        name = "tpudra-lockgraph" if args.lockgraph else "tpudra-lint"
        for f in findings:
            print(f.render())
        n = len(findings)
        print(
            f"{name}: {n} finding{'s' if n != 1 else ''}"
            if n
            else f"{name}: clean"
        )
    return 1 if findings else 0


def _graph_mode(args) -> int:
    """--witness / --emit-dot: operate on the static lock graph of the
    tpudra package (the lockgraph's scope) rather than on lint findings."""
    from tpudra.analysis.witness import build_graph, emit_markdown, merge

    root = _repo_root()
    if args.witness is not None and not os.path.exists(args.witness):
        # Before the (multi-second) whole-program pass: a typo'd log path
        # is a usage error, not a reason to build and maybe rewrite docs.
        print(
            f"tpudra-lockgraph: no witness log at {args.witness}",
            file=sys.stderr,
        )
        return 2
    result = build_graph(os.path.join(root, "tpudra"))
    rc = 0
    if args.emit_dot is not None:
        out_path = args.emit_dot
        if not os.path.isabs(out_path):
            out_path = os.path.join(root, out_path)
        with open(out_path, "w", encoding="utf-8") as f:
            f.write(emit_markdown(result))
        print(
            f"tpudra-lockgraph: wrote {out_path} "
            f"({len(result.locks)} locks, {len(result.edges)} edges)"
        )
    if args.witness is not None:
        report = merge(result, args.witness)
        print(report.render())
        rc = 0 if report.ok else 1
    return rc


if __name__ == "__main__":
    sys.exit(main())

"""CLI for tpudra-lint + tpudra-lockgraph + tpudra-effectgraph +
tpudra-racegraph: ``python -m tpudra.analysis``.

One shared parse pass (parallel across files when CPUs allow) feeds the
per-module lint rules and all whole-program analyses.  Extra modes:

- ``--lockgraph``: only the lock rules (the ``make lockgraph`` lane);
- ``--effectgraph``: only the WAL rules (the ``make effectgraph`` lane);
- ``--racegraph``: only the race rules (the ``make racegraph`` lane);
- ``--witness LOG``: merge a runtime lock witness log
  (tpudra/lockwitness.py) into the static lock graph — witnessed cycles
  and model gaps fail;
- ``--wal-witness LOG``: merge a runtime WAL witness log
  (tpudra/walwitness.py) into the static effect graph — witnessed
  ordering violations and model gaps fail;
- ``--race-witness LOG``: merge a runtime race witness log
  (tpudra/racewitness.py) into the static race model — witnessed races
  and model gaps fail;
- ``--emit-dot [PATH]``: regenerate docs/lock-order.md from the model;
- ``--emit-effectgraph [PATH]``: regenerate docs/effect-graph.md;
- ``--emit-racegraph [PATH]``: regenerate docs/race-model.md.

``--json`` emits the stable machine schema (documented in
docs/static-analysis.md and asserted by tests/test_lint.py)::

    {"schema": "tpudra-analysis/v1",
     "findings": [{"rule", "path", "line", "col", "message"}, ...],
     "count": N}

Exit status: 0 clean, 1 findings (or a failed witness merge), 2 usage/
internal error — the contract ``hack/lint.sh`` and ``make lint``/`
``make lockgraph``/``make effectgraph`` build on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from tpudra.analysis.engine import DEFAULT_ROOTS, lint_modules, parse_paths


def _repo_root() -> str:
    """The directory holding the ``tpudra`` package — so the default roots
    resolve no matter where the command is invoked from."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpudra.analysis",
        description="tpudra-lint: driver-specific AST invariant checks "
        "(docs/static-analysis.md)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=f"files/directories to lint (default: {', '.join(DEFAULT_ROOTS)} "
        "under the repo root)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print rule IDs and exit"
    )
    parser.add_argument(
        "--lockgraph",
        action="store_true",
        help="run only the whole-program lock rules (LOCK-CYCLE, "
        "BLOCK-UNDER-LOCK-IP, FLOCK-INVERSION)",
    )
    parser.add_argument(
        "--effectgraph",
        action="store_true",
        help="run only the whole-program WAL rules (WAL-INTENT-BEFORE-"
        "EFFECT, WAL-RECOVERY-EXHAUSTIVE, FENCE-DOMINATES-COMMIT, "
        "STRIPE-ORDER)",
    )
    parser.add_argument(
        "--racegraph",
        action="store_true",
        help="run only the whole-program race rules (RACE, "
        "GUARD-CONSISTENCY, THREAD-CONFINED-ESCAPE)",
    )
    parser.add_argument(
        "--witness",
        metavar="LOG",
        help="merge a TPUDRA_LOCK_WITNESS jsonl log into the static lock "
        "graph: witnessed cycles / model gaps fail, unwitnessed static "
        "edges are reported as coverage",
    )
    parser.add_argument(
        "--wal-witness",
        metavar="LOG",
        help="merge a TPUDRA_WAL_WITNESS jsonl log into the static effect "
        "graph: witnessed intent-before-effect violations / model gaps "
        "fail, unwitnessed modeled effects are reported as coverage",
    )
    parser.add_argument(
        "--race-witness",
        metavar="LOG",
        help="merge a TPUDRA_RACE_WITNESS jsonl log into the static race "
        "model: witnessed unordered cross-thread writes / model gaps fail, "
        "unwitnessed modeled shared fields are reported as coverage",
    )
    parser.add_argument(
        "--emit-dot",
        nargs="?",
        const="docs/lock-order.md",
        metavar="PATH",
        help="regenerate the lock-order document (default docs/lock-order.md) "
        "from the static graph and exit",
    )
    parser.add_argument(
        "--emit-effectgraph",
        nargs="?",
        const="docs/effect-graph.md",
        metavar="PATH",
        help="regenerate the effect-graph document (default "
        "docs/effect-graph.md) from the static WAL model and exit",
    )
    parser.add_argument(
        "--emit-racegraph",
        nargs="?",
        const="docs/race-model.md",
        metavar="PATH",
        help="regenerate the race-model document (default "
        "docs/race-model.md) from the static race model and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        from tpudra.analysis.rules import all_rules

        for rule in all_rules():
            print(f"{rule.rule_id}: {rule.description}")
        print(
            "SUPPRESS-REASON: every '# tpudra-lint: disable=...' states a "
            "reason (engine-level check)"
        )
        print(
            "ANNOTATION-REASON: every '# tpudra-lock:'/'# tpudra-wal:'/"
            "'# tpudra-race:' annotation states a reason after its keywords "
            "(engine-level check)"
        )
        return 0

    graph_flags = (
        args.witness is not None
        or args.wal_witness is not None
        or args.race_witness is not None
        or args.emit_dot is not None
        or args.emit_effectgraph is not None
        or args.emit_racegraph is not None
    )
    if graph_flags:
        # Graph modes operate on the tpudra package's static model; the
        # lint-mode arguments have no meaning there — reject rather than
        # silently ignore them.
        rejected = [
            name
            for name, present in (
                ("--json", args.json),
                ("--lockgraph", args.lockgraph),
                ("--effectgraph", args.effectgraph),
                ("--racegraph", args.racegraph),
                ("paths", bool(args.paths)),
            )
            if present
        ]
        if rejected:
            print(
                "tpudra-lockgraph: graph modes (--witness/--wal-witness/"
                "--race-witness/--emit-dot/--emit-effectgraph/"
                "--emit-racegraph) cannot be combined with "
                f"{', '.join(rejected)}",
                file=sys.stderr,
            )
            return 2
        return _graph_mode(args)

    if sum((args.lockgraph, args.effectgraph, args.racegraph)) > 1:
        print(
            "tpudra-lint: --lockgraph, --effectgraph and --racegraph are "
            "separate lanes; run the full analyzer for all",
            file=sys.stderr,
        )
        return 2

    paths = args.paths
    if not paths:
        root = _repo_root()
        paths = [
            p for p in (os.path.join(root, r) for r in DEFAULT_ROOTS)
            if os.path.exists(p)
        ]
        if not paths:
            print("tpudra-lint: no default roots found; pass paths", file=sys.stderr)
            return 2
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"tpudra-lint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    rules = None
    if args.lockgraph:
        from tpudra.analysis.rules import lockgraph_rules

        rules = lockgraph_rules()
    elif args.effectgraph:
        from tpudra.analysis.rules import effectgraph_rules

        rules = effectgraph_rules()
    elif args.racegraph:
        from tpudra.analysis.rules import racegraph_rules

        rules = racegraph_rules()
    started = time.monotonic()
    modules, parse_findings = parse_paths(paths)
    findings = lint_modules(modules, parse_findings, rules=rules)
    elapsed = time.monotonic() - started
    if args.json:
        # The stable machine schema; see the module docstring.  Keys and
        # their meanings only ever grow — tests/test_lint.py pins them.
        print(
            json.dumps(
                {
                    "schema": "tpudra-analysis/v1",
                    "findings": [
                        {
                            "rule": f.rule_id,
                            "path": f.path,
                            "line": f.line,
                            "col": f.col,
                            "message": f.message,
                        }
                        for f in findings
                    ],
                    "count": len(findings),
                },
                indent=2,
            )
        )
    else:
        name = "tpudra-lint"
        if args.lockgraph:
            name = "tpudra-lockgraph"
        elif args.effectgraph:
            name = "tpudra-effectgraph"
        elif args.racegraph:
            name = "tpudra-racegraph"
        for f in findings:
            print(f.render())
        n = len(findings)
        verdict = (
            f"{n} finding{'s' if n != 1 else ''}" if n else "clean"
        )
        print(
            f"{name}: {verdict} "
            f"({len(modules)} modules in {elapsed:.2f}s)"
        )
    return 1 if findings else 0


def _graph_mode(args) -> int:
    """--witness / --wal-witness / --race-witness / --emit-dot /
    --emit-effectgraph / --emit-racegraph: operate on the static
    whole-program models of the tpudra package rather than on lint
    findings.  One shared parse pass and one shared CallGraph feed
    whichever of the models the flags require."""
    root = _repo_root()
    for flag, log in (
        ("witness", args.witness),
        ("wal-witness", args.wal_witness),
        ("race-witness", args.race_witness),
    ):
        if log is not None and not os.path.exists(log):
            # Before the (multi-second) whole-program pass: a typo'd log
            # path is a usage error, not a reason to build and maybe
            # rewrite docs.
            print(
                f"tpudra-lockgraph: no --{flag} log at {log}",
                file=sys.stderr,
            )
            return 2

    from tpudra.analysis.callgraph import CallGraph

    modules, _ = parse_paths([os.path.join(root, "tpudra")])
    graph = CallGraph(modules)
    rc = 0

    if args.emit_dot is not None or args.witness is not None:
        from tpudra.analysis import witness
        from tpudra.analysis.lockmodel import analyze_modules

        result = analyze_modules(modules, graph)
        if args.emit_dot is not None:
            out_path = args.emit_dot
            if not os.path.isabs(out_path):
                out_path = os.path.join(root, out_path)
            with open(out_path, "w", encoding="utf-8") as f:
                f.write(witness.emit_markdown(result))
            print(
                f"tpudra-lockgraph: wrote {out_path} "
                f"({len(result.locks)} locks, {len(result.edges)} edges)"
            )
        if args.witness is not None:
            report = witness.merge(result, args.witness)
            print(report.render())
            rc = rc or (0 if report.ok else 1)

    if args.emit_effectgraph is not None or args.wal_witness is not None:
        from tpudra.analysis import effectwitness
        from tpudra.analysis.effectmodel import analyze_effects

        eresult = analyze_effects(modules, graph)
        if args.emit_effectgraph is not None:
            out_path = args.emit_effectgraph
            if not os.path.isabs(out_path):
                out_path = os.path.join(root, out_path)
            with open(out_path, "w", encoding="utf-8") as f:
                f.write(effectwitness.emit_markdown(eresult))
            print(
                f"tpudra-effectgraph: wrote {out_path} "
                f"({len(eresult.kinds)} kinds, {len(eresult.effects)} effect "
                f"sites, {len(eresult.commits)} commit sites)"
            )
        if args.wal_witness is not None:
            report = effectwitness.merge(eresult, args.wal_witness)
            print(report.render())
            rc = rc or (0 if report.ok else 1)

    if args.emit_racegraph is not None or args.race_witness is not None:
        from tpudra.analysis import racemerge
        from tpudra.analysis.racemodel import analyze_races

        rresult = analyze_races(modules, graph)
        if args.emit_racegraph is not None:
            out_path = args.emit_racegraph
            if not os.path.isabs(out_path):
                out_path = os.path.join(root, out_path)
            with open(out_path, "w", encoding="utf-8") as f:
                f.write(racemerge.emit_markdown(rresult))
            shared = rresult.shared_fields()
            print(
                f"tpudra-racegraph: wrote {out_path} "
                f"({len(rresult.roles)} roles, {len(rresult.fields)} fields, "
                f"{len(shared)} shared)"
            )
        if args.race_witness is not None:
            report = racemerge.merge(rresult, args.race_witness)
            print(report.render())
            rc = rc or (0 if report.ok else 1)
    return rc


if __name__ == "__main__":
    sys.exit(main())

"""CLI for tpudra-lint: ``python -m tpudra.analysis [paths...]``.

Exit status: 0 clean, 1 findings, 2 usage/internal error — the contract
``hack/lint.sh`` and the ``make lint`` gate build on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpudra.analysis.engine import DEFAULT_ROOTS, lint_paths


def _repo_root() -> str:
    """The directory holding the ``tpudra`` package — so the default roots
    resolve no matter where the command is invoked from."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpudra.analysis",
        description="tpudra-lint: driver-specific AST invariant checks "
        "(docs/static-analysis.md)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help=f"files/directories to lint (default: {', '.join(DEFAULT_ROOTS)} "
        "under the repo root)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print rule IDs and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        from tpudra.analysis.rules import all_rules

        for rule in all_rules():
            print(f"{rule.rule_id}: {rule.description}")
        print(
            "SUPPRESS-REASON: every '# tpudra-lint: disable=...' states a "
            "reason (engine-level check)"
        )
        return 0

    paths = args.paths
    if not paths:
        root = _repo_root()
        paths = [
            p for p in (os.path.join(root, r) for r in DEFAULT_ROOTS)
            if os.path.exists(p)
        ]
        if not paths:
            print("tpudra-lint: no default roots found; pass paths", file=sys.stderr)
            return 2
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"tpudra-lint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    findings = lint_paths(paths)
    if args.json:
        print(
            json.dumps(
                [
                    {
                        "path": f.path,
                        "line": f.line,
                        "col": f.col,
                        "rule": f.rule_id,
                        "message": f.message,
                    }
                    for f in findings
                ],
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(
            f"tpudra-lint: {n} finding{'s' if n != 1 else ''}"
            if n
            else "tpudra-lint: clean"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

"""The tpudra-lint engine: file walking, parsing, suppressions, rule runs.

Rules are small classes (tpudra/analysis/rules/) instantiated fresh per
lint run so cross-file state (METRICS-HYGIENE's duplicate-registration
check) never leaks between runs.  The engine owns everything that is not
rule-specific: which files to scan, the suppression syntax, ordering.
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional

#: What `python -m tpudra.analysis` scans with no path arguments, relative
#: to the repo root (the directory holding the ``tpudra`` package).
DEFAULT_ROOTS = ("tpudra", "tools", "bench.py")

#: Generated or vendored code the rules must not police.
_SKIP_DIR_NAMES = {"__pycache__", "drapb", "build", "vendor"}

_SUPPRESS_RE = re.compile(
    r"#\s*tpudra-lint:\s*disable=(?P<rules>[A-Z0-9-]+(?:,[A-Z0-9-]+)*)"
    r"(?:\s+(?P<reason>[^#\s][^#]*))?"
)


@dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    col: int
    rule_id: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}"


@dataclass
class ParsedModule:
    path: str
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.source.splitlines()


class Suppressions:
    """``# tpudra-lint: disable=RULE[,RULE] reason`` comments of one file.

    A suppression applies to findings on its own line; a comment that is
    the only thing on its line additionally covers the next line, so long
    statements keep their suppression adjacent.  Comments are found with
    ``tokenize`` (not substring search) so the directive inside a string
    literal is inert.
    """

    def __init__(self, source: str):
        self._by_line: dict[int, set[str]] = {}
        self.unreasoned: list[tuple[int, str]] = []
        try:
            tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = set(m.group("rules").split(","))
                if not m.group("reason"):
                    self.unreasoned.append((tok.start[0], m.group("rules")))
                line = tok.start[0]
                self._by_line.setdefault(line, set()).update(rules)
                stripped = tok.line.strip()
                if stripped.startswith("#"):  # comment-only line: cover the next
                    self._by_line.setdefault(line + 1, set()).update(rules)
        except tokenize.TokenError:
            # Unterminated trailer after the last suppression; the file
            # itself already parsed (ast.parse runs first), so any comment
            # tokens yielded before the error are kept and the rest of the
            # file simply has no suppressions.
            pass

    def covers(self, line: int, rule_id: str) -> bool:
        return rule_id in self._by_line.get(line, ())


def _iter_python_files(root: str) -> Iterable[str]:
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIR_NAMES)
        for name in sorted(filenames):
            if name.endswith(".py") and not name.endswith("_pb2.py"):
                yield os.path.join(dirpath, name)


def _make_rules() -> list:
    from tpudra.analysis.rules import all_rules

    return all_rules()


def lint_source(
    source: str, path: str = "<string>", rules: Optional[list] = None
) -> list[Finding]:
    """Lint one in-memory module (the fixture-test entrypoint)."""
    active = rules if rules is not None else _make_rules()
    findings = _lint_one(ParsedModule(path=path, source=source, tree=ast.parse(source)), active)
    if rules is None:
        for rule in active:
            findings.extend(rule.finalize())
        findings.sort()
    return findings


def _lint_one(module: ParsedModule, rules: list) -> list[Finding]:
    suppressed = Suppressions(module.source)
    out: list[Finding] = []
    for rule in rules:
        for f in rule.check_module(module):
            if not suppressed.covers(f.line, f.rule_id):
                out.append(f)
    # A suppression is a design decision; without a reason the next reader
    # cannot tell a considered exception from a silenced mistake.
    for line, rules_str in suppressed.unreasoned:
        if not suppressed.covers(line, "SUPPRESS-REASON"):
            out.append(
                Finding(
                    module.path, line, 0, "SUPPRESS-REASON",
                    f"suppression of {rules_str} states no reason — say why "
                    "the rule is safe to ignore here",
                )
            )
    return out


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    """Lint files/directories; returns sorted findings.  Unparseable files
    surface as SYNTAX findings rather than crashing the run — a file the
    analyzer cannot read is a finding, not an excuse."""
    rules = _make_rules()
    findings: list[Finding] = []
    for root in paths:
        for filename in _iter_python_files(root):
            try:
                with open(filename, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=filename)
            except (OSError, SyntaxError, ValueError) as e:
                line = getattr(e, "lineno", 1) or 1
                findings.append(
                    Finding(filename, line, 0, "SYNTAX", f"cannot analyze: {e}")
                )
                continue
            findings.extend(
                _lint_one(ParsedModule(path=filename, source=source, tree=tree), rules)
            )
    for rule in rules:
        findings.extend(rule.finalize())
    findings.sort()
    return findings

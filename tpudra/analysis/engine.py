"""The tpudra-lint engine: file walking, parsing, suppressions, rule runs.

Rules are small classes (tpudra/analysis/rules/) instantiated fresh per
lint run so cross-file state (METRICS-HYGIENE's duplicate-registration
check) never leaks between runs.  The engine owns everything that is not
rule-specific: which files to scan, the suppression syntax, ordering.
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional

#: What `python -m tpudra.analysis` scans with no path arguments, relative
#: to the repo root (the directory holding the ``tpudra`` package).
DEFAULT_ROOTS = ("tpudra", "tools", "bench.py")

#: Generated or vendored code the rules must not police.
_SKIP_DIR_NAMES = {"__pycache__", "drapb", "build", "vendor"}

_SUPPRESS_RE = re.compile(
    r"#\s*tpudra-lint:\s*disable=(?P<rules>[A-Z0-9-]+(?:,[A-Z0-9-]+)*)"
    r"(?:\s+(?P<reason>[^#\s][^#]*))?"
)

#: Analyzer annotations (`# tpudra-lock:` / `# tpudra-wal:` /
#: `# tpudra-race:`) change what the whole-program models believe about the
#: code; like suppressions, each must carry a free-text why after its
#: keywords (ANNOTATION-REASON).
_ANNOTATION_COMMENT_RE = re.compile(
    r"#\s*(?P<prefix>tpudra-(?:lock|wal|race)):\s*(?P<body>.+)"
)
_ANNOTATION_KV_RE = re.compile(r"^(id|acquires|kind|recovers|guard|owner)=\S+$")
_ANNOTATION_FLAGS = {"family", "nonblocking", "nonrecoverable", "handoff"}

#: Retired rule ids whose suppressions keep working: a finding from a
#: successor rule is covered by a suppression naming the predecessor
#: (SHARED-STATE was absorbed into tpudra-racegraph).
_RULE_ALIASES = {
    "RACE": ("SHARED-STATE",),
    "GUARD-CONSISTENCY": ("SHARED-STATE",),
    "THREAD-CONFINED-ESCAPE": ("SHARED-STATE",),
}


@dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    col: int
    rule_id: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}"


@dataclass
class ParsedModule:
    path: str
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.source.splitlines()


class Suppressions:
    """``# tpudra-lint: disable=RULE[,RULE] reason`` comments of one file.

    A suppression applies to findings on its own line; a comment that is
    the only thing on its line additionally covers the next line, so long
    statements keep their suppression adjacent.  Comments are found with
    ``tokenize`` (not substring search) so the directive inside a string
    literal is inert.
    """

    def __init__(self, source: str):
        self._by_line: dict[int, set[str]] = {}
        self.unreasoned: list[tuple[int, str]] = []
        #: (line, prefix, keywords) of analyzer annotations with no reason.
        self.unreasoned_annotations: list[tuple[int, str, str]] = []
        try:
            tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                am = _ANNOTATION_COMMENT_RE.search(tok.string)
                if am:
                    words = am.group("body").split()
                    keywords = []
                    for word in words:
                        if _ANNOTATION_KV_RE.match(word) or word in _ANNOTATION_FLAGS:
                            keywords.append(word)
                        else:
                            break  # free-text reason starts
                    rest = words[len(keywords):]
                    # Like _SUPPRESS_RE's reason group, a nested comment
                    # ("... # EXPECT: ...") is not a reason.
                    if not rest or rest[0].startswith("#"):
                        self.unreasoned_annotations.append(
                            (tok.start[0], am.group("prefix"), " ".join(keywords))
                        )
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = set(m.group("rules").split(","))
                if not m.group("reason"):
                    self.unreasoned.append((tok.start[0], m.group("rules")))
                line = tok.start[0]
                self._by_line.setdefault(line, set()).update(rules)
                stripped = tok.line.strip()
                if stripped.startswith("#"):  # comment-only line: cover the next
                    self._by_line.setdefault(line + 1, set()).update(rules)
        except tokenize.TokenError:
            # Unterminated trailer after the last suppression; the file
            # itself already parsed (ast.parse runs first), so any comment
            # tokens yielded before the error are kept and the rest of the
            # file simply has no suppressions.
            pass

    def covers(self, line: int, rule_id: str) -> bool:
        return rule_id in self._by_line.get(line, ())


def _iter_python_files(root: str) -> Iterable[str]:
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIR_NAMES)
        for name in sorted(filenames):
            if name.endswith(".py") and not name.endswith("_pb2.py"):
                yield os.path.join(dirpath, name)


def _make_rules() -> list:
    from tpudra.analysis.rules import all_rules

    return all_rules()


def lint_source(
    source: str, path: str = "<string>", rules: Optional[list] = None
) -> list[Finding]:
    """Lint one in-memory module (the fixture-test entrypoint)."""
    active = rules if rules is not None else _make_rules()
    module = ParsedModule(path=path, source=source, tree=ast.parse(source))
    suppressed = Suppressions(module.source)
    findings = _lint_one(module, active, suppressed, engine_checks=rules is None)
    if rules is None:
        for rule in active:
            findings.extend(rule.finalize())
    findings = _apply_suppressions(findings, {module.path: suppressed})
    if rules is None:
        findings.sort()
    return findings


def _lint_one(
    module: ParsedModule,
    rules: list,
    suppressed: Suppressions,
    engine_checks: bool = True,
) -> list[Finding]:
    out: list[Finding] = []
    for rule in rules:
        out.extend(rule.check_module(module))
    if not engine_checks:
        # A custom rule subset (the --lockgraph lane) must report only its
        # own rules — SUPPRESS-REASON hygiene belongs to the full run.
        return out
    # A suppression is a design decision; without a reason the next reader
    # cannot tell a considered exception from a silenced mistake.
    for line, rules_str in suppressed.unreasoned:
        out.append(
            Finding(
                module.path, line, 0, "SUPPRESS-REASON",
                f"suppression of {rules_str} states no reason — say why "
                "the rule is safe to ignore here",
            )
        )
    # An annotation rewrites what the whole-program models believe about
    # this code; without a reason nobody can audit whether the claim still
    # holds after the next refactor.
    for line, prefix, keywords in suppressed.unreasoned_annotations:
        what = f"'# {prefix}: {keywords}'" if keywords else f"'# {prefix}:'"
        out.append(
            Finding(
                module.path, line, 0, "ANNOTATION-REASON",
                f"annotation {what} states no reason — follow the keywords "
                "with free text saying why the claim holds",
            )
        )
    return out


def _apply_suppressions(
    findings: list[Finding], suppressions: dict[str, Suppressions]
) -> list[Finding]:
    """Drop findings covered by their file's suppression comments.  Applied
    once, AFTER finalize(): cross-file rules (lockgraph, metrics
    registration) anchor their findings at real (path, line) sites, and a
    suppression there must work exactly like one on an intra-file finding."""
    out = []
    for f in findings:
        sup = suppressions.get(f.path)
        if sup is not None:
            ids = (f.rule_id,) + _RULE_ALIASES.get(f.rule_id, ())
            if any(sup.covers(f.line, rid) for rid in ids):
                continue
        out.append(f)
    return out


#: Bump when ParsedModule's pickled shape changes — stale entries must
#: miss, not deserialize into the wrong structure.
_CACHE_FORMAT = "tpudra-parse-cache/1"


def _cache_dir() -> Optional[str]:
    """``.tpudra-analysis-cache/`` at the repo root (the directory holding
    the ``tpudra`` package); ``TPUDRA_LINT_CACHE=0`` is the escape hatch."""
    if os.environ.get("TPUDRA_LINT_CACHE", "1") == "0":
        return None
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, ".tpudra-analysis-cache")


def _cache_key(filename: str, source: str) -> str:
    import hashlib
    import sys

    h = hashlib.sha256()
    h.update(_CACHE_FORMAT.encode())
    h.update(("%d.%d" % sys.version_info[:2]).encode())
    h.update(filename.encode())  # same bytes at another path ≠ same module
    h.update(b"\0")
    h.update(source.encode())
    return h.hexdigest()


def _cache_get(cache_dir: str, key: str):
    import pickle

    try:
        with open(os.path.join(cache_dir, key + ".pkl"), "rb") as f:
            obj = pickle.load(f)
    except Exception:  # tpudra-lint: disable=EXC-SWALLOW any unpickle failure (miss, torn write, stale format) means exactly one thing: reparse — nothing to log, nothing to narrow (pickle raises arbitrary types)
        return None
    return obj if isinstance(obj, ParsedModule) else None


def _cache_put(cache_dir: str, key: str, module: ParsedModule) -> None:
    import pickle

    try:
        os.makedirs(cache_dir, exist_ok=True)
        tmp = os.path.join(cache_dir, f".{key}.{os.getpid()}.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(module, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, os.path.join(cache_dir, key + ".pkl"))
    except Exception:  # tpudra-lint: disable=EXC-SWALLOW the cache is an optimization — a full disk or unwritable dir must not fail lint, and there is no logger this deep in the parse worker
        pass


def _parse_one(filename: str):
    """Parse worker (top level so multiprocessing can pickle it): the
    ParsedModule, or the SYNTAX Finding when the file cannot be read.

    Results are memoized under ``.tpudra-analysis-cache/`` keyed by the
    content hash (plus path, format version, and interpreter version), so
    a warm lint run skips ``ast.parse`` for unchanged files; any edit
    changes the hash and misses.  Parse FAILURES are never cached — the
    error message must track the live file."""
    try:
        with open(filename, encoding="utf-8") as f:
            source = f.read()
    except (OSError, ValueError) as e:
        return Finding(filename, 1, 0, "SYNTAX", f"cannot analyze: {e}")
    cache_dir = _cache_dir()
    key = _cache_key(filename, source) if cache_dir else ""
    if cache_dir:
        cached = _cache_get(cache_dir, key)
        if cached is not None:
            return cached
    try:
        tree = ast.parse(source, filename=filename)
    except (SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", 1) or 1
        return Finding(filename, line, 0, "SYNTAX", f"cannot analyze: {e}")
    module = ParsedModule(path=filename, source=source, tree=tree)
    if cache_dir:
        _cache_put(cache_dir, key, module)
    return module


def _default_jobs(n_files: int) -> int:
    env = os.environ.get("TPUDRA_LINT_JOBS", "")
    if env:
        try:
            jobs = int(env)
        except ValueError:
            jobs = 1
    else:
        jobs = os.cpu_count() or 1
    return max(1, min(jobs, n_files))


def parse_paths(
    paths: Iterable[str], jobs: Optional[int] = None
) -> tuple[list[ParsedModule], list[Finding]]:
    """One ``ast.parse`` per file, shared by every analysis that runs over
    the tree (lint rules, the lockgraph, and the effectgraph all consume
    these modules — the parse pass is the expensive part of a cold run and
    must not be paid twice).  Unparseable files surface as SYNTAX findings.

    The per-file parses are independent, so with ``jobs >= 2`` (default:
    ``TPUDRA_LINT_JOBS`` or the CPU count) they fan out over a process
    pool; result order follows the sorted file walk either way, so output
    is deterministic.  Single-CPU boxes and tiny file sets stay serial —
    fork + pickle overhead would swamp the win."""
    filenames = [fn for root in paths for fn in _iter_python_files(root)]
    if jobs is None:
        jobs = _default_jobs(len(filenames))
    results = None
    if jobs >= 2 and len(filenames) >= 8:
        try:
            import multiprocessing

            with multiprocessing.Pool(jobs) as pool:
                results = pool.map(_parse_one, filenames)
        except (ImportError, OSError):
            results = None  # no usable pool here (sandbox): parse serially
    if results is None:
        results = [_parse_one(fn) for fn in filenames]
    modules = [r for r in results if isinstance(r, ParsedModule)]
    findings = [r for r in results if isinstance(r, Finding)]
    return modules, findings


def lint_modules(
    modules: list[ParsedModule],
    parse_findings: Optional[list[Finding]] = None,
    rules: Optional[list] = None,
) -> list[Finding]:
    """Run the rule set over already-parsed modules; returns sorted findings."""
    active = rules if rules is not None else _make_rules()
    findings: list[Finding] = list(parse_findings or [])
    suppressions: dict[str, Suppressions] = {}
    for module in modules:
        suppressions[module.path] = Suppressions(module.source)
        findings.extend(
            _lint_one(
                module, active, suppressions[module.path],
                engine_checks=rules is None,
            )
        )
    for rule in active:
        findings.extend(rule.finalize())
    findings = _apply_suppressions(findings, suppressions)
    findings.sort()
    return findings


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    """Lint files/directories; returns sorted findings.  Unparseable files
    surface as SYNTAX findings rather than crashing the run — a file the
    analyzer cannot read is a finding, not an excuse."""
    modules, parse_findings = parse_paths(paths)
    return lint_modules(modules, parse_findings)

"""Witness merge + race-model doc generation for tpudra-racegraph.

The static race model (racemodel.py) and the runtime race witness log
(tpudra/racewitness.py) validate each other:

- two WRITE samples of one field from different threads of one process
  with disjoint held locksets and NO vector-clock ordering is a
  **witnessed race** the suite actually exhibited — fail;
- a sample from a thread whose name classifies to a model role the
  static model says cannot reach that field — or of a field the model
  does not know at all — is a **model gap** (role derivation or call
  resolution missed a path) — fail, because RACE/GUARD-CONSISTENCY are
  only as good as the model;
- a modeled shared field never witnessed is a coverage statement,
  reported but non-failing (static analysis over-approximates by
  design).

Thread-name classification is deliberately conservative: a sample's
thread maps to the LONGEST role id that prefixes its runtime name
(``informer-resync-pods`` → ``informer-resync``, not ``informer``;
``MainThread`` → ``main``), and a name no role prefixes — pytest
workers, bare ``Thread-N`` spawns — maps to nothing and can neither gap
nor cover.  Races, by contrast, compare raw thread names: two unnamed
threads colliding unordered on a field is a real race whatever the
model calls them.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpudra import racewitness
from tpudra.analysis.engine import parse_paths
from tpudra.analysis.lockmodel import _rel
from tpudra.analysis.racemodel import MAIN_ROLE, RaceGraphResult, analyze_races


def build_graph(root: str) -> RaceGraphResult:
    """The static race model of the tree under ``root`` (normally the
    ``tpudra`` package directory) — one shared parse pass."""
    modules, _ = parse_paths([root])
    return analyze_races(modules)


def classify_thread(name: str, role_ids) -> str | None:
    """Runtime thread name → model role id, longest-prefix; None when no
    role claims the name (unknown threads are wildcards, not gaps)."""
    if name == racewitness.MAIN_THREAD_NAME:
        return MAIN_ROLE
    best = None
    for role_id in role_ids:
        if name == role_id or name.startswith(role_id):
            if best is None or len(role_id) > len(best):
                best = role_id
    return best


@dataclass
class MergeReport:
    sample_count: int
    thread_names: set
    violations: list = field(default_factory=list)  # (field, t1, t2, pid)
    model_gaps: list = field(default_factory=list)  # (field, role, thread)
    covered: set = field(default_factory=set)  # modeled shared ∩ witnessed
    uncovered: set = field(default_factory=set)  # modeled shared, unseen

    @property
    def ok(self) -> bool:
        return not self.violations and not self.model_gaps

    def coverage(self) -> float:
        total = len(self.covered) + len(self.uncovered)
        return (len(self.covered) / total) if total else 1.0

    def render(self) -> str:
        lines = [
            f"witnessed: {self.sample_count} access sample(s) from "
            f"{len(self.thread_names)} thread(s)",
        ]
        for fld, t1, t2, pid in self.violations:
            lines.append(
                f"WITNESSED VIOLATION: '{fld}' written by threads "
                f"'{t1}' and '{t2}' (pid {pid}) with disjoint locksets and "
                "no happens-before ordering — a data race the schedule "
                "actually exhibited"
            )
        for fld, role, thread in self.model_gaps:
            if role:
                lines.append(
                    f"MODEL GAP: thread '{thread}' (role '{role}') accessed "
                    f"'{fld}' but the static model does not reach that field "
                    "from that role — teach racemodel.py the spawn/call path "
                    "before trusting RACE verdicts"
                )
            else:
                lines.append(
                    f"MODEL GAP: runtime accessed '{fld}' but the static "
                    "model has no such field — instrumented name and model "
                    "display id have drifted"
                )
        lines.append(
            f"static shared-field coverage: {len(self.covered)}/"
            f"{len(self.covered) + len(self.uncovered)} "
            f"({self.coverage():.0%}) of modeled shared fields"
        )
        uncovered = sorted(self.uncovered)
        for fld in uncovered[:10]:
            lines.append(f"  never witnessed: {fld}")
        if len(uncovered) > 10:
            lines.append(
                f"  ... and {len(uncovered) - 10} more (static analysis "
                "over-approximates sharing; coverage is informational)"
            )
        lines.append("witness merge: " + ("OK" if self.ok else "FAILED"))
        return "\n".join(lines)


def merge(result: RaceGraphResult, log_path: str) -> MergeReport:
    samples, armed = racewitness.read_log(log_path)
    report = MergeReport(
        sample_count=len(samples),
        thread_names={s.thread for s in samples},
    )
    field_roles = {fid: info.roles() for fid, info in result.fields.items()}
    shared = set(result.shared_fields())
    role_ids = list(result.roles)

    # -- model gaps ---------------------------------------------------------
    seen_gap: set = set()
    for s in samples:
        role = classify_thread(s.thread, role_ids)
        roles = field_roles.get(s.field)
        if roles is None:
            key = (s.field, None)
            if key not in seen_gap:
                seen_gap.add(key)
                report.model_gaps.append((s.field, None, s.thread))
            continue
        if role is not None and role not in roles:
            key = (s.field, role)
            if key not in seen_gap:
                seen_gap.add(key)
                report.model_gaps.append((s.field, role, s.thread))

    # -- witnessed races ----------------------------------------------------
    by_field: dict = {}
    for s in samples:
        if s.write:
            by_field.setdefault((s.pid, s.field), []).append(s)
    seen_race: set = set()
    for (pid, fld), writes in sorted(by_field.items()):
        if not armed.get(pid, True):
            # This process ran without the lock witness: every lockset is
            # vacuously empty, and calling that a race would be noise.
            continue
        for i, a in enumerate(writes):
            for b in writes[i + 1:]:
                if a.thread == b.thread:
                    continue
                if a.locks & b.locks:
                    continue
                if a.ordered_before(b) or b.ordered_before(a):
                    continue
                key = (fld, *sorted((a.thread, b.thread)))
                if key in seen_race:
                    continue
                seen_race.add(key)
                t1, t2 = sorted((a.thread, b.thread))
                report.violations.append((fld, t1, t2, pid))

    witnessed_fields = {s.field for s in samples}
    report.covered = shared & witnessed_fields
    report.uncovered = shared - witnessed_fields
    report.violations.sort()
    report.model_gaps.sort(key=lambda g: (g[0], g[1] or ""))
    return report


# --------------------------------------------------------------- model doc


def _field_verdict(info) -> str:
    if info.owner:
        return f"owner=`{info.owner}`"
    writes = [a for a in info.sites if a.write and not a.init and not a.handoff]
    if not writes:
        return "init/handoff only"
    guards = frozenset.intersection(*[a.guards for a in writes])
    if guards:
        return "guarded: " + ", ".join(f"`{g}`" for g in sorted(guards))
    return "hb-ordered / annotated"


def emit_markdown(result: RaceGraphResult) -> str:
    """docs/race-model.md: thread roles with their spawn sites and
    entries, every shared field with its role set and verdict, and the
    witness workflow — regenerated by
    ``python -m tpudra.analysis --emit-racegraph`` (``make
    racegraph-docs``).  Deterministic output — a freshness test diffs it
    against the file."""
    out = [
        "# Thread-role race model",
        "",
        "**Generated** by `python -m tpudra.analysis --emit-racegraph"
        " docs/race-model.md`",
        "(`make racegraph-docs`) from the tpudra-racegraph static model —"
        " do not",
        "edit by hand.  Rules, lockset algorithm, HB edges, annotation"
        " grammar, and",
        "witness workflow: [static-analysis.md](static-analysis.md).",
        "",
        "Every field written from two or more thread roles must keep a",
        "non-empty intersection of held locks across its writes (RACE),",
        "under ONE consistent lock (GUARD-CONSISTENCY), unless a",
        "happens-before edge — init-before-start, spawn/join, queue or",
        "condition handoff — orders the writes, or a reasoned",
        "`# tpudra-race:` annotation claims the protocol.",
        "",
        "## Thread roles",
        "",
        "`main` is implicit: every function no modeled spawn reaches is",
        "public API assumed to run on the caller's thread.",
        "",
        "| role | kind | spawned at | entries |",
        "|---|---|---|---|",
    ]
    for role_id, role in sorted(result.roles.items()):
        entries = ", ".join(
            f"`{e.partition(':')[2] or e}`" for e in role.entries
        ) or "—"
        out.append(
            f"| `{role_id}` | {role.kind} | "
            f"{_rel(role.path)}:{role.line} | {entries} |"
        )
    out += [
        "",
        "## Shared fields",
        "",
        "Fields the model sees written or read from two or more roles,",
        "with the write-lockset verdict the RACE rule enforces.",
        "",
        "| field | roles | verdict |",
        "|---|---|---|",
    ]
    for fid, info in sorted(result.fields.items()):
        roles = info.roles()
        if len(roles) < 2:
            continue
        out.append(
            f"| `{fid}` | {', '.join(f'`{r}`' for r in sorted(roles))} | "
            f"{_field_verdict(info)} |"
        )
    out += [
        "",
        "## Witness workflow",
        "",
        "Run any suite with `TPUDRA_RACE_WITNESS=1` (the chaos soak and",
        "both crash sweeps arm it automatically, alongside the lock",
        "witness so held stacks are real), then merge:",
        "",
        "```console",
        "$ TPUDRA_RACE_WITNESS=1 TPUDRA_LOCK_WITNESS=1 \\",
        "    TPUDRA_RACE_WITNESS_LOG=/tmp/race.jsonl \\",
        "    python -m pytest tests/ -q",
        "$ python -m tpudra.analysis --race-witness /tmp/race.jsonl",
        "```",
        "",
        "Unordered cross-thread writes with disjoint locksets fail as",
        "witnessed races; accesses from a role the model cannot route to",
        "the field fail as model gaps; modeled-but-never-witnessed shared",
        "fields are the coverage report.",
        "",
    ]
    return "\n".join(out)

"""tpudra-lint: AST-based invariant checker for the driver codebase.

The analog of the reference driver's `go vet` + golangci-lint + race-detector
discipline: the invariants that make the pipelined claim-bind path safe —
the lock hierarchy, RMW purity, metrics hygiene (docs/bind-path.md) — live
here as machine-checked rules instead of prose only.  Pure stdlib (``ast``),
no third-party deps, so it runs in every environment the driver builds in.

Usage::

    python -m tpudra.analysis              # lint tpudra/, tools/, bench.py
    python -m tpudra.analysis path [...]   # lint specific files/dirs
    python -m tpudra.analysis --list-rules

Suppression: ``# tpudra-lint: disable=RULE-ID reason`` on the offending
line (or alone on the line just above it).  The reason is free text and
required by convention — a suppression is a design decision, and the next
reader needs to know which one.  Rules and rationale: docs/static-analysis.md.
"""

from tpudra.analysis.engine import (  # noqa: F401 — public API
    DEFAULT_ROOTS,
    Finding,
    lint_paths,
    lint_source,
)

"""Small AST helpers shared by the tpudra-lint rules.

Everything here is name-heuristic by design: the analyzer has no type
information, so rules classify objects by the naming conventions the
codebase already follows (``self._publish_lock``, ``Flock(...)``,
``*_stub``).  The conventions are part of the contract — a lock named
``self.helper`` evades the checker, and review should catch the name.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

#: Names that denote an in-process mutual-exclusion primitive.  ``_cond``
#: is included: a Condition wraps a lock and ``with cond:`` holds it.
_LOCKISH_SUFFIXES = ("_lock", "_cond", "_mutex")
_LOCKISH_EXACT = {"lock", "cond", "mutex"}


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of an expression: ``self._cp.mutate`` →
    ``self._cp.mutate``; unresolvable parts render as ``?``."""
    if isinstance(node, ast.Attribute):
        return f"{dotted_name(node.value)}.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return f"{dotted_name(node.func)}()"
    return "?"


def terminal_name(node: ast.AST) -> str:
    """The last path segment of an expression: the attribute name, the bare
    name, or the called object's terminal name for ``X(...)``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return terminal_name(node.func)
    return ""


def call_name(call: ast.Call) -> str:
    """Terminal name of the called object (``self._lib.create_partition(...)``
    → ``create_partition``)."""
    return terminal_name(call.func)


def is_lockish_name(name: str) -> bool:
    low = name.lower()
    return low in _LOCKISH_EXACT or low.endswith(_LOCKISH_SUFFIXES)


def is_flockish(expr: ast.AST) -> bool:
    """True when the expression denotes a cross-process flock rather than an
    in-process lock: a ``Flock(...)`` construction (possibly called again,
    ``Flock(p)(timeout=...)``), or any name with ``flock`` in it."""
    names = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            names.add(node.id.lower())
        elif isinstance(node, ast.Attribute):
            names.add(node.attr.lower())
    return any("flock" in n for n in names)


def withitem_lock_kind(item: ast.withitem) -> Optional[tuple[str, str]]:
    """Classify one ``with`` item: returns ``(kind, name)`` with kind
    ``"flock"`` or ``"inproc"``, or None when the item is not lock-like.

    Handles the codebase's forms: ``with self._publish_lock:``,
    ``with lock(timeout=...):`` (a Flock object being called),
    ``with Flock(path)(timeout=...):``, ``with self._cond:``.
    """
    expr = item.context_expr
    if is_flockish(expr):
        return ("flock", terminal_name(expr))
    name = terminal_name(expr)
    if is_lockish_name(name):
        return ("inproc", name)
    return None


def iter_calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def walk_body_shallow(stmts: list[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class
    definitions — their bodies run later, not under the enclosing block."""
    stack: list[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
            ):
                continue
            stack.append(child)


def collect_functions(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    """Every function/method in the module by bare name (last definition
    wins).  Used for the depth-limited call expansion of RMW-PURITY — a
    name collision between classes errs toward scanning more, which can
    only over-report, never under-report."""
    out: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def self_attr_target(node: ast.AST) -> Optional[str]:
    """``self.X`` when the node is an attribute on the name ``self``."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None

"""Module-qualified, class-method-aware call graph over parsed modules.

The resolution layer under tpudra-lockgraph (lockmodel.py): given the one
shared parse pass (engine.parse_paths), build a whole-program view of

- which functions/methods exist (``mod:Class.method`` / ``mod:function``),
- what each module imports (so ``metrics.observe_phase`` resolves to
  ``tpudra.metrics.observe_phase``),
- what type each ``self.attr`` holds (from ``self.x = ClassName(...)``
  constructions, ``self.x = param`` with an annotated parameter, and
  ``self.x: T = ...`` annotations),
- and which definition a call expression lands on.

Resolution is deliberately conservative: a call that cannot be resolved
through imports, ``self``, attribute types, or local constructor inference
falls back to a *unique-name* match — linked only when exactly one class
in the corpus defines a method of that name.  Common names (``start``,
``get``, ``wait``) therefore resolve to nothing rather than to everything,
which errs toward missing edges instead of inventing lock-order cycles
that do not exist.  The runtime witness (tpudra/lockwitness.py) is the
cross-check for the missing-edge direction: an edge the model lacks but
the test suite exhibits fails the witness merge as a model gap.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Optional

from tpudra.analysis.engine import ParsedModule


def module_name(path: str) -> str:
    """Dotted module name of a file path: anything under a ``tpudra``
    directory gets its real package path (``tpudra.plugin.driver``);
    everything else (bench.py, tools, fixtures) its bare stem."""
    parts = os.path.normpath(path).split(os.sep)
    stem = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    if "tpudra" in parts[:-1]:
        idx = parts.index("tpudra")
        pkg = parts[idx:-1]
        if stem == "__init__":
            return ".".join(pkg)
        return ".".join(pkg + [stem])
    return stem


def short_module(mod: str) -> str:
    """The human prefix used in derived lock IDs: ``tpudra.kube.informer``
    → ``kube.informer`` (lock IDs should read at a glance, and every lock
    in this repo lives under tpudra)."""
    return mod[len("tpudra."):] if mod.startswith("tpudra.") else mod


@dataclass
class FunctionInfo:
    qualname: str  # "tpudra.plugin.driver:Driver.prepare_resource_claims"
    name: str
    module: str  # dotted module name
    path: str  # file path (findings anchor here)
    node: ast.FunctionDef
    class_name: str = ""  # "" for module-level functions
    decorators: tuple[str, ...] = ()

    @property
    def is_contextmanager(self) -> bool:
        return any(d.endswith("contextmanager") for d in self.decorators)


@dataclass
class ClassInfo:
    qualname: str  # "tpudra.plugin.driver:Driver"
    name: str
    module: str
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    #: self.attr → class qualname (best effort)
    attr_types: dict[str, str] = field(default_factory=dict)
    bases: tuple[str, ...] = ()  # unresolved base-name strings


def _decorator_names(node: ast.FunctionDef) -> tuple[str, ...]:
    out = []
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        parts: list[str] = []
        while isinstance(target, ast.Attribute):
            parts.append(target.attr)
            target = target.value
        if isinstance(target, ast.Name):
            parts.append(target.id)
        out.append(".".join(reversed(parts)))
    return tuple(out)


class CallGraph:
    def __init__(self, modules: list[ParsedModule]):
        self.modules = modules
        #: qualname → FunctionInfo
        self.functions: dict[str, FunctionInfo] = {}
        #: class qualname → ClassInfo
        self.classes: dict[str, ClassInfo] = {}
        #: bare class name → [class qualnames]  (import-free lookup)
        self._class_by_name: dict[str, list[str]] = {}
        #: method name → [FunctionInfo] across every class (unique-name fallback)
        self._method_by_name: dict[str, list[FunctionInfo]] = {}
        #: module → {alias → dotted target} for both module and symbol imports
        self._imports: dict[str, dict[str, str]] = {}
        #: dotted module → {name → FunctionInfo} module-level functions
        self._module_functions: dict[str, dict[str, FunctionInfo]] = {}
        for m in modules:
            self._index_module(m)
        # Attribute types need the class table complete, so second pass.
        for info in list(self.classes.values()):
            self._infer_attr_types(info)

    # -- indexing -----------------------------------------------------------

    def _index_module(self, module: ParsedModule) -> None:
        mod = module_name(module.path)
        imports: dict[str, str] = {}
        self._imports[mod] = imports
        self._module_functions.setdefault(mod, {})
        for node in module.tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imports[alias.asname or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for alias in node.names:
                    imports[alias.asname or alias.name] = f"{node.module}.{alias.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(module, mod, node, class_name="")
            elif isinstance(node, ast.ClassDef):
                self._add_class(module, mod, node)

    def _add_function(
        self, module: ParsedModule, mod: str, node, class_name: str
    ) -> FunctionInfo:
        qual = (
            f"{mod}:{class_name}.{node.name}" if class_name else f"{mod}:{node.name}"
        )
        info = FunctionInfo(
            qualname=qual,
            name=node.name,
            module=mod,
            path=module.path,
            node=node,
            class_name=class_name,
            decorators=_decorator_names(node),
        )
        self.functions[qual] = info
        if class_name:
            self._method_by_name.setdefault(node.name, []).append(info)
        else:
            self._module_functions[mod][node.name] = info
        return info

    def _add_class(self, module: ParsedModule, mod: str, node: ast.ClassDef) -> None:
        qual = f"{mod}:{node.name}"
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        info = ClassInfo(qualname=qual, name=node.name, module=mod, bases=tuple(bases))
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[child.name] = self._add_function(
                    module, mod, child, class_name=node.name
                )
        self.classes[qual] = info
        self._class_by_name.setdefault(node.name, []).append(qual)

    # -- type/derivation helpers --------------------------------------------

    def resolve_class_name(self, name: str, mod: str) -> Optional[str]:
        """A bare class name, as visible from module ``mod``, to its class
        qualname: local definition first, then imports, then a unique
        global match."""
        if f"{mod}:{name}" in self.classes:
            return f"{mod}:{name}"
        target = self._imports.get(mod, {}).get(name)
        if target:
            tmod, _, tname = target.rpartition(".")
            if f"{tmod}:{tname}" in self.classes:
                return f"{tmod}:{tname}"
        quals = self._class_by_name.get(name, [])
        if len(quals) == 1:
            return quals[0]
        return None

    def _annotation_class(self, annotation, mod: str) -> Optional[str]:
        """``param: ClassName`` / ``param: Optional[ClassName]`` → qualname."""
        if annotation is None:
            return None
        node = annotation
        if isinstance(node, ast.Subscript):  # Optional[X] / list[X] → X
            node = node.slice
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            # String annotation: take the last dotted segment.
            return self.resolve_class_name(node.value.split(".")[-1], mod)
        if isinstance(node, ast.Attribute):
            return self.resolve_class_name(node.attr, mod)
        if isinstance(node, ast.Name):
            return self.resolve_class_name(node.id, mod)
        return None

    def _constructed_class(self, value, mod: str) -> Optional[str]:
        """First class construction inside an assigned value expression:
        ``DeviceState(...)`` → its qualname; handles ``x or Fallback(...)``."""
        for node in ast.walk(value):
            if isinstance(node, ast.Call):
                name = ""
                if isinstance(node.func, ast.Name):
                    name = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    name = node.func.attr
                if name and name[0].isupper():
                    qual = self.resolve_class_name(name, mod)
                    if qual is not None:
                        return qual
        return None

    def _infer_attr_types(self, info: ClassInfo) -> None:
        for method in info.methods.values():
            params: dict[str, Optional[str]] = {}
            args = method.node.args
            for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
                params[a.arg] = self._annotation_class(a.annotation, info.module)
            for node in ast.walk(method.node):
                target = None
                value = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value = node.target, node.value
                if (
                    not isinstance(target, ast.Attribute)
                    or not isinstance(target.value, ast.Name)
                    or target.value.id != "self"
                    or target.attr in info.attr_types
                ):
                    continue
                if isinstance(node, ast.AnnAssign):
                    qual = self._annotation_class(node.annotation, info.module)
                    if qual:
                        info.attr_types[target.attr] = qual
                        continue
                if value is None:
                    continue
                if isinstance(value, ast.Name) and value.id in params:
                    if params[value.id]:
                        info.attr_types[target.attr] = params[value.id]  # type: ignore[assignment]
                    continue
                qual = self._constructed_class(value, info.module)
                if qual:
                    info.attr_types[target.attr] = qual

    # -- call resolution ----------------------------------------------------

    def class_of(self, fn: FunctionInfo) -> Optional[ClassInfo]:
        if not fn.class_name:
            return None
        return self.classes.get(f"{fn.module}:{fn.class_name}")

    def method_on(self, class_qual: str, name: str) -> Optional[FunctionInfo]:
        """Method lookup with one level of (corpus-resolvable) base-class
        fallback — enough for the repo's shallow hierarchies."""
        info = self.classes.get(class_qual)
        if info is None:
            return None
        if name in info.methods:
            return info.methods[name]
        for base in info.bases:
            base_qual = self.resolve_class_name(base, info.module)
            if base_qual and base_qual != class_qual:
                found = self.classes.get(base_qual, ClassInfo("", "", "")).methods.get(name)
                if found:
                    return found
        return None

    #: Names never resolved by the unique-name fallback: they collide with
    #: file/socket/dict/thread object protocols, so "exactly one class in
    #: the corpus defines it" proves nothing about an untyped receiver
    #: (``f.read()`` on a local file handle must not resolve to
    #: ``CheckpointManager.read``).  Typed receivers (self.attr, params,
    #: locals) still resolve these precisely.
    _FALLBACK_BLOCKLIST = frozenset(
        {
            "read", "write", "close", "open", "flush", "get", "set", "pop",
            "put", "update", "add", "remove", "discard", "clear", "append",
            "copy", "send", "recv", "acquire", "release", "wait", "notify",
            "start", "stop", "run", "join", "items", "keys", "values",
            "strip", "split", "encode", "decode", "submit", "result",
            "cancel", "done", "poll", "terminate", "kill",
        }
    )

    def unique_method(self, name: str) -> Optional[FunctionInfo]:
        if name in self._FALLBACK_BLOCKLIST:
            return None
        owners = self._method_by_name.get(name, [])
        if len(owners) == 1:
            return owners[0]
        return None

    def module_function(self, mod: str, name: str) -> Optional[FunctionInfo]:
        fn = self._module_functions.get(mod, {}).get(name)
        if fn is not None:
            return fn
        target = self._imports.get(mod, {}).get(name)
        if target:
            tmod, _, tname = target.rpartition(".")
            return self._module_functions.get(tmod, {}).get(tname)
        return None

    def resolve_call(
        self,
        call: ast.Call,
        ctx: FunctionInfo,
        local_types: Optional[dict[str, str]] = None,
    ) -> Optional[FunctionInfo]:
        """The definition a call lands on, or None.  ``local_types`` maps
        local variable names to class qualnames (callgraph consumers feed
        constructor/return inference in)."""
        func = call.func
        if isinstance(func, ast.Name):
            qual = self.resolve_class_name(func.id, ctx.module)
            if qual is not None:  # ClassName(...) → its __init__
                return self.method_on(qual, "__init__")
            return self.module_function(ctx.module, func.id)
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = func.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and ctx.class_name:
                found = self.method_on(f"{ctx.module}:{ctx.class_name}", attr)
                if found:
                    return found
            elif local_types and recv.id in local_types:
                return self.method_on(local_types[recv.id], attr)
            else:
                target = self._imports.get(ctx.module, {}).get(recv.id)
                if target:  # imported module: mod_alias.func(...)
                    fn = self._module_functions.get(target, {}).get(attr)
                    if fn is not None:
                        return fn
                    # from-imported class used as namespace: Cls.method
                    tmod, _, tname = target.rpartition(".")
                    if f"{tmod}:{tname}" in self.classes:
                        return self.method_on(f"{tmod}:{tname}", attr)
                cls_qual = self.resolve_class_name(recv.id, ctx.module)
                if cls_qual is not None:
                    return self.method_on(cls_qual, attr)
            return self.unique_method(attr)
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and ctx.class_name
        ):
            owner = self.classes.get(f"{ctx.module}:{ctx.class_name}")
            if owner is not None:
                attr_cls = owner.attr_types.get(recv.attr)
                if attr_cls is not None:
                    found = self.method_on(attr_cls, attr)
                    if found:
                        return found
        return self.unique_method(attr)

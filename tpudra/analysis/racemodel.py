"""tpudra-racegraph static model: thread roles, Eraser-style locksets,
happens-before refinement.

Three layers, riding the same parse pass and CallGraph as the lockgraph
and the effectgraph:

1. a **thread model** — every ``threading.Thread(target=...)`` and every
   ``pool.submit(fn, ...)`` is a *spawn site* defining a logical thread
   role (the publisher loop, the informer watch thread, the claim-effects
   pool, workqueue workers, ...).  Each role's *reachable set* is the
   call-graph closure of its entry; functions nobody in the corpus calls
   are **main-role roots** (the public-API assumption: tests and gRPC
   invoke them from the caller's thread).  Informer handler callbacks and
   ``Driver._run_effects`` effect callables — dispatch the call graph
   cannot resolve — are folded in explicitly, exactly as lockmodel does.

2. **lockset inference per shared attribute** — every ``self.attr``
   write/mutation site carries the set of lock IDs *definitely held*
   there: the lexical ``with`` nesting (resolved through
   ``LockModel.resolve_lock``, including ``@contextmanager`` wrappers)
   plus the interprocedural *entry-held* fixpoint
   ``entry(f) = ∩ over call sites (entry(caller) ∪ held-at-site)``.
   A field written from ≥ 2 distinct roles must keep a non-empty
   intersection of held guards across all conflicting writes.  The
   conflict criterion is **write/write** (reads stay out: single-writer
   fields are safe under the GIL's per-bytecode atomicity, and the
   runtime witness covers the rest); intra-role concurrency (N threads
   sharing one role id) is likewise the witness's side of the contract.

3. **happens-before refinement** — conflicts are dropped when ordered:
   ``__init__`` writes (init-before-start publication), writes lexically
   before the role's spawn site in the spawning function, writes after a
   ``join()`` that follows the spawn, and channel handoff pairs
   (``Queue.put``/``get``, ``Event.set``/``wait``,
   ``Condition.notify``/``wait``) where the writer sends after writing
   and the other side receives before writing.

Rules:

- RACE — conflicting cross-role writes, empty guard intersection, some
  write wholly unguarded, no happens-before edge;
- GUARD-CONSISTENCY — every conflicting write holds *a* lock, but not
  the *same* lock (the classic split-guard refactor bug);
- THREAD-CONFINED-ESCAPE — a field declared ``# tpudra-race: owner=ROLE``
  is accessed (read or write) from a function another role reaches.

Annotations (``# tpudra-race:``, reason mandatory — ANNOTATION-REASON):
``guard=LOCKID`` adds a guard the resolver cannot see at the access on
its line; ``owner=ROLE`` declares thread confinement; ``handoff`` exempts
an access whose ordering is a protocol the model has no edge for.
"""

from __future__ import annotations

import ast
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional

from tpudra.analysis import astutil
from tpudra.analysis.callgraph import CallGraph, FunctionInfo
from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.lockmodel import LockModel, _rel

MAIN_ROLE = "main"

#: Mutating container/set/dict method names: a call on a ``self.attr``
#: receiver with one of these IS a write to the attribute's object — but
#: only once the field has *container evidence* (it is assigned a
#: dict/list/set/deque literal or constructor somewhere in the corpus).
#: Without that gate, every domain method named ``update`` or ``remove``
#: (kube clients, managers) would read as a container write.
_MUTATORS = frozenset(
    {
        "update", "add", "append", "appendleft", "extend", "insert",
        "remove", "discard", "clear", "pop", "popitem", "setdefault",
    }
)

_CONTAINER_CTORS = frozenset(
    {
        "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
        "Counter",
    }
)


def _is_container_expr(expr: Optional[ast.expr]) -> bool:
    if isinstance(expr, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        from tpudra.analysis.astutil import terminal_name

        return terminal_name(expr.func) in _CONTAINER_CTORS
    return False

#: Channel-op classification for happens-before handoff edges.  ``put``
#: has no dict/list collision; ``get`` is ambiguous (dict.get) so it only
#: counts as a receive on a channel some function also ``put``s to;
#: zero-arg ``set`` is ``Event.set`` (dicts have no ``set``).
_SEND_METHODS = frozenset({"put", "put_nowait", "notify", "notify_all"})
_RECV_METHODS = frozenset({"wait", "wait_for", "get_nowait"})


# ------------------------------------------------------------- annotations

_RACE_ANNOTATION_RE = re.compile(r"#\s*tpudra-race:\s*(?P<body>.+)")
_RACE_KV_RE = re.compile(r"^(?P<key>guard|owner)=(?P<value>\S+)$")


@dataclass
class RaceDirective:
    line: int
    guards: tuple[str, ...] = ()
    owner: str = ""
    handoff: bool = False


class RaceAnnotations:
    """``# tpudra-race: guard=ID / owner=ROLE / handoff <why>`` comments
    of one file, found with ``tokenize`` so string literals are inert.  A
    comment alone on its line covers the next line (same convention as
    the lock/WAL annotations and suppressions)."""

    def __init__(self, source: str):
        self.by_line: dict[int, RaceDirective] = {}
        try:
            tokens = tokenize.generate_tokens(
                iter(source.splitlines(True)).__next__
            )
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _RACE_ANNOTATION_RE.search(tok.string)
                if not m:
                    continue
                directive = RaceDirective(line=tok.start[0])
                guards: list[str] = []
                for word in m.group("body").split():
                    kv = _RACE_KV_RE.match(word)
                    if kv and kv.group("key") == "guard":
                        guards.append(kv.group("value"))
                    elif kv:
                        directive.owner = kv.group("value")
                    elif word == "handoff":
                        directive.handoff = True
                    else:
                        break  # free-text reason starts
                directive.guards = tuple(guards)
                self.by_line[directive.line] = directive
                if tok.line.strip().startswith("#"):
                    self.by_line.setdefault(directive.line + 1, directive)
        except tokenize.TokenError:
            pass  # file parsed; trailing tokenize hiccups lose nothing

    def at(self, *lines: int) -> Optional[RaceDirective]:
        for line in lines:
            d = self.by_line.get(line)
            if d is not None:
                return d
        return None


# ------------------------------------------------------------ result model


@dataclass(frozen=True)
class ThreadRole:
    role_id: str
    kind: str  # "thread" | "pool"
    spawned_in: str  # qualname of the spawning function
    path: str
    line: int
    entries: tuple[str, ...]  # entry-function qualnames


@dataclass
class Access:
    field: tuple[str, str]  # (class_qual, attr)
    path: str
    line: int
    fn_qual: str
    write: bool
    init: bool
    guards: frozenset  # lock IDs definitely held (lexical ∪ entry ∪ guard=)
    roles: frozenset  # role ids whose reachable set contains fn_qual
    handoff: bool = False
    owner: str = ""  # owner=ROLE declared on this site's line
    #: write inferred from a _MUTATORS method call — only counts once the
    #: field has container evidence, else it demotes to a read
    mutate: bool = False


@dataclass
class FieldInfo:
    field: tuple[str, str]
    display: str  # "Class.attr" — the runtime witness's field id
    sites: list[Access] = field(default_factory=list)
    owner: str = ""

    def roles(self) -> set:
        out: set = set()
        for s in self.sites:
            out |= s.roles
        return out


@dataclass
class RaceGraphResult:
    roles: dict[str, ThreadRole]
    fields: dict[str, FieldInfo]  # display id → info
    findings: list[Finding]

    def shared_fields(self) -> dict[str, set]:
        """display id → role set, for fields reachable from ≥ 2 roles —
        the witness merge's model-gap and coverage universe."""
        return {
            fid: info.roles()
            for fid, info in self.fields.items()
            if len(info.roles()) >= 2
        }


# -------------------------------------------------------------- the analysis


@dataclass
class _PseudoFn:
    """A nested def handed to a spawn site: not in graph.functions, but it
    needs its own scan (its writes belong to its role, not the enclosing
    function's).  Mirrors the FunctionInfo surface the scanner touches."""

    qualname: str
    name: str
    module: str
    path: str
    node: ast.AST
    class_name: str = ""


@dataclass
class _SpawnSite:
    role_id: str
    kind: str
    fn_qual: str
    path: str
    line: int
    entry_qual: str  # "" when the target could not be resolved


@dataclass
class _FnScan:
    fn: object  # FunctionInfo | _PseudoFn
    accesses: list[Access] = field(default_factory=list)
    calls: list[tuple[str, frozenset]] = field(default_factory=list)
    spawns: list[_SpawnSite] = field(default_factory=list)
    joins: list[int] = field(default_factory=list)
    #: (channel key, "send"|"recv", line)
    channels: list[tuple[tuple, str, int]] = field(default_factory=list)


class RaceAnalysis:
    def __init__(
        self,
        modules: list[ParsedModule],
        graph: Optional[CallGraph] = None,
        model: Optional[LockModel] = None,
    ):
        self.modules = modules
        self.graph = graph or CallGraph(modules)
        self.model = model or LockModel(modules, self.graph)
        self.annotations = {
            m.path: RaceAnnotations(m.source) for m in modules
        }
        self.scans: dict[str, _FnScan] = {}
        self._container_fields: set = set()
        self.roles: dict[str, ThreadRole] = {}
        self._role_entries: dict[str, list[_SpawnSite]] = {}
        self.findings: list[Finding] = []

    # -- driver --------------------------------------------------------------

    def run(self) -> RaceGraphResult:
        for fn in list(self.graph.functions.values()):
            self._scan_function(fn)
        self._fold_callbacks()
        self._build_roles()
        role_reach = self._role_reachability()
        main_reach = self._main_reachability()
        entry_held = self._entry_held_fixpoint()
        fields = self._collect_fields(role_reach, main_reach, entry_held)
        self._finalize_rules(fields)
        self.findings.sort()
        return RaceGraphResult(
            roles=self.roles, fields=fields, findings=self.findings
        )

    # -- per-function scan ---------------------------------------------------

    def _scan_function(self, fn: FunctionInfo) -> None:
        if fn.qualname in self.scans:
            return
        nested = self._nested_defs(fn.node)
        spawn_names = self._spawn_target_names(fn.node)
        called_names = {
            c.func.id
            for c in astutil.iter_calls(fn.node)
            if isinstance(c.func, ast.Name)
        }
        # A nested def ONLY referenced as a spawn target runs on the new
        # thread, never on this one: scan it as its own pseudo-function so
        # its writes are attributed to the role, not the spawner.
        spawn_only = {
            name
            for name in nested
            if name in spawn_names and name not in called_names
        }
        scan = _FnScan(fn=fn)
        self.scans[fn.qualname] = scan
        body = getattr(fn.node, "body", [])
        self._walk_stmts(scan, fn, body, held=(), skip_defs=spawn_only)
        for name in sorted(spawn_only):
            sub = _PseudoFn(
                qualname=f"{fn.qualname}.{name}",
                name=name,
                module=fn.module,
                path=fn.path,
                node=nested[name],
                class_name=fn.class_name,
            )
            sub_scan = _FnScan(fn=sub)
            self.scans[sub.qualname] = sub_scan
            self._walk_stmts(
                sub_scan, fn, nested[name].body, held=(), skip_defs=set()
            )
            # Re-anchor: accesses inside the pseudo-def belong to it.
            for acc in sub_scan.accesses:
                acc.fn_qual = sub.qualname
            sub_scan.calls = [c for c in sub_scan.calls]

    @staticmethod
    def _nested_defs(node: ast.AST) -> dict[str, ast.FunctionDef]:
        out: dict[str, ast.FunctionDef] = {}
        for sub in ast.walk(node):
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub is not node
            ):
                out.setdefault(sub.name, sub)
        return out

    def _spawn_target_names(self, node: ast.AST) -> set:
        out: set = set()
        for call in astutil.iter_calls(node):
            expr = self._spawn_entry_expr(call)
            if isinstance(expr, ast.Name):
                out.add(expr.id)
        return out

    @staticmethod
    def _spawn_entry_expr(call: ast.Call) -> Optional[ast.expr]:
        """The function expression a call hands to another thread:
        ``Thread(target=f)`` / ``pool.submit(f, ...)``, including the
        contextvars idiom ``pool.submit(ctx.run, f, ...)`` where the real
        entry is the second argument."""
        name = astutil.call_name(call)
        if name == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    return kw.value
            return None
        if name == "submit" and call.args:
            first = call.args[0]
            if (
                isinstance(first, ast.Attribute)
                and first.attr == "run"
                and len(call.args) >= 2
            ):
                return call.args[1]
            return first
        return None

    def _walk_stmts(
        self,
        scan: _FnScan,
        ctx: FunctionInfo,
        stmts: Iterable[ast.stmt],
        held: tuple,
        skip_defs: set,
    ) -> None:
        for stmt in stmts:
            self._walk_stmt(scan, ctx, stmt, held, skip_defs)

    def _walk_stmt(
        self,
        scan: _FnScan,
        ctx: FunctionInfo,
        stmt: ast.stmt,
        held: tuple,
        skip_defs: set,
    ) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if stmt.name in skip_defs:
                return
            # A locally-invoked nested def runs on this thread; lexical
            # holds do NOT carry into its body (it runs when called, not
            # where defined) — entry-held propagation owns that edge.
            self._walk_stmts(scan, ctx, stmt.body, (), skip_defs)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            layer = list(held)
            for item in stmt.items:
                for lock_id in self._with_item_locks(item.context_expr, ctx):
                    layer.append(lock_id)
                self._scan_exprs(scan, ctx, [item.context_expr], held)
            self._walk_stmts(scan, ctx, stmt.body, tuple(layer), skip_defs)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                stmt.targets
                if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            value = getattr(stmt, "value", None)
            for target in targets:
                self._note_target_write(scan, ctx, target, held, value)
            if value is not None:
                self._scan_exprs(scan, ctx, [value], held)
            return
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                self._note_target_write(scan, ctx, target, held)
            return
        self._scan_exprs(
            scan,
            ctx,
            [v for v in ast.iter_child_nodes(stmt) if isinstance(v, ast.expr)],
            held,
        )
        for block in ("body", "orelse", "finalbody"):
            self._walk_stmts(scan, ctx, getattr(stmt, block, []), held, skip_defs)
        for handler in getattr(stmt, "handlers", []):
            self._walk_stmts(scan, ctx, handler.body, held, skip_defs)

    def _with_item_locks(self, expr: ast.expr, ctx: FunctionInfo) -> list:
        ref = self.model.resolve_lock(expr, ctx)
        if ref is not None:
            return [ref.id]
        if isinstance(expr, ast.Call):
            callee = self.graph.resolve_call(expr, ctx)
            if callee is not None and callee.is_contextmanager:
                return [r.id for r in self.model.cm_yield(callee)]
        return []

    def _note_target_write(
        self,
        scan: _FnScan,
        ctx: FunctionInfo,
        target: ast.expr,
        held: tuple,
        value: Optional[ast.expr] = None,
    ) -> None:
        node = target
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                self._note_target_write(scan, ctx, elt, held)
            return
        subscripted = isinstance(node, ast.Subscript)
        if subscripted:
            node = node.value
        attr = astutil.self_attr_target(node)
        if attr is not None and ctx.class_name:
            if not subscripted and _is_container_expr(value):
                self._container_fields.add(
                    (f"{ctx.module}:{ctx.class_name}", attr)
                )
            if subscripted:
                # self.x[k] = v mutates the container; same evidence gate
                # as the method-mutator form.
                self._container_fields.add(
                    (f"{ctx.module}:{ctx.class_name}", attr)
                )
            self._note_access(scan, ctx, attr, node, held, write=True)

    def _scan_exprs(
        self,
        scan: _FnScan,
        ctx: FunctionInfo,
        exprs: Iterable[ast.expr],
        held: tuple,
    ) -> None:
        mutator_receivers: set = set()
        calls: list[ast.Call] = []
        for expr in exprs:
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    calls.append(node)
        for call in calls:
            self._note_call(scan, ctx, call, held, mutator_receivers)
        for expr in exprs:
            for node in ast.walk(expr):
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and id(node) not in mutator_receivers
                ):
                    attr = astutil.self_attr_target(node)
                    if attr is not None and ctx.class_name:
                        self._note_access(
                            scan, ctx, attr, node, held, write=False
                        )

    def _note_call(
        self,
        scan: _FnScan,
        ctx: FunctionInfo,
        call: ast.Call,
        held: tuple,
        mutator_receivers: set,
    ) -> None:
        name = astutil.call_name(call)
        func = call.func
        # Mutating method on a self attribute is a write to that field.
        if (
            isinstance(func, ast.Attribute)
            and name in _MUTATORS
            and astutil.self_attr_target(func.value) is not None
            and ctx.class_name
        ):
            mutator_receivers.add(id(func.value))
            self._note_access(
                scan,
                ctx,
                astutil.self_attr_target(func.value),
                func.value,
                held,
                write=True,
                mutate=True,
            )
        self._note_channel_op(scan, ctx, call, name)
        if name == "join" and not call.args and not call.keywords:
            scan.joins.append(call.lineno)
        spawn_entry = self._spawn_entry_expr(call)
        if spawn_entry is not None:
            self._note_spawn(scan, ctx, call, spawn_entry)
        callee = self.graph.resolve_call(call, ctx)
        if callee is not None:
            scan.calls.append((callee.qualname, frozenset(held)))

    def _note_channel_op(
        self, scan: _FnScan, ctx: FunctionInfo, call: ast.Call, name: str
    ) -> None:
        direction = ""
        if name in _SEND_METHODS or (name == "set" and not call.args):
            direction = "send"
        elif name in _RECV_METHODS or name == "get":
            direction = "recv"
        if not direction or not isinstance(call.func, ast.Attribute):
            return
        recv = call.func.value
        attr = astutil.self_attr_target(recv)
        if attr is not None and ctx.class_name:
            key = ("attr", f"{ctx.module}:{ctx.class_name}", attr)
        elif isinstance(recv, ast.Name):
            key = ("name", ctx.module, recv.id)
        else:
            return
        scan.channels.append((key, direction, call.lineno))

    def _note_spawn(
        self,
        scan: _FnScan,
        ctx: FunctionInfo,
        call: ast.Call,
        entry_expr: ast.expr,
    ) -> None:
        kind = "thread" if astutil.call_name(call) == "Thread" else "pool"
        entry = self._resolve_entry(entry_expr, ctx)
        role_id = self._role_id(call, entry_expr, entry, kind)
        if role_id is None:
            return
        scan.spawns.append(
            _SpawnSite(
                role_id=role_id,
                kind=kind,
                fn_qual=self._scan_qual(scan),
                path=ctx.path,
                line=call.lineno,
                entry_qual=entry.qualname if entry is not None else "",
            )
        )

    @staticmethod
    def _scan_qual(scan: _FnScan) -> str:
        return scan.fn.qualname

    def _resolve_entry(self, expr: ast.expr, ctx: FunctionInfo):
        if isinstance(expr, ast.Name):
            fn = self.graph.module_function(ctx.module, expr.id)
            if fn is not None:
                return fn
            # A nested def in the spawning function: pseudo-scanned by
            # _scan_function; reference it by its pseudo qualname.
            pseudo = self.scans.get(f"{ctx.qualname}.{expr.id}")
            if pseudo is not None:
                return pseudo.fn
            return _PseudoFn(
                qualname=f"{ctx.qualname}.{expr.id}",
                name=expr.id,
                module=ctx.module,
                path=ctx.path,
                node=expr,
                class_name=ctx.class_name,
            )
        attr = astutil.self_attr_target(expr)
        if attr is not None and ctx.class_name:
            return self.graph.method_on(f"{ctx.module}:{ctx.class_name}", attr)
        # ``target=self.queue.run``: resolve the receiver attribute's class
        # through the call graph's attr-type inference, then the method on
        # that class — the controller spawns its workers this way.
        if isinstance(expr, ast.Attribute) and ctx.class_name:
            recv_attr = astutil.self_attr_target(expr.value)
            if recv_attr is not None:
                owner = self.graph.classes.get(
                    f"{ctx.module}:{ctx.class_name}"
                )
                attr_cls = owner.attr_types.get(recv_attr) if owner else None
                if attr_cls:
                    return self.graph.method_on(attr_cls, expr.attr)
        return None

    def _role_id(
        self, call: ast.Call, entry_expr: ast.expr, entry, kind: str
    ) -> Optional[str]:
        for kw in call.keywords:
            if kw.arg != "name":
                continue
            if isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, str
            ):
                return kw.value.value
            if isinstance(kw.value, ast.JoinedStr) and kw.value.values:
                first = kw.value.values[0]
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    prefix = first.value.rstrip("-_ ")
                    if prefix:
                        return prefix
        name = ""
        if entry is not None:
            name = entry.name
        elif isinstance(entry_expr, ast.Name):
            name = entry_expr.id
        elif isinstance(entry_expr, ast.Attribute):
            name = entry_expr.attr
        if not name:
            return None
        return f"{kind}:{name.lstrip('_')}"

    # -- callback dispatch the call graph cannot resolve ---------------------

    def _fold_callbacks(self) -> None:
        """Informer handlers run on the watch thread under the dispatch
        lock; ``_run_effects`` callables run on the claim-effects pool.
        Both are function-valued dispatch lockmodel already resolves —
        reuse its target lists as synthetic call edges / role entries."""
        dispatch = self.scans.get("tpudra.kube.informer:Informer._dispatch")
        if dispatch is not None:
            for target in self.model._handler_targets:
                # _dispatch invokes handlers holding its RLock (the
                # registry id of Informer._dispatch_lock).
                dispatch.calls.append(
                    (target.qualname, frozenset({"informer.dispatch_lock"}))
                )
        run_effects = self.scans.get("tpudra.plugin.driver:Driver._run_effects")
        if run_effects is not None:
            for scan in list(self.scans.values()):
                for spawn in scan.spawns:
                    if spawn.fn_qual != run_effects.fn.qualname:
                        continue
                    pseudo = self.scans.get(spawn.entry_qual)
                    if pseudo is None:
                        continue
                    for target in self.model._effect_targets:
                        pseudo.calls.append((target.qualname, frozenset()))

    # -- roles and reachability ----------------------------------------------

    def _build_roles(self) -> None:
        for scan in self.scans.values():
            for spawn in scan.spawns:
                self._role_entries.setdefault(spawn.role_id, []).append(spawn)
        for role_id, sites in sorted(self._role_entries.items()):
            first = min(sites, key=lambda s: (s.path, s.line))
            entries = tuple(
                sorted({s.entry_qual for s in sites if s.entry_qual})
            )
            self.roles[role_id] = ThreadRole(
                role_id=role_id,
                kind=first.kind,
                spawned_in=first.fn_qual,
                path=first.path,
                line=first.line,
                entries=entries,
            )

    def _adjacency(self) -> dict[str, set]:
        adj: dict[str, set] = {}
        for qual, scan in self.scans.items():
            adj.setdefault(qual, set())
            for callee, _held in scan.calls:
                if callee in self.scans:
                    adj[qual].add(callee)
        return adj

    def _closure(self, roots: Iterable[str], adj: dict[str, set]) -> set:
        seen: set = set()
        stack = [r for r in roots if r in adj]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(adj.get(q, ()))
        return seen

    def _role_reachability(self) -> dict[str, set]:
        adj = self._adjacency()
        return {
            role_id: self._closure(role.entries, adj)
            for role_id, role in self.roles.items()
        }

    def _main_reachability(self) -> set:
        adj = self._adjacency()
        indeg: dict[str, int] = {q: 0 for q in adj}
        for callees in adj.values():
            for c in callees:
                indeg[c] = indeg.get(c, 0) + 1
        entry_quals = {e for r in self.roles.values() for e in r.entries}
        roots = [
            q for q, d in indeg.items() if d == 0 and q not in entry_quals
        ]
        return self._closure(roots, adj)

    def _entry_held_fixpoint(self) -> dict[str, frozenset]:
        """``entry(f) = ∩ over call sites (entry(caller) ∪ held-at-site)``
        — the lock set DEFINITELY held whenever ``f`` runs.  Roots (main
        roots, role entries) start empty; the intersection only ever
        shrinks, so the optimistic worklist terminates."""
        entry: dict[str, Optional[frozenset]] = {q: None for q in self.scans}
        adj = self._adjacency()
        indeg: dict[str, int] = {q: 0 for q in adj}
        for callees in adj.values():
            for c in callees:
                indeg[c] = indeg.get(c, 0) + 1
        for role in self.roles.values():
            for e in role.entries:
                entry[e] = frozenset()
        for q, d in indeg.items():
            if d == 0:
                entry[q] = frozenset()
        for _ in range(len(self.scans) + 1):
            changed = False
            for qual, scan in self.scans.items():
                base = entry.get(qual)
                if base is None:
                    continue
                for callee, held in scan.calls:
                    if callee not in entry:
                        continue
                    cand = base | held
                    cur = entry[callee]
                    new = cand if cur is None else (cur & cand)
                    if new != cur:
                        entry[callee] = new
                        changed = True
            if not changed:
                break
        return {q: (s or frozenset()) for q, s in entry.items()}

    # -- access collection ---------------------------------------------------

    def _note_access(
        self,
        scan: _FnScan,
        ctx: FunctionInfo,
        attr: str,
        node: ast.AST,
        held: tuple,
        write: bool,
        mutate: bool = False,
    ) -> None:
        directive = self.annotations.get(
            ctx.path, RaceAnnotations("")
        ).at(getattr(node, "lineno", 0))
        guards = frozenset(held)
        owner = ""
        handoff = False
        if directive is not None:
            guards |= frozenset(directive.guards)
            owner = directive.owner
            handoff = directive.handoff
        fn_qual = self._scan_qual(scan)
        scan.accesses.append(
            Access(
                field=(f"{ctx.module}:{ctx.class_name}", attr),
                path=ctx.path,
                line=getattr(node, "lineno", 1),
                fn_qual=fn_qual,
                write=write,
                init=scan.fn.name == "__init__",
                guards=guards,
                roles=frozenset(),
                handoff=handoff,
                owner=owner,
                mutate=mutate,
            )
        )

    def _collect_fields(
        self,
        role_reach: dict[str, set],
        main_reach: set,
        entry_held: dict[str, frozenset],
    ) -> dict[str, FieldInfo]:
        roles_of: dict[str, frozenset] = {}
        for qual in self.scans:
            mine = {
                role_id
                for role_id, reach in role_reach.items()
                if qual in reach
            }
            if qual in main_reach or not mine:
                mine.add(MAIN_ROLE)
            roles_of[qual] = frozenset(mine)
        fields: dict[tuple, FieldInfo] = {}
        for qual, scan in self.scans.items():
            for acc in scan.accesses:
                acc.roles = roles_of[qual]
                acc.guards = acc.guards | entry_held.get(qual, frozenset())
                if acc.mutate and acc.field not in self._container_fields:
                    acc.write = False  # '.update()' on a non-container: a read
                cls = acc.field[0].partition(":")[2] or acc.field[0]
                display = f"{cls}.{acc.field[1]}"
                info = fields.get(acc.field)
                if info is None:
                    info = fields[acc.field] = FieldInfo(
                        field=acc.field, display=display
                    )
                info.sites.append(acc)
                if acc.owner:
                    info.owner = info.owner or acc.owner
        out: dict[str, FieldInfo] = {}
        for info in sorted(fields.values(), key=lambda i: i.field):
            info.sites.sort(key=lambda a: (a.path, a.line))
            out[info.display] = info
        return out

    # -- happens-before ------------------------------------------------------

    def _channel_map(self) -> dict[tuple, dict[str, tuple[list, list]]]:
        """channel key → fn_qual → (send lines, recv lines).  Bare ``get``
        receives only count on channels some function also ``put``s to."""
        chans: dict[tuple, dict[str, tuple[list, list]]] = {}
        has_put: set = set()
        for qual, scan in self.scans.items():
            for key, direction, line in scan.channels:
                sends, recvs = chans.setdefault(key, {}).setdefault(
                    qual, ([], [])
                )
                (sends if direction == "send" else recvs).append(line)
                if direction == "send":
                    has_put.add(key)
        return {
            key: per_fn
            for key, per_fn in chans.items()
            if key in has_put
        }

    def _hb_covers(self, acc: Access, role_id: str) -> bool:
        """True when ``acc`` is ordered against the WHOLE life of the
        role: it is init-before-start publication, runs in the spawning
        function before the spawn, or runs there after a post-spawn
        ``join()``."""
        if acc.init or acc.handoff:
            return True
        for site in self._role_entries.get(role_id, ()):
            if site.fn_qual != acc.fn_qual:
                continue
            if acc.line < site.line:
                return True
            scan = self.scans.get(acc.fn_qual)
            if scan and any(
                site.line < j <= acc.line for j in scan.joins
            ):
                return True
        return False

    def _channel_ordered(
        self,
        a: Access,
        b: Access,
        chans: dict[tuple, dict[str, tuple[list, list]]],
    ) -> bool:
        """Handoff HB: one side writes then sends on a channel, the other
        receives on it then writes — either direction."""
        for per_fn in chans.values():
            a_ops = per_fn.get(a.fn_qual)
            b_ops = per_fn.get(b.fn_qual)
            if a_ops is None or b_ops is None:
                continue
            if any(line >= a.line for line in a_ops[0]) and any(
                line <= b.line for line in b_ops[1]
            ):
                return True
            if any(line >= b.line for line in b_ops[0]) and any(
                line <= a.line for line in a_ops[1]
            ):
                return True
        return False

    def _pair_ordered(self, a: Access, b: Access, chans) -> bool:
        if self._channel_ordered(a, b, chans):
            return True
        for r1 in a.roles:
            for r2 in b.roles:
                if r1 == r2:
                    continue
                if not (self._hb_covers(a, r2) or self._hb_covers(b, r1)):
                    return False
        return True

    # -- rules ---------------------------------------------------------------

    def _finalize_rules(self, fields: dict[str, FieldInfo]) -> None:
        chans = self._channel_map()
        for info in fields.values():
            if info.owner:
                self._check_owner(info)
                continue
            self._check_locksets(info, chans)

    def _check_owner(self, info: FieldInfo) -> None:
        for acc in info.sites:
            if acc.init or acc.handoff:
                continue
            strays = sorted(acc.roles - {info.owner})
            if not strays:
                continue
            self.findings.append(
                Finding(
                    path=acc.path,
                    line=acc.line,
                    col=0,
                    rule_id="THREAD-CONFINED-ESCAPE",
                    message=(
                        f"'{info.display}' is declared owner={info.owner} "
                        f"but this {'write' if acc.write else 'read'} runs "
                        f"on role(s) {', '.join(strays)} "
                        f"({acc.fn_qual.partition(':')[2] or acc.fn_qual}) — "
                        "confine the access to the owning thread or drop "
                        "the owner= claim"
                    ),
                )
            )

    def _check_locksets(self, info: FieldInfo, chans) -> None:
        writes = [
            a for a in info.sites if a.write and not a.init and not a.handoff
        ]
        if len({r for a in writes for r in a.roles}) < 2:
            return
        live: list[Access] = []
        for a in writes:
            conflicted = False
            for b in writes:
                cross = any(
                    r1 != r2 for r1 in a.roles for r2 in b.roles
                )
                if not cross:
                    continue
                if a.guards & b.guards:
                    continue
                if self._pair_ordered(a, b, chans):
                    continue
                conflicted = True
                break
            if conflicted:
                live.append(a)
        if not live:
            return
        intersection = frozenset.intersection(*[a.guards for a in live])
        if intersection:
            return
        anchor = self._anchor(live)
        sites = ", ".join(
            f"{_rel(a.path)}:{a.line}"
            + (f" [{'+'.join(sorted(a.guards))}]" if a.guards else "")
            for a in live
        )
        role_list = ", ".join(sorted({r for a in live for r in a.roles}))
        if all(a.guards for a in live):
            self.findings.append(
                Finding(
                    path=anchor.path,
                    line=anchor.line,
                    col=0,
                    rule_id="GUARD-CONSISTENCY",
                    message=(
                        f"'{info.display}' is written under DIFFERENT locks "
                        f"at different sites ({sites}; roles: {role_list}) — "
                        "pick one guard for every write or annotate "
                        "'# tpudra-race: guard=' with why two suffice"
                    ),
                )
            )
            return
        self.findings.append(
            Finding(
                path=anchor.path,
                line=anchor.line,
                col=0,
                rule_id="RACE",
                message=(
                    f"'{info.display}' is written from roles {role_list} "
                    f"with no common guard and no happens-before edge "
                    f"(writes: {sites}) — guard every write with one lock, "
                    "order them (start/join, queue or event handoff), or "
                    "annotate '# tpudra-race: guard=/owner=/handoff' with a "
                    "reason"
                ),
            )
        )

    @staticmethod
    def _anchor(live: list) -> "Access":
        """Deterministic finding anchor: prefer an unguarded write on a
        non-main role (the spawned-thread side reads best in review)."""
        for a in live:
            if not a.guards and a.roles != frozenset({MAIN_ROLE}):
                return a
        for a in live:
            if not a.guards:
                return a
        return live[0]


def analyze_races(
    modules: list[ParsedModule],
    graph: Optional[CallGraph] = None,
    model: Optional[LockModel] = None,
) -> RaceGraphResult:
    return RaceAnalysis(modules, graph, model).run()

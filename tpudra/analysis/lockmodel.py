"""tpudra-lockgraph: the whole-program lock model.

Three layers on top of the call graph (callgraph.py):

1. **Lock registry** — every ``threading.Lock/RLock/Condition`` attribute,
   every ``lockwitness.make_*`` construction (the instrumented modules), and
   every ``Flock`` family resolves to a *stable lock ID*.  IDs are lockdep
   classes, not instances: every ``Informer``'s store lock is one node
   (``informer.store_lock``), every per-claim flock is ``flock:claim-uid``.
   Dynamic cases carry a ``# tpudra-lock: id=NAME`` annotation
   (``vfio.py``'s per-device submutexes, ``Flock`` construction sites whose
   path is computed).

2. **Held-set propagation** — each function's body becomes an event tree
   (lock ``with`` blocks, contextmanager expansions, calls, raw
   acquire/release); walking it with the held set derives the global lock
   *acquisition graph*: an edge A → B means "B was acquired while A was
   held", with one concrete call path recorded per edge.

3. **Rules** over that graph:

   - ``LOCK-CYCLE``: a cycle in the acquisition graph is a static deadlock
     candidate; reported once per cycle with the concrete path pair.
     Re-entrant locks (RLock, Condition) and ordered families (claim-uid
     flocks, per-device mutexes — their intra-family order is LOCK-ORDER's
     ``sorted()`` check) do not self-cycle.
   - ``BLOCK-UNDER-LOCK-IP``: the interprocedural upgrade of
     BLOCK-UNDER-LOCK — sleep / subprocess / gRPC / apiserver calls /
     blocking waits reachable within ``MAX_BLOCK_DEPTH`` calls while an
     in-process lock is held.  Direct (depth-0) sleep/subprocess/open/stub
     offenses stay BLOCK-UNDER-LOCK's; this rule owns everything the
     lexical rule cannot see.
   - ``FLOCK-INVERSION``: a cross-process flock acquired while an
     in-process lock is held — the ordering that wedges a node when two
     driver processes race (the in-process holder waits on a flock held by
     a process waiting to enter the same in-process critical section).

Annotations (comment on the line, or alone on the line above):

    # tpudra-lock: id=NAME [family] <reason>      — name this lock
    # tpudra-lock: acquires=NAME <reason>         — calling this function
    #     leaves NAME held (it returns a held lock to its caller)
    # tpudra-lock: nonblocking <reason>           — calls to this function
    #     are not blocking for BLOCK-UNDER-LOCK-IP (modeled by design)
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional, Union

from tpudra.analysis import astutil
from tpudra.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    module_name,
    short_module,
)
from tpudra.analysis.engine import Finding, ParsedModule

#: Max call depth BLOCK-UNDER-LOCK-IP follows under a held in-process lock.
#: (The acquired-locks closure acq_star is full-depth by design — edges are
#: correctness, not latency; only the blocking rule has a reach horizon.)
MAX_BLOCK_DEPTH = 4

#: Blocking categories the lexical BLOCK-UNDER-LOCK rule already owns at
#: depth 0 — re-reporting them here would double-bill one offense.
_LEXICAL_CATEGORIES = frozenset({"time.sleep", "subprocess", "open()", "gRPC stub call"})

#: The lock IDs that make up the claim-bind path — the witness coverage
#: criterion (docs/static-analysis.md) is computed over edges whose both
#: endpoints are in this set.
#: The request-accounting wrapper's counter mutex (kube/accounting.py).
#: Every ``KubeAPI`` verb may run through ``AccountingKube`` — the
#: standard wrapper in the binaries and every harness — which takes this
#: lock inside the verb.  The call graph cannot see that dispatch (the
#: verb resolves to the ``KubeAPI`` protocol, not to a concrete class),
#: so the walker models it: an apiserver-verb call under held locks
#: contributes ``held → accounting.counts_lock`` edges.  Without this the
#: runtime witness reports a model gap the first time a soak publishes
#: slices (publish_lock held) through an accounted fake.
ACCOUNTING_COUNTS_LOCK = "accounting.counts_lock"

BIND_PATH_LOCKS = frozenset(
    {
        "flock:pu.lock",
        "flock:cp.lock",
        "flock:claim-uid",
        "checkpoint.cache_lock",
        "checkpoint.commit_cond",
        "driver.publish_lock",
        "driver.publish_cond",
        "driver.unhealthy_lock",
        "singleflight.lock",
    }
)

_ANNOTATION_RE = re.compile(r"#\s*tpudra-lock:\s*(?P<body>.+)")
_KV_RE = re.compile(r"^(id|acquires)=(\S+)$")

_KUBE_VERBS = frozenset({"get", "list", "create", "patch", "delete", "watch", "apply"})
_WITNESS_CTORS = {"make_lock": "lock", "make_rlock": "rlock", "make_condition": "cond"}
_THREADING_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond"}


@dataclass(frozen=True)
class LockRef:
    id: str
    kind: str  # lock | rlock | cond | flock
    family: bool = False
    witnessable: bool = False
    defined_at: str = ""  # "path:line" of the defining site (docs)

    @property
    def reentrant(self) -> bool:
        # threading.Condition's default internal lock IS an RLock.
        return self.kind in ("rlock", "cond")

    @property
    def in_process(self) -> bool:
        return self.kind != "flock"


# ---------------------------------------------------------------- annotations


@dataclass
class _Directive:
    lock_id: Optional[str] = None
    acquires: Optional[str] = None
    family: bool = False
    nonblocking: bool = False


class LockAnnotations:
    """``# tpudra-lock: ...`` directives of one file, by line (a directive
    alone on its line also covers the next, like lint suppressions)."""

    def __init__(self, source: str):
        self.by_line: dict[int, _Directive] = {}
        try:
            tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _ANNOTATION_RE.search(tok.string)
                if not m:
                    continue
                directive = _Directive()
                for word in m.group("body").split():
                    kv = _KV_RE.match(word)
                    if kv:
                        if kv.group(1) == "id":
                            directive.lock_id = kv.group(2)
                        else:
                            directive.acquires = kv.group(2)
                    elif word == "family":
                        directive.family = True
                    elif word == "nonblocking":
                        directive.nonblocking = True
                    else:
                        break  # free-text reason starts
                line = tok.start[0]
                self.by_line[line] = directive
                if tok.line.strip().startswith("#"):
                    self.by_line.setdefault(line + 1, directive)
        except tokenize.TokenError:
            pass

    def at(self, *lines: int) -> Optional[_Directive]:
        for line in lines:
            d = self.by_line.get(line)
            if d is not None:
                return d
        return None


# ------------------------------------------------------------------ event IR


@dataclass
class WithLockEv:
    lock: LockRef
    node: ast.AST
    body: list = field(default_factory=list)
    #: True when astutil.withitem_lock_kind would classify this item, i.e.
    #: the lexical BLOCK-UNDER-LOCK rule already polices the body.
    lexical: bool = False


@dataclass
class WithCMEv:
    fn: FunctionInfo
    node: ast.AST
    body: list = field(default_factory=list)


@dataclass
class CallEv:
    node: ast.Call
    fn: Optional[FunctionInfo] = None
    blocking: str = ""  # nonempty: the call itself blocks (label)
    wait_on: Optional[LockRef] = None  # cond.wait(...) target
    wait_exempt: bool = False  # wait on a lock this function lexically holds


@dataclass
class AcqEv:
    lock: LockRef
    node: ast.AST


@dataclass
class RelEv:
    lock: LockRef
    node: ast.AST


@dataclass
class YieldEv:
    node: ast.AST


Event = Union[WithLockEv, WithCMEv, CallEv, AcqEv, RelEv, YieldEv]


# ------------------------------------------------------------------- results


@dataclass
class Edge:
    src: LockRef
    dst: LockRef
    path: str  # file path of the acquisition site
    line: int
    chain: str  # human call chain, e.g. "Driver.prepare → _locked_pu"


@dataclass
class LockGraphResult:
    locks: dict[str, LockRef]
    edges: dict[tuple[str, str], Edge]
    findings: list[Finding]

    def edge_ids(self) -> set[tuple[str, str]]:
        return set(self.edges)

    def witnessable_edge_ids(self) -> set[tuple[str, str]]:
        """Edges the runtime witness could ever observe: both endpoints
        are instrumented locks (lockwitness-constructed or flocks)."""
        return {
            (a, b)
            for (a, b), _ in self.edges.items()
            if self.locks[a].witnessable and self.locks[b].witnessable
        }


# ------------------------------------------------------------------ analysis


def _finding(rule_id: str, path: str, node, message: str) -> Finding:
    return Finding(
        path=path,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        rule_id=rule_id,
        message=message,
    )


def _rel(path: str) -> str:
    """Paths in messages/docs are repo-relative for stable output."""
    for marker in ("tpudra" + os.sep, "tools" + os.sep):
        idx = path.find(os.sep + marker)
        if idx >= 0:
            return path[idx + 1:]
    return os.path.basename(path)


class LockModel:
    """Builds the registry, the per-function event IR, and the acquisition
    graph over one corpus of parsed modules."""

    def __init__(self, modules: list[ParsedModule], graph: Optional[CallGraph] = None):
        self.modules = modules
        self.graph = graph or CallGraph(modules)
        self.annotations: dict[str, LockAnnotations] = {
            m.path: LockAnnotations(m.source) for m in modules
        }
        #: (class_qual, attr) → LockRef
        self.attr_locks: dict[tuple[str, str], LockRef] = {}
        #: (module, name) → LockRef for module-level locks
        self.module_locks: dict[tuple[str, str], LockRef] = {}
        #: annotated id → LockRef (the registry of explicitly named locks)
        self.named: dict[str, LockRef] = {}
        self.nonblocking: set[str] = set()  # function qualnames
        self.acquires_ann: dict[str, str] = {}  # function qualname → lock id
        self._ir: dict[str, list[Event]] = {}
        self._local_types: dict[str, dict[str, str]] = {}
        self._local_locks: dict[str, dict[str, LockRef]] = {}
        self._returns_lock: dict[str, Optional[LockRef]] = {}
        self._returns_lock_stack: set[str] = set()
        self._acq_star: dict[str, dict[str, tuple[LockRef, str]]] = {}
        self._acq_star_stack: set[str] = set()
        self._block_star: dict[tuple[str, int], list[tuple[str, str, int, str]]] = {}
        self._cm_yield: dict[str, list[LockRef]] = {}
        self._kube_quals = self._collect_kube_quals()
        self._flock_quals = self._collect_flock_quals()
        self._build_registry()
        #: Functions registered as informer event handlers
        #: (``Informer.add_handler(fn)``): callback dispatch the call
        #: graph cannot resolve — ``Informer._dispatch`` invokes them
        #: under ``informer.dispatch_lock``, so every lock a handler takes
        #: is an edge from the dispatch lock (the cd_wave soak witnessed
        #: informer.dispatch_lock → workqueue.cond/backoff_lock exactly
        #: this way: controller handlers enqueue reconciles in-handler).
        self._handler_targets = self._collect_handler_targets()
        #: Methods passed to ``Driver._run_effects`` (the effects-phase
        #: fan-out invokes them through a function-valued ``effect``
        #: parameter the call graph cannot resolve) — modeled as direct
        #: callees of the dispatch, like Informer handlers above.  The
        #: partition_fault soak witnessed flock:claim-uid →
        #: accounting.counts_lock exactly this way: the MP control-daemon
        #: stamp is an apiserver write inside the prepare effects phase.
        self._effect_targets = self._collect_effect_targets()

    def _collect_effect_targets(self) -> list[FunctionInfo]:
        """Every bound method passed as the effect callable to a
        ``_run_effects(...)`` call (``self.state.run_prepare_effects``
        shapes): resolved by unique method name across the graph — the
        same last-resort resolution the call graph itself uses, precise
        here because the effect entry points are uniquely named."""
        targets: list[FunctionInfo] = []
        seen: set[str] = set()
        for fn in self.graph.functions.values():
            for node in ast.walk(fn.node):
                if not (
                    isinstance(node, ast.Call)
                    and astutil.call_name(node) == "_run_effects"
                    and len(node.args) >= 2
                ):
                    continue
                arg = node.args[1]
                if not isinstance(arg, ast.Attribute):
                    continue
                for cand in self.graph.functions.values():
                    if (
                        cand.name == arg.attr
                        and cand.class_name
                        and cand.qualname not in seen
                    ):
                        seen.add(cand.qualname)
                        targets.append(cand)
        return sorted(targets, key=lambda f: f.qualname)

    def _collect_handler_targets(self) -> list[FunctionInfo]:
        """Every function passed to an ``add_handler(...)`` registration:
        ``self._method`` args resolve on the registering class, bare names
        as module functions.  Order-stable and deduped so the derived IR
        (and therefore docs/lock-order.md) is deterministic."""
        targets: list[FunctionInfo] = []
        seen: set[str] = set()
        for fn in self.graph.functions.values():
            for node in ast.walk(fn.node):
                if not (
                    isinstance(node, ast.Call)
                    and astutil.call_name(node) == "add_handler"
                    and node.args
                ):
                    continue
                arg = node.args[0]
                target: Optional[FunctionInfo] = None
                if (
                    isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id == "self"
                ):
                    cls = self.graph.class_of(fn)
                    if cls is not None:
                        target = self.graph.method_on(cls.qualname, arg.attr)
                elif isinstance(arg, ast.Name):
                    target = self.graph.module_function(
                        module_name(fn.path), arg.id
                    )
                if target is not None and target.qualname not in seen:
                    seen.add(target.qualname)
                    targets.append(target)
        return targets

    # -- registry -----------------------------------------------------------

    def _collect_kube_quals(self) -> set[str]:
        out = set()
        for cls_name in ("KubeAPI", "KubeClient"):
            info = self.graph.classes.get(f"tpudra.kube.client:{cls_name}")
            if info is None:
                continue
            for name, fn in info.methods.items():
                if name in _KUBE_VERBS:
                    out.add(fn.qualname)
        return out

    def _collect_flock_quals(self) -> set[str]:
        info = self.graph.classes.get("tpudra.flock:Flock")
        if info is None:
            return set()
        return {
            fn.qualname
            for name, fn in info.methods.items()
            if name in ("acquire", "__call__", "__enter__")
        }

    def _register(self, ref: LockRef) -> LockRef:
        if ref.id in self.named:
            return self.named[ref.id]
        self.named[ref.id] = ref
        return ref

    def _ref_for_id(self, lock_id: str) -> LockRef:
        """A LockRef for an annotation-named ID with no registered
        construction site — the ``flock:`` prefix convention decides the
        kind (and thus in_process / witnessability), exactly as in
        resolve_lock's annotation path."""
        known = self.named.get(lock_id)
        if known is not None:
            return known
        if lock_id.startswith("flock:"):
            return LockRef(lock_id, "flock", witnessable=True)
        return LockRef(lock_id, "lock")

    def _lock_ctor_ref(
        self,
        call: ast.Call,
        module: ParsedModule,
        owner: str,  # derived-id prefix: "Class.attr" site context
        attr: str,
    ) -> Optional[LockRef]:
        """A LockRef when ``call`` constructs a lock, else None."""
        terminal = astutil.call_name(call)
        ann = self.annotations[module.path].at(call.lineno)
        site = f"{_rel(module.path)}:{call.lineno}"
        mod_short = short_module(_module_of(module))
        if terminal in _WITNESS_CTORS:
            lock_id = None
            if call.args and isinstance(call.args[0], ast.Constant):
                if isinstance(call.args[0].value, str):
                    lock_id = call.args[0].value
            if ann is not None and ann.lock_id:
                lock_id = ann.lock_id
            if lock_id is None:
                lock_id = _derived_id(mod_short, owner, attr)
            return LockRef(
                lock_id,
                _WITNESS_CTORS[terminal],
                family=bool(ann and ann.family),
                witnessable=True,
                defined_at=site,
            )
        if terminal in _THREADING_CTORS:
            lock_id = (
                ann.lock_id if ann is not None and ann.lock_id
                else _derived_id(mod_short, owner, attr)
            )
            return LockRef(
                lock_id,
                _THREADING_CTORS[terminal],
                family=bool(ann and ann.family),
                defined_at=site,
            )
        if terminal == "Flock" and astutil.is_flockish(call.func):
            return self._flock_ref(call, module, owner)
        return None

    def _flock_ref(self, call: ast.Call, module: ParsedModule, owner: str) -> LockRef:
        ann = self.annotations[module.path].at(call.lineno)
        site = f"{_rel(module.path)}:{call.lineno}"
        lock_id = None
        family = bool(ann and ann.family)
        if ann is not None and ann.lock_id:
            lock_id = ann.lock_id
        if lock_id is None:
            for kw in call.keywords:
                if (
                    kw.arg == "witness_id"
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                ):
                    lock_id = kw.value.value
        if lock_id is None and call.args:
            arg = call.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                lock_id = f"flock:{os.path.basename(arg.value)}"
        if lock_id is None:
            # Deterministic per-site fallback; annotate sites that a
            # witness run can reach so runtime and static IDs agree.
            lock_id = f"flock:{short_module(_module_of(module))}.{owner or '?'}"
        return LockRef(lock_id, "flock", family=family, witnessable=True, defined_at=site)

    def _build_registry(self) -> None:
        for module in self.modules:
            mod = _module_of(module)
            if mod not in ("tpudra.lockwitness", "tpudra.racewitness", "tpudra.trace"):
                # The witness and the tracer are the measurement apparatus:
                # their sink/ring guards are held for an append+flush and
                # never across another acquisition by construction;
                # modeling them would only wrap every instrumented
                # acquisition (and every span close) in a phantom lock
                # node.  (The modules stay in the CALL graph so references
                # into them resolve instead of degrading to unique-name
                # guesses, and their function-level directives below still
                # load — the witness emit paths declare nonblocking.)
                for node in module.tree.body:
                    if isinstance(node, ast.Assign) and len(node.targets) == 1:
                        target = node.targets[0]
                        if isinstance(target, ast.Name) and isinstance(node.value, ast.Call):
                            ref = self._lock_ctor_ref(node.value, module, "", target.id)
                            if ref is not None:
                                self.module_locks[(mod, target.id)] = self._register(ref)
                    elif isinstance(node, ast.ClassDef):
                        self._register_class_locks(module, mod, node)
            # Function-level directives: nonblocking / acquires on the def.
            for fn in self.graph.functions.values():
                if fn.path != module.path:
                    continue
                ann = self.annotations[module.path].at(fn.node.lineno)
                if ann is None:
                    continue
                if ann.nonblocking:
                    self.nonblocking.add(fn.qualname)
                if ann.acquires:
                    self.acquires_ann[fn.qualname] = ann.acquires

    def _register_class_locks(
        self, module: ParsedModule, mod: str, cls: ast.ClassDef
    ) -> None:
        cls_qual = f"{mod}:{cls.name}"
        for fn_node in cls.body:
            if not isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn_node):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                target = node.targets[0]
                if not isinstance(node.value, ast.Call):
                    continue
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    ref = self._lock_ctor_ref(
                        node.value, module, cls.name, target.attr
                    )
                    if ref is not None:
                        self.attr_locks[(cls_qual, target.attr)] = self._register(ref)
                elif isinstance(target, (ast.Subscript, ast.Name)):
                    # Dynamic-family (vfio submutexes) and annotated-local
                    # cases: registered only through their annotation, so
                    # the id's kind/family flags are known to every caller
                    # regardless of analysis order.
                    ann = self.annotations[module.path].at(node.value.lineno)
                    if ann is not None and ann.lock_id:
                        ref = self._lock_ctor_ref(node.value, module, cls.name, "?")
                        if ref is not None:
                            self._register(ref)

    # -- lock resolution ----------------------------------------------------

    def resolve_lock(
        self,
        expr: ast.AST,
        ctx: FunctionInfo,
        extra_lines: Iterable[int] = (),
    ) -> Optional[LockRef]:
        ann = self.annotations.get(ctx.path, LockAnnotations("")).at(
            getattr(expr, "lineno", 0), *extra_lines
        )
        if ann is not None and ann.lock_id:
            known = self.named.get(ann.lock_id)
            if known is not None:
                return known
            # Convention: ``flock:`` ids ARE flocks (kind decides both the
            # in-process rules and witness instrumentability).
            if ann.lock_id.startswith("flock:"):
                return self._register(
                    LockRef(ann.lock_id, "flock", family=ann.family, witnessable=True)
                )
            return self._register(
                LockRef(ann.lock_id, "lock", family=ann.family)
            )
        if isinstance(expr, ast.Name):
            ref = self._locals_of(ctx)[1].get(expr.id)
            if ref is not None:
                return ref
            return self.module_locks.get((ctx.module, expr.id))
        if isinstance(expr, ast.Attribute):
            return self._resolve_attr_lock(expr, ctx)
        if isinstance(expr, ast.Call):
            terminal = astutil.call_name(expr)
            if terminal == "Flock" and astutil.is_flockish(expr.func):
                return self._flock_ref(expr, _module_by_path(self.modules, ctx.path), ctx.name)
            # Calling a lock object: ``lock(timeout=...)`` / ``Flock(p)(t)``.
            inner = self.resolve_lock(expr.func, ctx, extra_lines)
            if inner is not None:
                return inner
            callee = self.graph.resolve_call(expr, ctx, self._locals_of(ctx)[0])
            if callee is not None:
                return self.returns_lock(callee)
        return None

    def _resolve_attr_lock(self, expr: ast.Attribute, ctx: FunctionInfo) -> Optional[LockRef]:
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id == "self" and ctx.class_name:
            ref = self.attr_locks.get((f"{ctx.module}:{ctx.class_name}", expr.attr))
            if ref is not None:
                return ref
            if self.graph.method_on(f"{ctx.module}:{ctx.class_name}", expr.attr):
                # A lock-ish NAME that is actually a method (``_pu_lock()``
                # factories) — resolution belongs to returns_lock().
                return None
            if astutil.is_lockish_name(expr.attr):
                kind = "cond" if "cond" in expr.attr.lower() else "lock"
                return self._register(
                    LockRef(
                        _derived_id(short_module(ctx.module), ctx.class_name, expr.attr),
                        kind,
                    )
                )
            return None
        if isinstance(recv, ast.Name):
            local_cls = self._locals_of(ctx)[0].get(recv.id)
            if local_cls is not None:
                return self.attr_locks.get((local_cls, expr.attr))
        return None

    def returns_lock(self, fn: FunctionInfo) -> Optional[LockRef]:
        """The lock a function returns (``_pu_lock`` factories), computed
        to full depth with a recursion-stack cycle guard — a truncated
        result is NEVER cached, or analysis order would decide whether a
        lock resolves."""
        if fn.qualname in self._returns_lock:
            return self._returns_lock[fn.qualname]
        if fn.qualname in self._returns_lock_stack:
            return None  # cycle: break without caching
        self._returns_lock_stack.add(fn.qualname)
        try:
            result: Optional[LockRef] = None
            for node in ast.walk(fn.node):
                if not (isinstance(node, ast.Return) and node.value is not None):
                    continue
                value = node.value
                if isinstance(value, ast.Call):
                    terminal = astutil.call_name(value)
                    if terminal == "Flock" and astutil.is_flockish(value.func):
                        result = self._flock_ref(
                            value, _module_by_path(self.modules, fn.path), fn.name
                        )
                        break
                    callee = self.graph.resolve_call(value, fn, self._locals_of(fn)[0])
                    if callee is not None:
                        result = self.returns_lock(callee)
                        if result is not None:
                            break
                elif isinstance(value, ast.Name):
                    result = self._locals_of(fn)[1].get(value.id)
                    if result is not None:
                        break
        finally:
            self._returns_lock_stack.discard(fn.qualname)
        self._returns_lock[fn.qualname] = result
        return result

    # -- per-function locals + IR -------------------------------------------

    def _locals_of(self, fn: FunctionInfo) -> tuple[dict[str, str], dict[str, LockRef]]:
        """(local class types, local lock refs) for one function: parameter
        annotations plus single-assignment constructor/return inference."""
        if fn.qualname in self._local_types:
            return self._local_types[fn.qualname], self._local_locks[fn.qualname]
        types: dict[str, str] = {}
        locks: dict[str, LockRef] = {}
        self._local_types[fn.qualname] = types
        self._local_locks[fn.qualname] = locks
        args = fn.node.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            qual = self.graph._annotation_class(a.annotation, fn.module)
            if qual:
                types[a.arg] = qual
        for node in ast.walk(fn.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            value = node.value
            if isinstance(value, ast.Name) and value.id in locks:
                locks[target.id] = locks[value.id]
                continue
            if not isinstance(value, ast.Call):
                continue
            terminal = astutil.call_name(value)
            if terminal in _THREADING_CTORS or terminal in _WITNESS_CTORS:
                ref = self._lock_ctor_ref(
                    value, _module_by_path(self.modules, fn.path), fn.name, target.id
                )
                if ref is not None:
                    locks[target.id] = self._register(ref)
                continue
            if terminal == "Flock" and astutil.is_flockish(value.func):
                locks[target.id] = self._flock_ref(
                    value, _module_by_path(self.modules, fn.path), fn.name
                )
                continue
            callee = self.graph.resolve_call(value, fn, types)
            if callee is not None:
                ref = self.returns_lock(callee)
                if ref is not None:
                    locks[target.id] = ref
                    continue
                cls = self.graph.class_of(callee)
                if cls is not None and callee.name == "__init__":
                    types[target.id] = cls.qualname
        return types, locks

    def ir(self, fn: FunctionInfo) -> list[Event]:
        if fn.qualname in self._ir:
            return self._ir[fn.qualname]
        self._ir[fn.qualname] = []  # recursion guard
        events = self._build_stmts(fn, fn.node.body, lexical_holds=[])
        if fn.qualname.endswith("Driver._run_effects"):
            # The effects-phase fan-out invokes a function-valued
            # ``effect`` parameter from inside a nested worker def the
            # statement walk deliberately skips — model the dispatch as
            # calling every registered effect method directly (see
            # _collect_effect_targets), so the locks effects take (the MP
            # daemon stamp's accounted apiserver write, devicelib
            # mutations) contribute edges from whatever the dispatching
            # bind holds (the partition_fault soak witnessed
            # flock:claim-uid → accounting.counts_lock exactly here).
            events.extend(CallEv(fn.node, fn=t) for t in self._effect_targets)
        self._ir[fn.qualname] = events
        return events

    def _build_stmts(
        self, fn: FunctionInfo, stmts: list, lexical_holds: list[str]
    ) -> list[Event]:
        events: list[Event] = []
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                events.extend(self._build_with(fn, stmt, lexical_holds))
                continue
            events.extend(self._build_expr_events(fn, stmt, lexical_holds))
            for body in _sub_bodies(stmt):
                events.extend(self._build_stmts(fn, body, lexical_holds))
        return events

    def _build_with(
        self, fn: FunctionInfo, stmt, lexical_holds: list[str]
    ) -> list[Event]:
        """Nested WithLock/WithCM events for one with statement; unclassified
        items contribute their context-expression calls and become
        transparent."""
        layers: list[Event] = []
        prefix: list[Event] = []
        for item in stmt.items:
            expr = item.context_expr
            ref = self.resolve_lock(expr, fn, extra_lines=(stmt.lineno,))
            if ref is not None:
                kind = astutil.withitem_lock_kind(item)
                layers.append(
                    WithLockEv(
                        ref,
                        stmt,
                        lexical=bool(kind is not None and kind[0] == "inproc"),
                    )
                )
                continue
            if isinstance(expr, ast.Call):
                callee = self.graph.resolve_call(expr, fn, self._locals_of(fn)[0])
                if callee is not None and callee.is_contextmanager:
                    layers.append(WithCMEv(callee, stmt))
                    prefix.extend(self._calls_in(fn, list(expr.args), lexical_holds))
                    continue
            prefix.extend(self._calls_in(fn, [expr], lexical_holds))
        inner_holds = lexical_holds + [
            ev.lock.id for ev in layers if isinstance(ev, WithLockEv)
        ]
        body = self._build_stmts(fn, stmt.body, inner_holds)
        for layer in reversed(layers):
            layer.body = body
            body = [layer]
        return prefix + body

    def _build_expr_events(
        self, fn: FunctionInfo, stmt, lexical_holds: list[str]
    ) -> list[Event]:
        exprs = list(_stmt_exprs(stmt))
        events = self._calls_in(fn, exprs, lexical_holds)
        for expr in exprs:
            for node in _walk_no_lambda(expr):
                if isinstance(node, (ast.Yield, ast.YieldFrom)):
                    events.append(YieldEv(node))
        return events

    def _calls_in(
        self, fn: FunctionInfo, exprs: list, lexical_holds: list[str]
    ) -> list[Event]:
        events: list[Event] = []
        calls: list[ast.Call] = []
        seen: set[int] = set()
        for expr in exprs:
            if expr is None:
                continue
            for node in _walk_no_lambda(expr):
                if isinstance(node, ast.Call) and id(node) not in seen:
                    seen.add(id(node))
                    calls.append(node)
        calls.sort(key=lambda c: (c.lineno, c.col_offset))
        types, locks = self._locals_of(fn)
        for call in calls:
            func = call.func
            terminal = astutil.call_name(call)
            # Raw acquire/release on a resolvable lock object.
            if isinstance(func, ast.Attribute) and terminal in ("acquire", "release"):
                ref = self.resolve_lock(func.value, fn)
                if ref is not None:
                    if terminal == "acquire":
                        events.append(AcqEv(ref, call))
                    else:
                        events.append(RelEv(ref, call))
                    continue
            if isinstance(func, ast.Attribute) and terminal in ("wait", "wait_for"):
                ref = self.resolve_lock(func.value, fn)
                if ref is not None:
                    events.append(
                        CallEv(
                            call,
                            wait_on=ref,
                            wait_exempt=ref.id in lexical_holds,
                        )
                    )
                    continue
            callee = self.graph.resolve_call(call, fn, types)
            if (
                callee is None
                and isinstance(func, ast.Name)
                and fn.qualname.endswith("Informer._dispatch")
            ):
                # Callback dispatch (see _collect_handler_targets): any
                # unresolved bare-name call inside the dispatch loop is
                # the handler invocation — keyed on the function, not the
                # loop variable's spelling, so a rename can't silently
                # drop the dispatch-lock edges.  Model it as calling
                # every registered handler.
                for target in self._handler_targets:
                    events.append(CallEv(call, fn=target))
                continue
            blocking = self._classify_blocking(call, callee)
            if callee is not None and self.acquires_ann.get(callee.qualname):
                held_ref = self._ref_for_id(self.acquires_ann[callee.qualname])
                events.append(CallEv(call, fn=callee, blocking=blocking))
                events.append(AcqEv(held_ref, call))
                continue
            if callee is not None or blocking:
                events.append(CallEv(call, fn=callee, blocking=blocking))
        return events

    def _classify_blocking(
        self, call: ast.Call, callee: Optional[FunctionInfo]
    ) -> str:
        if callee is not None:
            if callee.qualname in self.nonblocking:
                return ""
            if callee.qualname in self._kube_quals:
                return f"apiserver {callee.name}"
            if callee.qualname in self._flock_quals:
                return "flock-acquire"
        dotted = astutil.dotted_name(call.func)
        terminal = astutil.call_name(call)
        if terminal == "sleep":
            return "time.sleep"
        if dotted.startswith("subprocess.") or terminal == "Popen":
            return "subprocess"
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            return "open()"
        receiver_parts = dotted.lower().split(".")[:-1]
        if any("stub" in part for part in receiver_parts):
            return "gRPC stub call"
        if callee is None and terminal == "result":
            # Future.result().  (``join`` is deliberately absent: nearly
            # every ``.join`` in this tree is str.join.)
            return "blocking result()"
        if callee is None and terminal == "wait" and isinstance(call.func, ast.Attribute):
            return "blocking wait()"
        return ""

    # -- summaries ----------------------------------------------------------

    def acq_star(self, fn: FunctionInfo) -> dict[str, tuple[LockRef, str]]:
        """Every lock transitively acquired by ``fn``: id → (ref, chain).
        Full-depth with a recursion-stack cycle guard; in-progress callers
        contribute nothing but are NOT cached truncated (a depth-keyed or
        partial cache would make edges depend on analysis order)."""
        if fn.qualname in self._acq_star:
            return self._acq_star[fn.qualname]
        if fn.qualname in self._acq_star_stack:
            return {}  # cycle: break without caching
        self._acq_star_stack.add(fn.qualname)
        out: dict[str, tuple[LockRef, str]] = {}
        try:

            def visit(events: list[Event]) -> None:
                for ev in events:
                    if isinstance(ev, WithLockEv):
                        out.setdefault(ev.lock.id, (ev.lock, _label(fn)))
                        visit(ev.body)
                    elif isinstance(ev, AcqEv):
                        out.setdefault(ev.lock.id, (ev.lock, _label(fn)))
                    elif isinstance(ev, WithCMEv):
                        self._merge_star(out, ev.fn)
                        visit(ev.body)
                    elif isinstance(ev, CallEv):
                        if ev.blocking.startswith("apiserver"):
                            # Protocol dispatch the call graph cannot see:
                            # the verb may run through AccountingKube,
                            # which takes its counter mutex inside the
                            # call (ACCOUNTING_COUNTS_LOCK).
                            out.setdefault(
                                ACCOUNTING_COUNTS_LOCK,
                                (
                                    self._ref_for_id(ACCOUNTING_COUNTS_LOCK),
                                    _label(fn),
                                ),
                            )
                        if ev.fn is not None:
                            self._merge_star(out, ev.fn)

            visit(self.ir(fn))
            ann = self.acquires_ann.get(fn.qualname)
            if ann is not None and ann not in out:
                out[ann] = (self._ref_for_id(ann), _label(fn))
        finally:
            self._acq_star_stack.discard(fn.qualname)
        self._acq_star[fn.qualname] = out
        return out

    def _merge_star(
        self, out: dict[str, tuple[LockRef, str]], callee: FunctionInfo
    ) -> None:
        for lock_id, (ref, chain) in self.acq_star(callee).items():
            out.setdefault(lock_id, (ref, f"{_label(callee)} ← {chain}" if chain != _label(callee) else chain))

    def block_star(self, fn: FunctionInfo, depth: int) -> list[tuple[str, str, int, str]]:
        """Blocking operations reachable within ``depth`` calls:
        (label, path, line, chain).  Stops at flock bodies — the flock
        acquire itself is the reported operation there."""
        key = (fn.qualname, depth)
        if key in self._block_star:
            return self._block_star[key]
        self._block_star[key] = []  # recursion guard
        out: list[tuple[str, str, int, str]] = []

        def visit(events: list[Event]) -> None:
            for ev in events:
                if isinstance(ev, WithLockEv):
                    if ev.lock.kind == "flock":
                        out.append(
                            (
                                f"flock-acquire '{ev.lock.id}'",
                                fn.path,
                                ev.node.lineno,
                                _label(fn),
                            )
                        )
                        continue  # contents attributed to the flock acquire
                    visit(ev.body)
                elif isinstance(ev, AcqEv):
                    if ev.lock.kind == "flock":
                        out.append(
                            (
                                f"flock-acquire '{ev.lock.id}'",
                                fn.path,
                                ev.node.lineno,
                                _label(fn),
                            )
                        )
                elif isinstance(ev, WithCMEv):
                    self._merge_block(out, ev.fn, depth)
                    visit(ev.body)
                elif isinstance(ev, CallEv):
                    if ev.wait_on is not None:
                        if not ev.wait_exempt:
                            out.append(
                                (
                                    f"wait on '{ev.wait_on.id}'",
                                    fn.path,
                                    ev.node.lineno,
                                    _label(fn),
                                )
                            )
                        continue
                    if ev.blocking:
                        out.append((ev.blocking, fn.path, ev.node.lineno, _label(fn)))
                        continue
                    if ev.fn is not None:
                        self._merge_block(out, ev.fn, depth)

        visit(self.ir(fn))
        self._block_star[key] = out
        return out

    def _merge_block(self, out: list, callee: FunctionInfo, depth: int) -> None:
        if depth <= 1 or callee.qualname in self.nonblocking:
            return
        for label, path, line, chain in self.block_star(callee, depth - 1):
            out.append((label, path, line, f"{_label(callee)}: {chain}" if chain != _label(callee) else chain))

    def cm_yield(self, fn: FunctionInfo) -> list[LockRef]:
        """Locks held at a contextmanager function's yield — what the
        ``with`` body of its callers executes under."""
        if fn.qualname in self._cm_yield:
            return self._cm_yield[fn.qualname]
        self._cm_yield[fn.qualname] = []  # recursion guard
        found: list[LockRef] = []

        def visit(events: list[Event], held: list[LockRef]) -> bool:
            tail: list[LockRef] = []
            for ev in events:
                if isinstance(ev, YieldEv):
                    found.extend(held + tail)
                    return True
                if isinstance(ev, AcqEv):
                    tail.append(ev.lock)
                elif isinstance(ev, RelEv):
                    for i in range(len(tail) - 1, -1, -1):
                        if tail[i].id == ev.lock.id:
                            del tail[i]
                            break
                elif isinstance(ev, WithLockEv):
                    if visit(ev.body, held + tail + [ev.lock]):
                        return True
                elif isinstance(ev, WithCMEv):
                    if visit(ev.body, held + tail + self.cm_yield(ev.fn)):
                        return True
            return False

        visit(self.ir(fn), [])
        self._cm_yield[fn.qualname] = found
        return found


def _module_of(module: ParsedModule) -> str:
    from tpudra.analysis.callgraph import module_name

    return module_name(module.path)


def _module_by_path(modules: list[ParsedModule], path: str) -> ParsedModule:
    for m in modules:
        if m.path == path:
            return m
    raise KeyError(path)


def _label(fn: FunctionInfo) -> str:
    return f"{fn.class_name}.{fn.name}" if fn.class_name else fn.name


def _derived_id(mod_short: str, owner: str, attr: str) -> str:
    if owner:
        return f"{mod_short}.{owner}.{attr}"
    return f"{mod_short}.{attr}"


def _sub_bodies(stmt) -> list[list]:
    out = []
    for name in ("body", "orelse", "finalbody"):
        body = getattr(stmt, name, None)
        if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
            out.append(body)
    for handler in getattr(stmt, "handlers", []) or []:
        out.append(handler.body)
    return out


def _stmt_exprs(stmt) -> Iterable[ast.AST]:
    """Expression children of one statement (not its nested statements)."""
    for name, value in ast.iter_fields(stmt):
        if name in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for item in value:
                if isinstance(item, ast.expr):
                    yield item


def _walk_no_lambda(root: ast.AST):
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            stack.append(child)


# ------------------------------------------------------------ the full pass


class LockGraphAnalysis:
    """Runs held-set propagation over every function and derives the
    acquisition graph plus the three rule finding sets."""

    def __init__(
        self,
        modules: list[ParsedModule],
        graph: Optional[CallGraph] = None,
        model: Optional[LockModel] = None,
    ):
        self.model = model or LockModel(modules, graph)
        self.edges: dict[tuple[str, str], Edge] = {}
        self.locks: dict[str, LockRef] = {}
        self.block_findings: list[Finding] = []
        self.inversion_findings: list[Finding] = []
        self._seen_findings: set[tuple] = set()

    def run(self) -> LockGraphResult:
        for fn in list(self.model.graph.functions.values()):
            self._scan(fn)
        for ref in self.model.named.values():
            self.locks.setdefault(ref.id, ref)
        findings = self.block_findings + self.inversion_findings + self._cycle_findings()
        return LockGraphResult(locks=self.locks, edges=self.edges, findings=findings)

    # -- edges --------------------------------------------------------------

    def _note_lock(self, ref: LockRef) -> None:
        prev = self.locks.get(ref.id)
        if prev is None or (not prev.defined_at and ref.defined_at):
            self.locks[ref.id] = ref

    def _add_edge(
        self, src: LockRef, dst: LockRef, path: str, node, chain: str
    ) -> None:
        self._note_lock(src)
        self._note_lock(dst)
        if src.id == dst.id:
            if src.reentrant or src.family:
                return
        key = (src.id, dst.id)
        if key not in self.edges:
            self.edges[key] = Edge(
                src, dst, path, getattr(node, "lineno", 1), chain
            )

    # -- held-set walk ------------------------------------------------------

    def _scan(self, fn: FunctionInfo) -> None:
        self._walk(fn, self.model.ir(fn), held=[], lex_depth=0)

    def _walk(
        self, fn: FunctionInfo, events: list[Event], held: list[LockRef], lex_depth: int
    ) -> None:
        tail: list[LockRef] = []

        def current() -> list[LockRef]:
            return held + tail

        for ev in events:
            if isinstance(ev, WithLockEv):
                self._on_acquire(fn, ev.lock, ev.node, current())
                nested_lex = lex_depth + (
                    1 if ev.lexical and ev.lock.in_process else 0
                )
                self._walk(fn, ev.body, current() + [ev.lock], nested_lex)
            elif isinstance(ev, AcqEv):
                self._on_acquire(fn, ev.lock, ev.node, current())
                tail.append(ev.lock)
            elif isinstance(ev, RelEv):
                for i in range(len(tail) - 1, -1, -1):
                    if tail[i].id == ev.lock.id:
                        del tail[i]
                        break
            elif isinstance(ev, WithCMEv):
                self._on_call(fn, ev.fn, ev.node, current())
                self._walk(
                    fn, ev.body, current() + self.model.cm_yield(ev.fn), lex_depth
                )
            elif isinstance(ev, CallEv):
                if ev.wait_on is not None:
                    self._on_wait(fn, ev, current())
                    continue
                if ev.blocking:
                    self._on_direct_blocking(fn, ev, current(), lex_depth)
                    if ev.blocking.startswith("apiserver") and current():
                        # Protocol dispatch the graph can't resolve: the
                        # verb may run through AccountingKube, which takes
                        # its counter mutex inside the call (see
                        # ACCOUNTING_COUNTS_LOCK).
                        counts = self.model._ref_for_id(ACCOUNTING_COUNTS_LOCK)
                        for h in current():
                            self._add_edge(
                                h, counts, fn.path, ev.node,
                                f"{_label(fn)} → AccountingKube._count",
                            )
                if ev.fn is not None:
                    # A blocking-terminal callee (kube verb, Flock.acquire)
                    # was already reported whole; don't descend for more.
                    self._on_call(
                        fn, ev.fn, ev.node, current(), skip_block=bool(ev.blocking)
                    )

    def _on_acquire(
        self, fn: FunctionInfo, lock: LockRef, node, held: list[LockRef]
    ) -> None:
        self._note_lock(lock)
        for h in held:
            self._add_edge(h, lock, fn.path, node, _label(fn))
        if lock.kind == "flock":
            holder = _innermost_in_process(held)
            if holder is not None:
                self._report_inversion(fn, node, holder, lock, _label(fn))

    def _on_wait(self, fn: FunctionInfo, ev: CallEv, held: list[LockRef]) -> None:
        assert ev.wait_on is not None
        others = [h for h in held if h.id != ev.wait_on.id and h.in_process]
        if not others or ev.wait_exempt:
            return
        self._report_block(
            fn,
            ev.node,
            others[-1],
            f"wait on '{ev.wait_on.id}'",
            _label(fn),
        )

    def _on_direct_blocking(
        self, fn: FunctionInfo, ev: CallEv, held: list[LockRef], lex_depth: int
    ) -> None:
        holder = _innermost_in_process(held)
        if holder is None:
            return
        if ev.blocking == "flock-acquire":
            self._report_inversion(fn, ev.node, holder, None, _label(fn))
            return
        if ev.blocking in _LEXICAL_CATEGORIES and lex_depth > 0:
            return  # the lexical BLOCK-UNDER-LOCK rule owns this offense
        self._report_block(fn, ev.node, holder, ev.blocking, _label(fn))

    def _on_call(
        self,
        fn: FunctionInfo,
        callee: FunctionInfo,
        node,
        held: list[LockRef],
        skip_block: bool = False,
    ) -> None:
        if held:
            for lock_id, (ref, chain) in self.model.acq_star(callee).items():
                for h in held:
                    self._add_edge(
                        h, ref, fn.path, node, f"{_label(fn)} → {chain}"
                    )
        holder = _innermost_in_process(held)
        if holder is None or skip_block or callee.qualname in self.model.nonblocking:
            return
        for label, bpath, bline, chain in self.model.block_star(
            callee, MAX_BLOCK_DEPTH
        ):
            where = f"{_label(fn)} → {chain} ({_rel(bpath)}:{bline})"
            if label.startswith("flock-acquire"):
                flock_id = label.partition("'")[2].rstrip("'") or None
                ref = self.locks.get(flock_id) if flock_id else None
                if ref is None and flock_id:
                    ref = LockRef(flock_id, "flock")
                self._report_inversion(fn, node, holder, ref, where)
                continue
            self._report_block(fn, node, holder, label, where)

    # -- findings -----------------------------------------------------------

    def _report_block(
        self, fn: FunctionInfo, node, holder: LockRef, label: str, chain: str
    ) -> None:
        key = ("BLOCK-UNDER-LOCK-IP", fn.path, getattr(node, "lineno", 1), holder.id, label)
        if key in self._seen_findings:
            return
        self._seen_findings.add(key)
        self.block_findings.append(
            _finding(
                "BLOCK-UNDER-LOCK-IP",
                fn.path,
                node,
                f"{label} reachable while holding in-process lock "
                f"'{holder.id}' (via {chain}) — blocking work must leave "
                "the critical section",
            )
        )

    def _report_inversion(
        self,
        fn: FunctionInfo,
        node,
        holder: LockRef,
        flock: Optional[LockRef],
        chain: str,
    ) -> None:
        flock_id = flock.id if flock is not None else "a flock"
        key = ("FLOCK-INVERSION", fn.path, getattr(node, "lineno", 1), holder.id, flock_id)
        if key in self._seen_findings:
            return
        self._seen_findings.add(key)
        self.inversion_findings.append(
            _finding(
                "FLOCK-INVERSION",
                fn.path,
                node,
                f"cross-process flock '{flock_id}' acquired while holding "
                f"in-process lock '{holder.id}' (via {chain}) — an "
                "in-process lock must never wait on a flock: a sibling "
                "process holding the flock and wanting the in-process "
                "critical section wedges the node",
            )
        )

    def _cycle_findings(self) -> list[Finding]:
        adj: dict[str, list[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        for dsts in adj.values():
            dsts.sort()
        out: list[Finding] = []
        for cycle in _find_cycles(adj):
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            parts = []
            for a, b in pairs:
                e = self.edges[(a, b)]
                parts.append(f"{a} → {b} (in {e.chain}, {_rel(e.path)}:{e.line})")
            anchor = self.edges[pairs[0]]
            out.append(
                Finding(
                    path=anchor.path,
                    line=anchor.line,
                    col=0,
                    rule_id="LOCK-CYCLE",
                    message=(
                        "lock acquisition cycle — a static deadlock candidate: "
                        + "; ".join(parts)
                    ),
                )
            )
        return out


def _innermost_in_process(held: list[LockRef]) -> Optional[LockRef]:
    for h in reversed(held):
        if h.in_process:
            return h
    return None


def _find_cycles(adj: dict[str, list[str]]) -> list[list[str]]:
    """One representative simple cycle per strongly connected component
    (plus self-loops), deterministically ordered."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(adj.get(v, [])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj.get(w, []))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(sorted(comp))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    cycles: list[list[str]] = []
    for comp in sorted(sccs):
        if len(comp) == 1:
            v = comp[0]
            if v in adj.get(v, []):
                cycles.append([v])
            continue
        # Deterministic representative cycle: DFS within the component from
        # its smallest node back to itself.
        start = comp[0]
        comp_set = set(comp)
        path = [start]
        seen = {start}

        def dfs(node: str) -> bool:
            for w in adj.get(node, []):
                if w == start and len(path) > 1:
                    return True
                if w in comp_set and w not in seen:
                    seen.add(w)
                    path.append(w)
                    if dfs(w):
                        return True
                    path.pop()
            return False

        if dfs(start):
            cycles.append(path)
    return cycles


def analyze_modules(
    modules: list[ParsedModule],
    graph: Optional[CallGraph] = None,
    model: Optional[LockModel] = None,
) -> LockGraphResult:
    return LockGraphAnalysis(modules, graph, model).run()

"""RMW-PURITY: callables passed to ``CheckpointManager.mutate`` stay pure.

docs/bind-path.md's batched-RMW protocol: the mutator runs under the
``cp.lock`` flock, inside the two per-batch critical sections that every
other driver process serializes on.  Side effects belong in phase 2
(effects, outside every lock) — a partition create, a CDI write, a daemon
start, or a kube call inside the mutator stretches the node-wide critical
section by its whole latency AND breaks crash convergence (the crash-sweep
contract is that effects are covered by a durable record written *before*
they run, which an effect inside the RMW is not).

The check is depth-limited interprocedural: the mutator's body is scanned,
plus (up to 3 calls deep) any ``self.X(...)``/``X(...)`` callee defined in
the same module — ``start_all`` delegating to ``_start_one`` is still
covered.  Cross-module helpers are matched by name only.
"""

from __future__ import annotations

import ast
from typing import Optional, Union

from tpudra.analysis import astutil
from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule

_MAX_DEPTH = 3

#: Terminal call names that are side effects, grouped for the message.
_FORBIDDEN = {
    # hardware mutation
    "create_partition": "partition create",
    "delete_partition": "partition delete",
    "set_timeslice": "timeslice mutation",
    "set_exclusive": "exclusive-mode mutation",
    "configure": "vfio configure",
    "unconfigure": "vfio unconfigure",
    # CDI spec files
    "create_claim_spec_file": "CDI spec write",
    "delete_claim_spec_file": "CDI spec delete",
    "_write_cdi_spec": "CDI spec write",
    # sharing-daemon lifecycle
    "new_daemon": "daemon creation",
    "assert_ready": "daemon readiness wait",
    "start": "lifecycle start",
    "stop": "lifecycle stop",
    "restart": "lifecycle restart",
    # kube / network
    "publish_slices": "ResourceSlice publication",
    "remove_node_label": "kube node-label write",
    "add_node_label": "kube node-label write",
    "cleanup_daemon_settings": "daemon-settings teardown",
    # blocking / filesystem
    "sleep": "sleep",
    # nested RMW deadlocks on cp.lock
    "mutate": "nested checkpoint RMW",
}

#: os-level filesystem mutations (matched as ``os.X`` only, so a domain
#: method named ``replace`` does not trip the rule).
_OS_EFFECTS = {"replace", "unlink", "makedirs", "rmdir", "remove", "rename"}


def _forbidden_reason(call: ast.Call) -> str:
    dotted = astutil.dotted_name(call.func)
    terminal = astutil.call_name(call)
    if terminal in _FORBIDDEN:
        return _FORBIDDEN[terminal]
    if dotted.startswith("subprocess.") or terminal == "Popen":
        return "subprocess"
    if isinstance(call.func, ast.Name) and call.func.id == "open":
        return "file I/O"
    if dotted.startswith("os.") and terminal in _OS_EFFECTS:
        return "filesystem mutation"
    receiver = dotted.lower().split(".")[:-1]
    if any("stub" in part for part in receiver):
        return "gRPC call"
    if any(part in ("kube", "_kube") for part in receiver):
        return "kube API call"
    return ""


class RmwPurity(Rule):
    rule_id = "RMW-PURITY"
    description = (
        "callables passed to CheckpointManager.mutate must not run side "
        "effects (CDI, partitions, daemons, kube, filesystem, sleep)"
    )

    def check_module(self, module: ParsedModule) -> list[Finding]:
        functions = astutil.collect_functions(module.tree)
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and astutil.call_name(node) == "mutate"):
                continue
            mutator = self._mutator_arg(node)
            if mutator is None:
                continue
            target = self._resolve(mutator, functions)
            if target is None:
                continue
            label = getattr(target, "name", "<lambda>")
            out.extend(
                self._scan(module, target, functions, chain=[label], visited=set())
            )
        return out

    @staticmethod
    def _mutator_arg(call: ast.Call) -> Optional[ast.expr]:
        if call.args:
            return call.args[0]
        for kw in call.keywords:
            if kw.arg in ("fn", "func", "mutator"):
                return kw.value
        return None

    @staticmethod
    def _resolve(
        expr: ast.expr, functions: dict[str, ast.FunctionDef]
    ) -> Optional[Union[ast.FunctionDef, ast.Lambda]]:
        if isinstance(expr, ast.Lambda):
            return expr
        name = ""
        if isinstance(expr, ast.Name):
            name = expr.id
        elif astutil.self_attr_target(expr) is not None:
            name = expr.attr
        return functions.get(name)

    def _scan(
        self,
        module: ParsedModule,
        fn: Union[ast.FunctionDef, ast.Lambda],
        functions: dict[str, ast.FunctionDef],
        chain: list[str],
        visited: set[str],
    ) -> list[Finding]:
        out: list[Finding] = []
        body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
        for sub in astutil.walk_body_shallow(body):
            if not isinstance(sub, ast.Call):
                continue
            reason = _forbidden_reason(sub)
            if reason:
                where = " → ".join(chain)
                out.append(
                    self.finding(
                        module, sub,
                        f"mutator {where} performs {reason} "
                        f"('{astutil.dotted_name(sub.func)}') inside the "
                        "checkpoint RMW — side effects belong in the effects "
                        "phase (docs/bind-path.md)",
                    )
                )
                continue
            if len(chain) >= _MAX_DEPTH:
                continue
            callee_name = ""
            if isinstance(sub.func, ast.Name):
                callee_name = sub.func.id
            elif astutil.self_attr_target(sub.func) is not None:
                callee_name = sub.func.attr
            callee = functions.get(callee_name)
            if callee is not None and callee_name not in visited:
                visited.add(callee_name)
                out.extend(
                    self._scan(
                        module, callee, functions, chain + [callee_name], visited
                    )
                )
        return out

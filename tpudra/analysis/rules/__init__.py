"""The tpudra-lint rule set.

Each rule is a class with a stable ``rule_id`` (the suppression and
documentation handle), a one-line ``description`` (``--list-rules``), a
``check_module`` hook, and an optional ``finalize`` hook for cross-file
checks.  Rules are instantiated fresh per run (engine.py) so cross-file
state never leaks.  Rationale per rule: docs/static-analysis.md.
"""

from __future__ import annotations

from tpudra.analysis.engine import Finding, ParsedModule


class Rule:
    rule_id: str = ""
    description: str = ""

    def check_module(self, module: ParsedModule) -> list[Finding]:
        raise NotImplementedError

    def finalize(self) -> list[Finding]:
        return []

    def finding(self, module: ParsedModule, node, message: str) -> Finding:
        return Finding(
            path=module.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            rule_id=self.rule_id,
            message=message,
        )


def all_rules() -> list[Rule]:
    from tpudra.analysis.rules.apiserver_retry import ApiserverRetry
    from tpudra.analysis.rules.durable_write import DurableWrite
    from tpudra.analysis.rules.effectgraph import (
        EffectgraphState,
        FenceDominatesCommit,
        StripeOrder,
        WalIntentBeforeEffect,
        WalRecoveryExhaustive,
    )
    from tpudra.analysis.rules.exc_swallow import ExcSwallow
    from tpudra.analysis.rules.lockgraph import (
        BlockUnderLockIP,
        FlockInversion,
        LockCycle,
        LockgraphState,
    )
    from tpudra.analysis.rules.locks import BlockUnderLock, LockOrder
    from tpudra.analysis.rules.metrics_hygiene import MetricsHygiene
    from tpudra.analysis.rules.partition_phase import PartitionPhase
    from tpudra.analysis.rules.program import ProgramState
    from tpudra.analysis.rules.racegraph import (
        GuardConsistency,
        Race,
        RacegraphState,
        ThreadConfinedEscape,
    )
    from tpudra.analysis.rules.rmw_purity import RmwPurity
    from tpudra.analysis.rules.span_hygiene import SpanHygiene

    # The whole-program rule families each share ONE analysis per run,
    # and all analyses share ONE CallGraph (and the lock/race pair one
    # LockModel) over the same corpus.
    program = ProgramState()
    lockgraph = LockgraphState(program)
    effectgraph = EffectgraphState(program)
    racegraph = RacegraphState(program)
    return [
        LockOrder(),
        BlockUnderLock(),
        RmwPurity(),
        MetricsHygiene(),
        ExcSwallow(),
        SpanHygiene(),
        DurableWrite(),
        PartitionPhase(),
        ApiserverRetry(),
        LockCycle(lockgraph),
        BlockUnderLockIP(lockgraph),
        FlockInversion(lockgraph),
        WalIntentBeforeEffect(effectgraph),
        WalRecoveryExhaustive(effectgraph),
        FenceDominatesCommit(effectgraph),
        StripeOrder(effectgraph),
        Race(racegraph),
        GuardConsistency(racegraph),
        ThreadConfinedEscape(racegraph),
    ]


def lockgraph_rules() -> list[Rule]:
    """Just the whole-program lock rules (the ``make lockgraph`` lane)."""
    from tpudra.analysis.rules.lockgraph import (
        BlockUnderLockIP,
        FlockInversion,
        LockCycle,
        LockgraphState,
    )

    state = LockgraphState()
    return [LockCycle(state), BlockUnderLockIP(state), FlockInversion(state)]


def effectgraph_rules() -> list[Rule]:
    """Just the whole-program WAL rules (the ``make effectgraph`` lane)."""
    from tpudra.analysis.rules.effectgraph import (
        EffectgraphState,
        FenceDominatesCommit,
        StripeOrder,
        WalIntentBeforeEffect,
        WalRecoveryExhaustive,
    )

    state = EffectgraphState()
    return [
        WalIntentBeforeEffect(state),
        WalRecoveryExhaustive(state),
        FenceDominatesCommit(state),
        StripeOrder(state),
    ]


def racegraph_rules() -> list[Rule]:
    """Just the whole-program race rules (the ``make racegraph`` lane)."""
    from tpudra.analysis.rules.racegraph import (
        GuardConsistency,
        Race,
        RacegraphState,
        ThreadConfinedEscape,
    )

    state = RacegraphState()
    return [Race(state), GuardConsistency(state), ThreadConfinedEscape(state)]

"""SHARED-STATE: instance attributes written from a spawned thread AND
from plain methods, with neither write under a lock.

The Python analog of the Go race detector's most common catch in the
reference driver: a worker submitted to a pool (or a ``threading.Thread``
target) assigning ``self.x`` that a lock-free method also assigns.  The
GIL makes single bytecodes atomic, not read-modify-write sequences — and
even where it would save you, relying on it is the kind of invariant this
linter exists to make explicit.

Scope is deliberately narrow to stay precise with no type information:

- only ``self.attr`` targets (locals and item attributes are per-task);
- only functions reachable as a ``submit(...)`` first argument or a
  ``Thread(target=...)`` within the class (nested defs and ``self.X``
  methods resolve; anything else is out of reach); methods a threaded
  function calls via ``self.X()`` fold into the threaded set transitively
  — they run on that thread, not the main one.  A method called from both
  sides folds into the threaded set (the rule errs toward silence, not
  noise; the race detector analog is best-effort too);
- ``__init__`` writes are exempt (construction happens before threads);
- a write inside any in-process-lock ``with`` body counts as guarded, and
  the rule only fires when BOTH sides are unguarded.
"""

from __future__ import annotations

import ast
from typing import Optional

from tpudra.analysis import astutil
from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule


def _assignment_targets(node: ast.AST) -> list[ast.expr]:
    if isinstance(node, ast.Assign):
        return node.targets
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    return []


def _unguarded_self_writes(fn: ast.AST, include_nested: bool) -> dict[str, int]:
    """attr → first line of a ``self.attr`` write not under a lock with.

    ``include_nested`` is True when scanning a threaded entry function (a
    closure it defines runs on that same thread) and False when scanning a
    plain method — a nested def there does not execute when the method
    does; if it is handed to a pool, the threaded-entry resolution already
    attributes its writes to the thread side."""

    writes: dict[str, int] = {}

    def visit(node: ast.AST, guarded: bool) -> None:
        if not include_nested and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            holds = any(
                (k := astutil.withitem_lock_kind(i)) is not None and k[0] == "inproc"
                for i in node.items
            )
            for child in node.body:
                visit(child, guarded or holds)
            return
        for target in _assignment_targets(node):
            attr = astutil.self_attr_target(target)
            if attr is not None and not guarded:
                writes.setdefault(attr, node.lineno)
        for child in ast.iter_child_nodes(node):
            visit(child, guarded)

    for stmt in fn.body:
        visit(stmt, False)
    return writes


class SharedState(Rule):
    rule_id = "SHARED-STATE"
    description = (
        "self attributes assigned from both threaded functions and "
        "lock-free methods of the same class without a guard"
    )

    def check_module(self, module: ParsedModule) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(module, node))
        return out

    def _check_class(self, module: ParsedModule, cls: ast.ClassDef) -> list[Finding]:
        methods = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        nested: dict[str, ast.FunctionDef] = {}
        for m in methods.values():
            for sub in ast.walk(m):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) and sub is not m:
                    nested[sub.name] = sub

        threaded: dict[str, ast.AST] = {}
        for m in methods.values():
            for call in astutil.iter_calls(m):
                target = self._thread_entry(call)
                if target is None:
                    continue
                fn = self._resolve(target, methods, nested)
                if fn is not None:
                    threaded[fn.name] = fn
        if not threaded:
            return []
        # A method invoked as self.X() from a threaded function runs on that
        # same thread — fold it (transitively, to a fixpoint) into the
        # threaded set rather than mistaking it for a main-thread writer.
        frontier = list(threaded.values())
        while frontier:
            fn = frontier.pop()
            for call in astutil.iter_calls(fn):
                attr = astutil.self_attr_target(call.func)
                callee = methods.get(attr) if attr else None
                if callee is not None and callee.name not in threaded:
                    threaded[callee.name] = callee
                    frontier.append(callee)

        threaded_writes: dict[str, tuple[int, str]] = {}
        for name, fn in threaded.items():
            for attr, line in _unguarded_self_writes(fn, include_nested=True).items():
                threaded_writes.setdefault(attr, (line, name))

        out: list[Finding] = []
        for name, m in methods.items():
            if name == "__init__" or name in threaded:
                continue
            for attr, line in _unguarded_self_writes(m, include_nested=False).items():
                if attr not in threaded_writes:
                    continue
                tline, tname = threaded_writes[attr]
                out.append(
                    Finding(
                        path=module.path,
                        line=tline,
                        col=0,
                        rule_id=self.rule_id,
                        message=(
                            f"self.{attr} assigned in threaded function "
                            f"'{tname}' (line {tline}) and in method "
                            f"'{name}' (line {line}) with neither write "
                            "under a lock — guard both or confine the "
                            "attribute to one thread"
                        ),
                    )
                )
        return out

    @staticmethod
    def _thread_entry(call: ast.Call) -> Optional[ast.expr]:
        """The function expression a call hands to another thread:
        ``pool.submit(f, ...)`` or ``Thread(target=f)``."""
        name = astutil.call_name(call)
        if name == "submit" and call.args:
            return call.args[0]
        if name == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    return kw.value
        return None

    @staticmethod
    def _resolve(
        expr: ast.expr,
        methods: dict[str, ast.FunctionDef],
        nested: dict[str, ast.FunctionDef],
    ) -> Optional[ast.FunctionDef]:
        if isinstance(expr, ast.Name):
            return nested.get(expr.id) or methods.get(expr.id)
        attr = astutil.self_attr_target(expr)
        if attr is not None:
            return methods.get(attr)
        return None

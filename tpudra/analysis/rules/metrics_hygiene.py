"""METRICS-HYGIENE: the Prometheus surface stays coherent.

Every metric the driver exports: named ``tpudra_*`` (one grep finds the
whole surface, dashboards never collide with another exporter), declared
at module level of ``metrics.py`` (prometheus_client registers globally
at construction — a constructor inside a function re-registers on second
call and raises ``Duplicated timeseries``), and registered exactly once
across the tree.

Only constructors actually imported from ``prometheus_client`` count, so
``collections.Counter`` never trips the rule.
"""

from __future__ import annotations

import ast
import os

from tpudra.analysis import astutil
from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule

_CONSTRUCTORS = {"Counter", "Gauge", "Histogram", "Summary", "Info", "Enum"}
_METRICS_BASENAME = "metrics.py"
_PREFIX = "tpudra_"


def _prometheus_names(tree: ast.Module) -> set[str]:
    """Local names bound to prometheus_client constructors in this module
    (handles ``from prometheus_client import Counter as C``)."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "prometheus_client":
            for alias in node.names:
                if alias.name in _CONSTRUCTORS:
                    names.add(alias.asname or alias.name)
    return names


class MetricsHygiene(Rule):
    rule_id = "METRICS-HYGIENE"
    description = (
        "prometheus metrics are tpudra_*-named literals, module-level in "
        "metrics.py, registered exactly once"
    )

    def __init__(self) -> None:
        #: metric name → (path, line) of its first registration.
        self._registered: dict[str, tuple[str, int]] = {}

    def check_module(self, module: ParsedModule) -> list[Finding]:
        local = _prometheus_names(module.tree)
        dotted_ok = {f"prometheus_client.{c}" for c in _CONSTRUCTORS}
        nested_ids = {
            id(sub)
            for node in ast.walk(module.tree)
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
            )
            for sub in ast.walk(node)
            if sub is not node
        }
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_ctor = (
                isinstance(func, ast.Name) and func.id in local
            ) or astutil.dotted_name(func) in dotted_ok
            if not is_ctor:
                continue
            out.extend(self._check_ctor(module, node, id(node) not in nested_ids))
        return out

    def _check_ctor(
        self, module: ParsedModule, call: ast.Call, at_module_level: bool
    ) -> list[Finding]:
        out: list[Finding] = []
        if os.path.basename(module.path) != _METRICS_BASENAME:
            out.append(
                self.finding(
                    module, call,
                    "prometheus metric constructed outside metrics.py — "
                    "all metric families live in tpudra/metrics.py so the "
                    "export surface is one file",
                )
            )
        elif not at_module_level:
            out.append(
                self.finding(
                    module, call,
                    "prometheus metric constructed inside a function/class — "
                    "constructors register globally; a second call raises "
                    "'Duplicated timeseries'. Declare at module level",
                )
            )
        name_arg = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg == "name":
                name_arg = kw.value
        if not (isinstance(name_arg, ast.Constant) and isinstance(name_arg.value, str)):
            out.append(
                self.finding(
                    module, call,
                    "metric name must be a string literal (greppable, "
                    "checkable); computed names hide the export surface",
                )
            )
            return out
        name = name_arg.value
        if not name.startswith(_PREFIX):
            out.append(
                self.finding(
                    module, call,
                    f"metric '{name}' does not start with '{_PREFIX}' — every "
                    "exported family carries the driver prefix",
                )
            )
        first = self._registered.get(name)
        if first is not None:
            out.append(
                self.finding(
                    module, call,
                    f"metric '{name}' already registered at "
                    f"{first[0]}:{first[1]} — prometheus_client raises "
                    "'Duplicated timeseries' on the second registration",
                )
            )
        else:
            self._registered[name] = (module.path, call.lineno)
        return out

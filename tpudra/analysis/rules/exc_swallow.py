"""EXC-SWALLOW: no silent broad excepts.

A ``except:``/``except Exception:`` whose body is only ``pass`` (or a
bare constant) swallows everything including the bugs this repo's
prepare/unprepare convergence story depends on surfacing — a claim whose
teardown half-fails silently is exactly the leak the checkpoint protocol
exists to prevent.  ``contextlib.suppress(Exception)`` is the same
construct in a trench coat.

Narrow, typed suppression (``except DeviceLibError: pass`` with a comment
saying why already-gone is fine) does not trip the rule; neither does a
broad except that logs or re-raises.  Where a broad swallow really is the
design (best-effort cleanup on an exit path), say so with
``# tpudra-lint: disable=EXC-SWALLOW <why>``.
"""

from __future__ import annotations

import ast

from tpudra.analysis import astutil
from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule

_BROAD = {"Exception", "BaseException"}


def _is_broad(exc_type: ast.expr | None) -> bool:
    if exc_type is None:
        return True  # bare except
    if isinstance(exc_type, ast.Name):
        return exc_type.id in _BROAD
    if isinstance(exc_type, ast.Tuple):
        return any(_is_broad(e) for e in exc_type.elts)
    return False


def _swallows(body: list[ast.stmt]) -> bool:
    """True when the handler body does nothing observable: only ``pass``,
    ``...``, or bare constants (a docstring-style comment)."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


class ExcSwallow(Rule):
    rule_id = "EXC-SWALLOW"
    description = "no bare/broad 'except: pass' (or suppress(Exception))"

    def check_module(self, module: ParsedModule) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ExceptHandler):
                if _is_broad(node.type) and _swallows(node.body):
                    what = (
                        "bare except" if node.type is None
                        else f"except {astutil.dotted_name(node.type)}"
                    )
                    out.append(
                        self.finding(
                            module, node,
                            f"{what} swallows every error silently — log it, "
                            "narrow the type, or suppress with a stated reason",
                        )
                    )
            elif isinstance(node, ast.Call) and astutil.call_name(node) == "suppress":
                if any(
                    isinstance(a, ast.Name) and a.id in _BROAD for a in node.args
                ):
                    out.append(
                        self.finding(
                            module, node,
                            "contextlib.suppress(Exception) swallows every "
                            "error silently — narrow it or handle and log",
                        )
                    )
        return out

"""DURABLE-WRITE: persistence-layer writes go through the storage seam.

The plugins' crash-safety contracts (fail-stop fsync poisoning, the
tmp-fsync → rename → dir-fsync atomic idiom, degraded-mode detection,
disk-fault injection — docs/bind-path.md "Storage fault contract") only
hold for bytes that travel through ``tpudra/storage.py``.  A new call
site that writes a checkpoint/CDI-adjacent file with raw ``open(...,
"w")`` or ``os.replace`` silently opts out of all of it: the chaos soak's
``disk_fault`` kind cannot fail it, a crashed rename can lose it, and a
failed fsync on it goes unnoticed — exactly how the pre-seam CDI spec
write lost acknowledged grants.

So, in the persistence modules (scope below), the raw durable-write
primitives — write-mode builtin ``open``, ``os.open``/``os.write``/
``os.fsync``/``os.replace``/``os.rename``/``os.ftruncate`` — are
findings; route the write through ``storage.atomic_replace`` /
``storage.write_file`` / the fd ops instead.  Read-mode ``open`` and
stat-family calls are untouched (the degraded-mode contract keeps read
paths alive and un-seamed).  Deliberate exceptions carry a reasoned
suppression: the in-place ``/etc/hosts`` rewrite (rename onto a
bind-mount target fails EBUSY) and sysfs attribute stores (in-kernel
control writes with nothing to make durable).

Scope is the module list, not the whole tree: trace/lockwitness logs, the
mock devicelib's simulated silicon, and report sinks are measurement
apparatus whose durability is not load-bearing, and dragging them through
the seam would only manufacture suppression noise.
"""

from __future__ import annotations

import ast
import os

from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule

#: The persistence layer: everything the plugins' crash-safety story
#: depends on.  (cddaemon/coordproxy.py is deliberately out of scope: its
#: registration files are liveness-probed and rewritten on a cadence, so
#: crash durability is not load-bearing there.)  The two fixture paths
#: keep the rule demonstrable in the lint corpus.
SCOPE_SUFFIXES = (
    "tpudra/plugin/cdi.py",
    "tpudra/plugin/checkpoint.py",
    "tpudra/plugin/journal.py",
    "tpudra/plugin/vfio.py",
    "tpudra/cdplugin/computedomain.py",
    "tpudra/cdplugin/state.py",
    "tpudra/cddaemon/dnsnames.py",
    "fixtures/lint/bad/durable_write.py",
    "fixtures/lint/good/durable_write.py",
)

#: os.<name> spellings that put bytes on disk (or move them) — the seam's
#: job.  Stat/close/read-side os calls are not listed.
OS_WRITE_CALLS = frozenset(
    {"open", "write", "fsync", "replace", "rename", "ftruncate"}
)

_WRITE_MODE_CHARS = frozenset("wax+")


def _in_scope(path: str) -> bool:
    return path.replace(os.sep, "/").endswith(SCOPE_SUFFIXES)


def _open_mode(call: ast.Call):
    """The mode argument of a builtin open() call, or None."""
    if len(call.args) > 1:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            return kw.value
    return None


class DurableWrite(Rule):
    rule_id = "DURABLE-WRITE"
    description = (
        "persistence-module file writes route through tpudra.storage "
        "(the fault-injectable seam / atomic durable-write helpers), "
        "never raw open('w')/os.replace/os.fsync"
    )

    def check_module(self, module: ParsedModule) -> list[Finding]:
        if not _in_scope(module.path):
            return []
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "os"
                and func.attr in OS_WRITE_CALLS
            ):
                out.append(
                    self.finding(
                        module, node,
                        f"raw os.{func.attr} in a persistence module: "
                        "route it through tpudra.storage so fault "
                        "injection and the fail-stop durability contract "
                        "cover this call site",
                    )
                )
            elif isinstance(func, ast.Name) and func.id == "open":
                mode = _open_mode(node)
                if (
                    isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and set(mode.value) & _WRITE_MODE_CHARS
                ):
                    out.append(
                        self.finding(
                            module, node,
                            "write-mode open() in a persistence module: "
                            "use storage.atomic_replace / "
                            "storage.write_file (the fault-injectable "
                            "seam) so a crash or a misbehaving disk "
                            "cannot silently lose or tear this file",
                        )
                    )
        return out

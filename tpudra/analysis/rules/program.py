"""Shared whole-program state for the cross-file rule families.

The whole-program analyses (tpudra-lockgraph, tpudra-effectgraph,
tpudra-racegraph) resolve calls over the same corpus; building the
CallGraph or the lock registry twice per lint run would double the most
expensive non-parse steps for no information.  One ``ProgramState``
accumulates the engine's shared parse pass and hands every analysis the
SAME lazily-built CallGraph and LockModel.
"""

from __future__ import annotations

from typing import Optional

from tpudra.analysis.callgraph import CallGraph
from tpudra.analysis.engine import ParsedModule


class ProgramState:
    def __init__(self) -> None:
        self.modules: list[ParsedModule] = []
        self._paths: set[str] = set()
        self._graph: Optional[CallGraph] = None
        self._lockmodel = None

    def add(self, module: ParsedModule) -> bool:
        """Register a module; True when it was new (consumers invalidate
        their cached analysis on that signal)."""
        if module.path in self._paths:
            return False
        self._paths.add(module.path)
        self.modules.append(module)
        self._graph = None
        self._lockmodel = None
        return True

    def graph(self) -> CallGraph:
        if self._graph is None:
            self._graph = CallGraph(self.modules)
        return self._graph

    def lockmodel(self):
        """The shared lock registry + resolver (lockgraph and racegraph
        both consume it; built once per corpus)."""
        if self._lockmodel is None:
            from tpudra.analysis.lockmodel import LockModel

            self._lockmodel = LockModel(self.modules, self.graph())
        return self._lockmodel

"""Shared whole-program state for the cross-file rule families.

Both whole-program analyses (tpudra-lockgraph and tpudra-effectgraph)
resolve calls over the same corpus; building the CallGraph twice per lint
run would double the most expensive non-parse step for no information.
One ``ProgramState`` accumulates the engine's shared parse pass and hands
every analysis the SAME lazily-built CallGraph.
"""

from __future__ import annotations

from typing import Optional

from tpudra.analysis.callgraph import CallGraph
from tpudra.analysis.engine import ParsedModule


class ProgramState:
    def __init__(self) -> None:
        self.modules: list[ParsedModule] = []
        self._paths: set[str] = set()
        self._graph: Optional[CallGraph] = None

    def add(self, module: ParsedModule) -> bool:
        """Register a module; True when it was new (consumers invalidate
        their cached analysis on that signal)."""
        if module.path in self._paths:
            return False
        self._paths.add(module.path)
        self.modules.append(module)
        self._graph = None
        return True

    def graph(self) -> CallGraph:
        if self._graph is None:
            self._graph = CallGraph(self.modules)
        return self._graph

"""SPAN-HYGIENE: trace spans are greppable and can never leak open.

Two contracts on ``start_span`` (tpudra/trace.py):

- the span NAME is a string literal.  ``grep start_span`` must enumerate
  the whole span vocabulary (trace_report's tree assertions, the docs'
  span table, and dashboards all key on names); a computed name hides
  part of the surface and can explode label cardinality.
- every call is the context expression of a ``with`` statement.  A
  manually-started span has no guaranteed close: any exception path (and
  the bind path is built from per-claim fault barriers) leaks it open,
  silently truncating the trace tree — exactly the kind of half-present
  data that makes people stop trusting the tool.  The context-manager
  protocol is also what scopes the contextvar parent correctly; an
  orphaned span would re-parent unrelated siblings.

``trace.record_span`` (the retroactive form) is exempt by construction:
it has no open/close window to leak.  Any name ending in ``start_span``
counts — ``trace.start_span``, a bare imported ``start_span`` — so an
aliased import cannot dodge the rule.
"""

from __future__ import annotations

import ast

from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule


def _is_start_span(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id == "start_span"
    if isinstance(func, ast.Attribute):
        return func.attr == "start_span"
    return False


class SpanHygiene(Rule):
    rule_id = "SPAN-HYGIENE"
    description = (
        "start_span names are string literals and every call is a "
        "with-statement context manager (no orphaned manual starts)"
    )

    def check_module(self, module: ParsedModule) -> list[Finding]:
        with_exprs = {
            id(item.context_expr)
            for node in ast.walk(module.tree)
            if isinstance(node, (ast.With, ast.AsyncWith))
            for item in node.items
        }
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not _is_start_span(node):
                continue
            name_arg = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "name":
                    name_arg = kw.value
            if not (
                isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)
            ):
                out.append(
                    self.finding(
                        module, node,
                        "span name must be a string literal — computed "
                        "names hide the span vocabulary from grep and "
                        "from trace_report's tree assertions; put the "
                        "variable part in attrs",
                    )
                )
            if id(node) not in with_exprs:
                out.append(
                    self.finding(
                        module, node,
                        "start_span must be used as a context manager "
                        "(`with trace.start_span(...)`) — a manually "
                        "started span leaks open on any exception path "
                        "and re-parents unrelated spans",
                    )
                )
        return out

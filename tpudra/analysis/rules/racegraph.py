"""RACE, GUARD-CONSISTENCY, THREAD-CONFINED-ESCAPE: the whole-program
data-race rules (tpudra-racegraph).

The heavy lifting lives in tpudra/analysis/racemodel.py; these Rule
shells adapt it to the engine's per-module + finalize protocol.  All
three rules SHARE one analysis per run, and the analysis shares its
CallGraph AND LockModel with the lockgraph through ``ProgramState`` —
one parse pass, one call graph, one lock registry, three whole-program
models.

This family supersedes the old single-module SHARED-STATE heuristic;
``# tpudra-lint: disable=SHARED-STATE`` suppressions alias to the three
new rule ids (engine._apply_suppressions) so they do not silently go
stale.
"""

from __future__ import annotations

from typing import Optional

from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.racemodel import RaceGraphResult, analyze_races
from tpudra.analysis.rules import Rule
from tpudra.analysis.rules.program import ProgramState


class RacegraphState:
    """Accumulates the modules of one lint run; analyzes once on demand."""

    def __init__(self, program: Optional[ProgramState] = None) -> None:
        self.program = program or ProgramState()
        self._result: Optional[RaceGraphResult] = None

    def add(self, module: ParsedModule) -> None:
        if self.program.add(module):
            self._result = None

    def result(self) -> RaceGraphResult:
        if self._result is None:
            self._result = analyze_races(
                self.program.modules,
                self.program.graph(),
                self.program.lockmodel(),
            )
        return self._result


class _RacegraphRule(Rule):
    def __init__(self, state: Optional[RacegraphState] = None):
        self.state = state or RacegraphState()

    def check_module(self, module: ParsedModule) -> list[Finding]:
        self.state.add(module)
        return []

    def finalize(self) -> list[Finding]:
        return [
            f for f in self.state.result().findings if f.rule_id == self.rule_id
        ]


class Race(_RacegraphRule):
    rule_id = "RACE"
    description = (
        "every attribute written from two or more thread roles keeps a "
        "non-empty intersection of held locks across all conflicting "
        "writes, after happens-before refinement (Eraser-style lockset "
        "over the shared call graph)"
    )


class GuardConsistency(_RacegraphRule):
    rule_id = "GUARD-CONSISTENCY"
    description = (
        "a cross-thread field is guarded by the SAME lock at every write "
        "site — different locks at different sites is the split-guard "
        "refactor bug, mutual exclusion in name only"
    )


class ThreadConfinedEscape(_RacegraphRule):
    rule_id = "THREAD-CONFINED-ESCAPE"
    description = (
        "a field declared '# tpudra-race: owner=ROLE' is only accessed by "
        "functions that role reaches — any other role touching it breaks "
        "the confinement claim"
    )

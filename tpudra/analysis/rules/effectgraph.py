"""WAL-INTENT-BEFORE-EFFECT, WAL-RECOVERY-EXHAUSTIVE,
FENCE-DOMINATES-COMMIT, STRIPE-ORDER: the whole-program WAL rules
(tpudra-effectgraph).

The heavy lifting lives in tpudra/analysis/effectmodel.py; these Rule
shells adapt it to the engine's per-module + finalize protocol.  All four
rules SHARE one analysis per run, and the analysis shares its CallGraph
with the lockgraph through ``ProgramState`` — one parse pass, one call
graph, two whole-program models.
"""

from __future__ import annotations

from typing import Optional

from tpudra.analysis.effectmodel import EffectGraphResult, analyze_effects
from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule
from tpudra.analysis.rules.program import ProgramState


class EffectgraphState:
    """Accumulates the modules of one lint run; analyzes once on demand."""

    def __init__(self, program: Optional[ProgramState] = None) -> None:
        self.program = program or ProgramState()
        self._result: Optional[EffectGraphResult] = None

    def add(self, module: ParsedModule) -> None:
        if self.program.add(module):
            self._result = None

    def result(self) -> EffectGraphResult:
        if self._result is None:
            self._result = analyze_effects(
                self.program.modules, self.program.graph()
            )
        return self._result


class _EffectgraphRule(Rule):
    def __init__(self, state: Optional[EffectgraphState] = None):
        self.state = state or EffectgraphState()

    def check_module(self, module: ParsedModule) -> list[Finding]:
        self.state.add(module)
        return []

    def finalize(self) -> list[Finding]:
        return [
            f for f in self.state.result().findings if f.rule_id == self.rule_id
        ]


class WalIntentBeforeEffect(_EffectgraphRule):
    rule_id = "WAL-INTENT-BEFORE-EFFECT"
    description = (
        "every registered hardware/disk/daemon side effect is dominated by "
        "a durable intent record of its matching kind (the WAL "
        "crash-consistency contract, statically)"
    )


class WalRecoveryExhaustive(_EffectgraphRule):
    rule_id = "WAL-RECOVERY-EXHAUSTIVE"
    description = (
        "two-sided recovery coverage: every committed record kind has a "
        "'# tpudra-wal: recovers=' handler and every declared handler "
        "matches a kind actually committed"
    )


class FenceDominatesCommit(_EffectgraphRule):
    rule_id = "FENCE-DOMINATES-COMMIT"
    description = (
        "every checkpoint commit site in controller code is dominated by a "
        "gangmeta/term fence check (the static form of the StaleLeader "
        "runtime refusal)"
    )


class StripeOrder(_EffectgraphRule):
    rule_id = "STRIPE-ORDER"
    description = (
        "cross-family mutators first-touch record families in the "
        "canonical stripe order gangmeta < gang < claim < partition (the "
        "striped-checkpoint pre-flight)"
    )

"""LOCK-CYCLE, BLOCK-UNDER-LOCK-IP, FLOCK-INVERSION: the whole-program
lock rules (tpudra-lockgraph).

The heavy lifting lives in tpudra/analysis/lockmodel.py; these Rule
shells adapt it to the engine's per-module + finalize protocol.  All
three rules SHARE one analysis (all_rules wires one ``LockgraphState``
into the three instances), so the held-set propagation runs once per
lint run no matter how many of its rules are active — and the modules
they consume are the engine's shared parse pass, so the lockgraph adds
zero extra ``ast.parse`` work on top of tpudra-lint.
"""

from __future__ import annotations

from typing import Optional

from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.lockmodel import LockGraphResult, analyze_modules
from tpudra.analysis.rules import Rule
from tpudra.analysis.rules.program import ProgramState


class LockgraphState:
    """Accumulates the modules of one lint run; analyzes once on demand.

    The corpus and CallGraph live in a ``ProgramState`` so the effectgraph
    (rules/effectgraph.py) can share them — pass the same instance to both
    and the call graph is built once per run."""

    def __init__(self, program: Optional[ProgramState] = None) -> None:
        self.program = program or ProgramState()
        self._result: Optional[LockGraphResult] = None

    @property
    def modules(self) -> list[ParsedModule]:
        return self.program.modules

    def add(self, module: ParsedModule) -> None:
        if self.program.add(module):
            self._result = None

    def result(self) -> LockGraphResult:
        if self._result is None:
            self._result = analyze_modules(
                self.program.modules,
                self.program.graph(),
                self.program.lockmodel(),
            )
        return self._result


class _LockgraphRule(Rule):
    def __init__(self, state: Optional[LockgraphState] = None):
        self.state = state or LockgraphState()

    def check_module(self, module: ParsedModule) -> list[Finding]:
        self.state.add(module)
        return []

    def finalize(self) -> list[Finding]:
        return [
            f for f in self.state.result().findings if f.rule_id == self.rule_id
        ]


class LockCycle(_LockgraphRule):
    rule_id = "LOCK-CYCLE"
    description = (
        "the global lock acquisition graph is acyclic — a cycle is a "
        "static deadlock candidate, reported with a concrete call-path pair"
    )


class BlockUnderLockIP(_LockgraphRule):
    rule_id = "BLOCK-UNDER-LOCK-IP"
    description = (
        "no sleep / subprocess / gRPC / apiserver call / blocking wait "
        "reachable through calls while an in-process lock is held "
        "(interprocedural BLOCK-UNDER-LOCK)"
    )


class FlockInversion(_LockgraphRule):
    rule_id = "FLOCK-INVERSION"
    description = (
        "no cross-process flock acquired while an in-process lock is held "
        "— the inversion that wedges a node when two driver processes race"
    )

"""LOCK-ORDER and BLOCK-UNDER-LOCK: the bind-path lock hierarchy.

docs/bind-path.md §"Lock hierarchy" in prose; here as machine checks:

- the publish lock (level 3) must never wait on a flock (level 1/2) or on
  the checkpoint RMW (``mutate`` takes ``cp.lock``);
- per-claim-uid flocks are acquired in sorted-uid order, or two batches
  sharing uids deadlock;
- an in-process-lock ``with`` body must not block: no ``time.sleep``, no
  ``subprocess``, no gRPC stub calls, no ``open()`` — every other thread
  needing the lock stalls for the duration, and on the bind path that is
  a p99 regression hiding in a critical section.
"""

from __future__ import annotations

import ast

from tpudra.analysis import astutil
from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule

#: Attribute names that denote the ResourceSlice publish lock (level 3).
_PUBLISH_LOCK_NAMES = {"_publish_lock", "publish_lock"}

#: Helpers that acquire a per-claim-uid flock (driver.py).
_CLAIM_LOCK_ACQUIRERS = {"_acquire_claim_lock"}


def _is_publish_lock_with(item: ast.withitem) -> bool:
    return astutil.terminal_name(item.context_expr) in _PUBLISH_LOCK_NAMES


def _blocking_call(call: ast.Call) -> str:
    """Non-empty description when the call blocks: sleep, subprocess, a
    gRPC stub method, or file I/O via ``open``."""
    dotted = astutil.dotted_name(call.func)
    terminal = astutil.call_name(call)
    if terminal == "sleep":
        return "time.sleep"
    if dotted.startswith("subprocess.") or terminal == "Popen":
        return dotted
    if isinstance(call.func, ast.Name) and call.func.id == "open":
        return "open()"
    # A method on something named *stub* (gRPC convention: self._stub,
    # node_stub, registration_stub ...).
    receiver_parts = dotted.lower().split(".")[:-1]
    if any("stub" in part for part in receiver_parts):
        return f"gRPC stub call {dotted}"
    return ""


class LockOrder(Rule):
    rule_id = "LOCK-ORDER"
    description = (
        "flocks and the checkpoint RMW are never awaited under the publish "
        "lock; per-claim-uid locks are acquired in sorted order"
    )

    def check_module(self, module: ParsedModule) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.With) and any(
                _is_publish_lock_with(i) for i in node.items
            ):
                out.extend(self._check_publish_body(module, node))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                out.extend(self._check_claim_lock_loop(module, node))
        return out

    def _check_publish_body(self, module: ParsedModule, with_node: ast.With) -> list[Finding]:
        """Nothing under ``_publish_lock`` may wait on a lower lock level:
        no Flock construction/acquire/with, no ``mutate`` (cp.lock RMW).
        One finding per line: a ``with Flock(...)`` is both a With and a
        Call, and two findings for one offense reads as two bugs."""
        out = []
        seen_lines: set[int] = set()

        def add(f: Finding) -> None:
            if f.line not in seen_lines:
                seen_lines.add(f.line)
                out.append(f)
        for sub in astutil.walk_body_shallow(with_node.body):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    kind = astutil.withitem_lock_kind(item)
                    if kind is not None and kind[0] == "flock":
                        add(
                            self.finding(
                                module, sub,
                                f"flock '{kind[1]}' taken inside the publish-lock "
                                "block — the publish lock (level 3) must never "
                                "wait on a flock (docs/bind-path.md)",
                            )
                        )
            if not isinstance(sub, ast.Call):
                continue
            name = astutil.call_name(sub)
            if astutil.is_flockish(sub.func) and name in ("Flock", "acquire"):
                add(
                    self.finding(
                        module, sub,
                        f"'{astutil.dotted_name(sub.func)}' under the publish lock — "
                        "flocks are below the publish lock in the hierarchy",
                    )
                )
            elif name == "mutate":
                add(
                    self.finding(
                        module, sub,
                        "checkpoint RMW (mutate takes cp.lock) under the publish "
                        "lock — run the RMW first, publish after",
                    )
                )
        return out

    def _check_claim_lock_loop(self, module: ParsedModule, loop: ast.For) -> list[Finding]:
        """A loop acquiring per-claim-uid locks must iterate ``sorted(...)``
        — unsorted acquisition order deadlocks two batches sharing uids."""
        acquires = [
            c
            for c in astutil.walk_body_shallow(loop.body)
            if isinstance(c, ast.Call)
            and (
                astutil.call_name(c) in _CLAIM_LOCK_ACQUIRERS
                or (
                    astutil.call_name(c) == "Flock"
                    and any(
                        "claim" in astutil.dotted_name(a).lower()
                        for a in c.args
                        if isinstance(a, (ast.Call, ast.Attribute, ast.Name))
                    )
                )
            )
        ]
        if not acquires:
            return []
        it = loop.iter
        if isinstance(it, ast.Call) and astutil.call_name(it) in ("sorted", "reversed"):
            # reversed(sorted(...)) is still a total order; plain reversed
            # of an arbitrary iterable is not — only accept it over sorted.
            if astutil.call_name(it) == "sorted" or (
                it.args
                and isinstance(it.args[0], ast.Call)
                and astutil.call_name(it.args[0]) == "sorted"
            ):
                return []
        return [
            self.finding(
                module, acquires[0],
                "per-claim-uid locks acquired from an unsorted iterable — "
                "two batches sharing uids can deadlock; iterate sorted(uids)",
            )
        ]


class BlockUnderLock(Rule):
    rule_id = "BLOCK-UNDER-LOCK"
    description = (
        "no time.sleep / subprocess / gRPC stub call / open() inside an "
        "in-process-lock with body"
    )

    def check_module(self, module: ParsedModule) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            locks = [
                kind
                for kind in (astutil.withitem_lock_kind(i) for i in node.items)
                if kind is not None and kind[0] == "inproc"
            ]
            if not locks:
                continue
            lock_name = locks[0][1]
            for sub in astutil.walk_body_shallow(node.body):
                if not isinstance(sub, ast.Call):
                    continue
                what = _blocking_call(sub)
                if what:
                    out.append(
                        self.finding(
                            module, sub,
                            f"{what} while holding in-process lock "
                            f"'{lock_name}' — move the blocking work outside "
                            "the critical section",
                        )
                    )
        return out

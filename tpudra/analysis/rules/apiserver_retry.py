"""APISERVER-RETRY: apiserver-verb retry loops pace with the shared Backoff.

An apiserver failure is almost never one client's private event: a flap,
a 429 shed window, or an outage puts EVERY client into its failure path
within milliseconds.  A retry loop that sleeps a constant after catching
the error marches the whole fleet back in lockstep — the synchronized
storm lands exactly when the server is weakest, which is why every
production retry path in this tree (informer relist, workqueue limiter,
publisher, lease elector) runs on ``tpudra/backoff.py``'s capped
full-jitter policy, flooring on any 429/503 ``Retry-After`` hint.

This rule pins the discipline as a machine check: inside a loop that
calls an apiserver verb, an ``except`` handler for an API-error-ish
exception may not reach a **literal-constant** ``time.sleep`` — route the
delay through a :class:`tpudra.backoff.Backoff` (``sleep(b.next_delay())``
or ``stop.wait(...)``) instead.  The match is deliberately narrow:

- only sleeps whose argument is a numeric literal fire (a delay computed
  from ``next_delay()`` / ``full_jitter_delay`` is exactly the fix);
- only sleeps INSIDE the except handler fire — a loop-tail sleep pacing a
  bounded state poll is cadence, not failure retry, and jittering it
  buys nothing;
- the loop must actually touch the apiserver: a call whose attribute is a
  KubeAPI verb on a receiver mentioning ``kube`` (``self._kube.get``,
  ``sim.kube.create``, ...).
"""

from __future__ import annotations

import ast

from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule

#: KubeAPI protocol verbs (kube/client.py).
APISERVER_VERBS = frozenset(
    {"get", "list", "create", "update", "update_status", "patch", "delete",
     "watch"}
)

#: Exception names that mark a handler as "the apiserver failed" — the
#: typed errors plus the broad catches retry loops actually write.
_API_ERRORISH = frozenset(
    {
        "ApiError",
        "Timeout",
        "TooManyRequests",
        "ServiceUnavailable",
        "InternalError",
        "Expired",
        "Conflict",
        "Exception",
    }
)


def _is_apiserver_call(node: ast.Call) -> bool:
    func = node.func
    if not (isinstance(func, ast.Attribute) and func.attr in APISERVER_VERBS):
        return False
    try:
        receiver = ast.unparse(func.value)
    except Exception:  # noqa: BLE001 — unparse failure: not a finding
        return False
    return "kube" in receiver.lower()


def _handler_names(handler: ast.ExceptHandler) -> set[str]:
    t = handler.type
    if t is None:
        return {"Exception"}  # bare except: at least as broad
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out: set[str] = set()
    for e in elts:
        if isinstance(e, ast.Name):
            out.add(e.id)
        elif isinstance(e, ast.Attribute):
            out.add(e.attr)
    return out


def _literal_sleeps(node: ast.AST) -> list[ast.Call]:
    """time.sleep(<numeric literal>) calls anywhere under ``node``."""
    out = []
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        func = sub.func
        named_sleep = (
            isinstance(func, ast.Attribute)
            and func.attr == "sleep"
            and isinstance(func.value, ast.Name)
            and func.value.id == "time"
        ) or (isinstance(func, ast.Name) and func.id == "sleep")
        if not named_sleep or not sub.args:
            continue
        arg = sub.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)):
            out.append(sub)
    return out


class ApiserverRetry(Rule):
    rule_id = "APISERVER-RETRY"
    description = (
        "apiserver-verb retry loops may not sleep a literal constant in "
        "their error handler — route the delay through tpudra.backoff's "
        "shared full-jitter Backoff (Retry-After as a floor)"
    )

    def check_module(self, module: ParsedModule) -> list[Finding]:
        out: list[Finding] = []
        # Nested retry loops (per-node outer, per-attempt inner) both
        # match the verb predicate and would each re-report the same
        # sleep — one finding per sleep site.
        seen: set[tuple[int, int]] = set()
        for loop in ast.walk(module.tree):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            if not any(
                isinstance(n, ast.Call) and _is_apiserver_call(n)
                for n in ast.walk(loop)
            ):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if not (_handler_names(handler) & _API_ERRORISH):
                        continue
                    for sleep in _literal_sleeps(handler):
                        key = (sleep.lineno, sleep.col_offset)
                        if key in seen:
                            continue
                        seen.add(key)
                        out.append(
                            self.finding(
                                module, sleep,
                                "constant sleep in an apiserver-verb retry "
                                "loop's error handler: a fleet of clients "
                                "retrying on the same constant marches "
                                "back in lockstep — use the shared "
                                "tpudra.backoff.Backoff (full jitter, "
                                "Retry-After floor) for the delay",
                            )
                        )
        return out

"""PARTITION-PHASE: partition lifecycle calls run in the effects phase.

The phased bind discipline (docs/bind-path.md, docs/partitioning.md)
puts hardware mutation — ``create_partition`` / ``delete_partition``,
O(seconds) on real silicon — in the EFFECTS phase: outside the node-wide
``pu.lock`` and every in-process lock, and never inside a checkpoint
mutator closure (the RMW phases must stay pure and O(µs); a devicelib
call in a mutator would also run on whichever thread leads the group
commit, under the ``cp.lock`` flock, serializing every other bind on the
node behind a hardware op).  The per-claim-uid flock family is exempt by
design — effects DO run under ``_claims_serialized``.

Two shapes are findings in the scoped modules:

- a lifecycle call lexically inside a ``with`` whose context is a lock
  (``_locked_pu()`` / ``_pu_lock()`` / a ``Flock`` acquisition / any
  ``*_lock`` / ``*_cond`` attribute);
- a lifecycle call inside a function (or lambda) passed to a
  ``mutate(...)`` call — a checkpoint mutator closure.
"""

from __future__ import annotations

import ast
import os

from tpudra.analysis import astutil
from tpudra.analysis.engine import Finding, ParsedModule
from tpudra.analysis.rules import Rule

SCOPE_SUFFIXES = (
    "tpudra/plugin/device_state.py",
    "tpudra/plugin/driver.py",
    "fixtures/lint/bad/partition_phase.py",
    "fixtures/lint/good/partition_phase.py",
)

LIFECYCLE_CALLS = frozenset({"create_partition", "delete_partition"})

#: With-contexts that mark the locked (non-effects) phases.  The
#: claim-uid flock helper (``_claims_serialized``) is deliberately NOT
#: here: effects run under it by design.
_LOCK_CALL_NAMES = frozenset({"_locked_pu", "_pu_lock", "Flock"})


def _in_scope(path: str) -> bool:
    return path.replace(os.sep, "/").endswith(SCOPE_SUFFIXES)


def _is_lockish_context(expr) -> bool:
    """True when a with-item context expression is a lock acquisition."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = astutil.call_name(node)
            if name in _LOCK_CALL_NAMES:
                return True
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name and (name.endswith("_lock") or name.endswith("_cond")):
            return True
    return False


class PartitionPhase(Rule):
    rule_id = "PARTITION-PHASE"
    description = (
        "partition lifecycle calls (create_partition/delete_partition) "
        "must run in the effects phase: not under in-process/pu locks, "
        "not inside checkpoint mutator closures"
    )

    def check_module(self, module: ParsedModule) -> list[Finding]:
        if not _in_scope(module.path):
            return []
        out: list[Finding] = []
        # Functions/lambdas handed to mutate(...) are mutator closures.
        mutator_names: set[str] = set()
        mutator_lambdas: set[int] = set()
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and astutil.call_name(node) == "mutate"
                and node.args
            ):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                mutator_names.add(arg.id)
            elif isinstance(arg, ast.Lambda):
                mutator_lambdas.add(id(arg))

        def scan(node, in_mutator: bool, lock_depth: int) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_mutator = in_mutator or node.name in mutator_names
                # A fresh def resets the lexical lock context: its body
                # runs when CALLED, not where it is defined — except that
                # a mutator closure's body always runs inside the commit.
                lock_depth = 0
            elif isinstance(node, ast.Lambda):
                in_mutator = in_mutator or id(node) in mutator_lambdas
                lock_depth = 0
            if isinstance(node, (ast.With, ast.AsyncWith)):
                lockish = any(
                    _is_lockish_context(item.context_expr)
                    for item in node.items
                )
                for item in node.items:
                    scan(item.context_expr, in_mutator, lock_depth)
                for child in node.body:
                    scan(child, in_mutator, lock_depth + int(lockish))
                return
            if (
                isinstance(node, ast.Call)
                and astutil.call_name(node) in LIFECYCLE_CALLS
            ):
                if in_mutator:
                    out.append(
                        self.finding(
                            module, node,
                            f"{astutil.call_name(node)} inside a checkpoint "
                            "mutator closure: partition lifecycle is "
                            "effects-phase work — the RMW must journal "
                            "intent, never mutate hardware",
                        )
                    )
                elif lock_depth > 0:
                    out.append(
                        self.finding(
                            module, node,
                            f"{astutil.call_name(node)} under a held lock: "
                            "partition lifecycle is effects-phase work — "
                            "run it outside the locked RMW phases",
                        )
                    )
            for child in ast.iter_child_nodes(node):
                scan(child, in_mutator, lock_depth)

        scan(module.tree, in_mutator=False, lock_depth=0)
        return out

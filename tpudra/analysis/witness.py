"""Witness merge + lock-order doc generation for tpudra-lockgraph.

The static acquisition graph (lockmodel.py) and the runtime witness log
(tpudra/lockwitness.py) validate each other:

- a cycle among *witnessed* edges is an ordering inconsistency the test
  suite actually exhibited — fail;
- a witnessed edge the static model lacks is a **model gap** (the
  analyzer's resolution missed a call path) — fail, because every other
  guarantee the static rules make is only as good as the model;
- a static edge never witnessed is a coverage statement, reported but
  non-failing (static analysis over-approximates by design).

Coverage is computed over *witnessable* edges only — both endpoints
instrumented (lockwitness-constructed locks and flocks); an edge between
two plain ``threading`` locks can never appear in a log, and counting it
against coverage would just punish unwired modules.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpudra import lockwitness
from tpudra.analysis.engine import parse_paths
from tpudra.analysis.lockmodel import (
    BIND_PATH_LOCKS,
    LockGraphResult,
    _find_cycles,
    _rel,
    analyze_modules,
)


def build_graph(root: str) -> LockGraphResult:
    """The static lock graph of the tree under ``root`` (normally the
    ``tpudra`` package directory) — one shared parse pass."""
    modules, _ = parse_paths([root])
    return analyze_modules(modules)


@dataclass
class MergeReport:
    witnessed_locks: set
    witnessed_edges: set
    witnessed_cycles: list = field(default_factory=list)
    model_gaps: list = field(default_factory=list)  # witnessed, not modeled
    covered: set = field(default_factory=set)  # static ∩ witnessed
    uncovered: set = field(default_factory=set)  # witnessable static, never seen
    bind_covered: set = field(default_factory=set)
    bind_uncovered: set = field(default_factory=set)

    @property
    def ok(self) -> bool:
        return not self.witnessed_cycles and not self.model_gaps

    def coverage(self) -> float:
        total = len(self.covered) + len(self.uncovered)
        return (len(self.covered) / total) if total else 1.0

    def bind_path_coverage(self) -> float:
        total = len(self.bind_covered) + len(self.bind_uncovered)
        return (len(self.bind_covered) / total) if total else 1.0

    def render(self) -> str:
        lines = [
            f"witnessed: {len(self.witnessed_locks)} locks, "
            f"{len(self.witnessed_edges)} edges",
        ]
        for cycle in self.witnessed_cycles:
            lines.append(
                "WITNESSED CYCLE: " + " → ".join(cycle + cycle[:1])
            )
        for a, b in sorted(self.model_gaps):
            lines.append(
                f"MODEL GAP: runtime acquired '{b}' while holding '{a}' but "
                "the static graph has no such edge — teach lockmodel.py the "
                "call path (or annotate it) before trusting the other rules"
            )
        lines.append(
            f"static edge coverage: {len(self.covered)}/"
            f"{len(self.covered) + len(self.uncovered)} "
            f"({self.coverage():.0%}) of witnessable edges"
        )
        lines.append(
            f"bind-path edge coverage: {len(self.bind_covered)}/"
            f"{len(self.bind_covered) + len(self.bind_uncovered)} "
            f"({self.bind_path_coverage():.0%})"
        )
        for a, b in sorted(self.uncovered):
            lines.append(f"  never witnessed: {a} → {b}")
        lines.append("witness merge: " + ("OK" if self.ok else "FAILED"))
        return "\n".join(lines)


def merge(result: LockGraphResult, log_path: str) -> MergeReport:
    locks, edges = lockwitness.read_log(log_path)
    edges = {(a, b) for (a, b) in edges if a != b}
    report = MergeReport(witnessed_locks=locks, witnessed_edges=edges)

    adj: dict[str, list[str]] = {}
    for a, b in sorted(edges):
        adj.setdefault(a, []).append(b)
    report.witnessed_cycles = _find_cycles(adj)

    static_edges = result.edge_ids()
    report.model_gaps = sorted(e for e in edges if e not in static_edges)

    witnessable = result.witnessable_edge_ids()
    report.covered = {e for e in witnessable if e in edges}
    report.uncovered = witnessable - report.covered
    bind = {
        e for e in witnessable if e[0] in BIND_PATH_LOCKS and e[1] in BIND_PATH_LOCKS
    }
    report.bind_covered = {e for e in bind if e in edges}
    report.bind_uncovered = bind - report.bind_covered
    return report


# --------------------------------------------------------------- lock-order doc


def _topo_order(result: LockGraphResult) -> list[list[str]]:
    """Topological levels of the acquisition DAG's participating locks
    (level N may be acquired while anything in levels < N is held)."""
    nodes = sorted({a for a, _ in result.edges} | {b for _, b in result.edges})
    preds: dict[str, set] = {n: set() for n in nodes}
    for a, b in result.edges:
        if a != b:
            preds[b].add(a)
    levels: list[list[str]] = []
    placed: set = set()
    while len(placed) < len(nodes):
        ready = sorted(
            n for n in nodes if n not in placed and preds[n] <= placed
        )
        if not ready:  # cycle: emit the remainder as one level (lint fails it)
            levels.append(sorted(n for n in nodes if n not in placed))
            break
        levels.append(ready)
        placed.update(ready)
    return levels


def emit_markdown(result: LockGraphResult) -> str:
    """docs/lock-order.md: the canonical acquisition-order table plus the
    raw graph, regenerated by ``python -m tpudra.analysis --emit-dot``.
    Deterministic output — a freshness test diffs it against the file."""
    out = [
        "# Lock acquisition order",
        "",
        "**Generated** by `python -m tpudra.analysis --emit-dot docs/lock-order.md`",
        "(`make lockgraph-docs`) from the tpudra-lockgraph static model — do not",
        "edit by hand.  Rules and witness workflow:",
        "[static-analysis.md](static-analysis.md); the prose argument for the",
        "hierarchy: [bind-path.md](bind-path.md).",
        "",
        "A lock may only be acquired while holding locks from *strictly earlier*",
        "levels (or none).  `flock:` locks are cross-process `flock(2)` files;",
        "everything else is in-process.  *family* locks are ID classes with many",
        "runtime instances, acquired intra-family in sorted order (LOCK-ORDER).",
        "",
        "## Canonical acquisition order",
        "",
        "| level | lock | kind | defined at |",
        "|---|---|---|---|",
    ]
    ordered: set = set()
    for i, level in enumerate(_topo_order(result), 1):
        for lock_id in level:
            ordered.add(lock_id)
            ref = result.locks[lock_id]
            kind = ref.kind + (" (family)" if ref.family else "")
            out.append(f"| {i} | `{lock_id}` | {kind} | {ref.defined_at or '—'} |")
    out += [
        "",
        "## Acquisition edges",
        "",
        "`A → B`: B is acquired while A is held, with one concrete call path.",
        "",
        "| held | acquires | via |",
        "|---|---|---|",
    ]
    for (a, b) in sorted(result.edges):
        e = result.edges[(a, b)]
        out.append(f"| `{a}` | `{b}` | {e.chain} ({_rel(e.path)}:{e.line}) |")
    isolated = sorted(set(result.locks) - ordered)
    if isolated:
        out += [
            "",
            "## Locks with no ordering constraints",
            "",
            "Never held together with another modeled lock (leaf critical",
            "sections).",
            "",
            "| lock | kind | defined at |",
            "|---|---|---|",
        ]
        for lock_id in isolated:
            ref = result.locks[lock_id]
            kind = ref.kind + (" (family)" if ref.family else "")
            out.append(f"| `{lock_id}` | {kind} | {ref.defined_at or '—'} |")
    out += [
        "",
        "## Graphviz",
        "",
        "```dot",
        "digraph lockorder {",
        "  rankdir=LR;",
    ]
    for (a, b) in sorted(result.edges):
        out.append(f'  "{a}" -> "{b}";')
    out += ["}", "```", ""]
    return "\n".join(out)

"""Fault-injectable storage seam under everything the plugins persist.

Every byte the kubelet plugins stake crash-safety on — the checkpoint
snapshot, the WAL (``plugin/journal.py``), CDI spec files, the CD daemon's
config files — reaches the disk through the small os-ops layer in this
module: ``open``/``write``/``fsync``/``replace``/``ftruncate``/
``fsync_dir`` plus the two composed helpers ``atomic_replace`` (tmp write →
file fsync → rename → directory fsync, the rename-durability idiom) and
``write_file``.  Two reasons it exists:

1. **Fault injection.**  A :class:`FaultPlan` installed via
   :func:`install_fault_plan` (or the ``TPUDRA_STORAGE_FAULT`` env, gated
   on ``TPUDRA_TEST_HOOKS=1`` like the crashpoints) makes any call site
   fail with a chosen errno — per op (write vs fsync vs replace…), per
   path substring (one node's plugin dir, just ``checkpoint.wal``),
   fail-once or fail-until-healed, optionally with a slow-I/O stall or a
   partial write before the error.  The chaos soak's ``disk_fault`` kind
   and the storage-fault unit tests drive everything through here; no
   test ever monkeypatches ``os`` internals.

2. **One place for the fail-stop contract.**  The durability rules the
   callers implement (a failed fsync poisons the fd — fsyncgate; never
   ``os.replace`` over a good file after a failed tmp fsync; acknowledge a
   mutation only after its bytes are provably durable) only hold if every
   write goes through a layer whose failures are typed and observable.
   ``tpudra_storage_faults_total{op,errno}`` counts every storage-errno
   failure surfaced here, injected or real; the ``DURABLE-WRITE`` lint
   rule (tpudra/analysis/rules/durable_write.py) keeps new persistence
   call sites from dodging the seam.

Reads are deliberately NOT routed here: the degraded-mode contract
(docs/bind-path.md "Storage fault contract") keeps read paths, health,
and slice publication alive while the disk refuses writes.
"""

from __future__ import annotations

import contextlib
import errno as errno_mod
import os
import time
from dataclasses import dataclass
from typing import Optional

from tpudra import lockwitness, metrics

#: Errnos that mean "the disk/filesystem misbehaved" (vs. a programming
#: error like ENOENT on a bad path).  Only these flip the checkpoint
#: manager into storage-degraded mode.
STORAGE_ERRNOS = frozenset(
    {
        errno_mod.ENOSPC,
        errno_mod.EIO,
        errno_mod.EROFS,
        errno_mod.EDQUOT,
        errno_mod.ENODEV,
    }
)

#: Greppable marker every degraded-mode shed error carries across the DRA
#: gRPC boundary — the "typed" half of the typed retryable error (the
#: response dict's ``permanent: False`` is the retryable half).
DEGRADED_ERROR_PREFIX = "[storage-degraded]"

#: Env arming for subprocess harnesses (the crash sweeps): a semicolon-
#: separated list of ``op:ERRNO_NAME:times:path_substring`` rules, honored
#: only under ``TPUDRA_TEST_HOOKS=1`` (two-key arming, like
#: TPUDRA_CRASHPOINT).  ``times`` is an integer or ``inf`` (= until
#: healed).  Example: ``write:ENOSPC:1:checkpoint.wal``.
ENV_FAULT = "TPUDRA_STORAGE_FAULT"

#: The op vocabulary rules may name (also the ``op`` label values of
#: ``tpudra_storage_faults_total``).
OPS = ("open", "write", "fsync", "fsync_dir", "replace", "truncate")


def is_storage_error(e: BaseException) -> bool:
    return isinstance(e, OSError) and e.errno in STORAGE_ERRNOS


def _errno_name(code: Optional[int]) -> str:
    return errno_mod.errorcode.get(code or 0, str(code))


def _count_fault(op: str, code: Optional[int]) -> None:
    if code in STORAGE_ERRNOS:
        metrics.STORAGE_FAULTS_TOTAL.labels(op, _errno_name(code)).inc()


@dataclass
class FaultRule:
    """One injected misbehavior.  ``err=None`` is a pure slow-I/O stall;
    ``times=None`` fails until the plan is healed; ``partial_bytes`` (write
    op only) really writes that prefix before raising — the mid-append
    torn-frame shape."""

    op: str
    path: str = ""  # substring of the op's path; "" matches every path
    err: Optional[int] = errno_mod.EIO
    times: Optional[int] = 1
    delay_s: float = 0.0
    partial_bytes: Optional[int] = None
    fired: int = 0


class FaultPlan:
    """A thread-safe rule set; first matching rule wins per op."""

    def __init__(self):
        self._lock = lockwitness.make_lock("storage.fault_plan_lock")
        self._rules: list[FaultRule] = []

    def add(
        self,
        op: str,
        path: str = "",
        err: Optional[int] = errno_mod.EIO,
        times: Optional[int] = 1,
        delay_s: float = 0.0,
        partial_bytes: Optional[int] = None,
    ) -> FaultRule:
        if op not in OPS:
            raise ValueError(f"unknown storage op {op!r} (want one of {OPS})")
        rule = FaultRule(
            op=op, path=path, err=err, times=times,
            delay_s=delay_s, partial_bytes=partial_bytes,
        )
        with self._lock:
            self._rules.append(rule)
        return rule

    def heal(self) -> None:
        """Clear every rule — the disk starts behaving again."""
        with self._lock:
            self._rules.clear()

    def fired_total(self) -> int:
        with self._lock:
            return sum(r.fired for r in self._rules)

    def match(self, op: str, path: str) -> Optional[FaultRule]:
        """Claim one firing of the first live rule matching (op, path)."""
        with self._lock:
            for rule in self._rules:
                if rule.op != op or rule.path not in path:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                rule.fired += 1
                return rule
        return None


_plan_lock = lockwitness.make_lock("storage.plan_lock")
_active_plan: Optional[FaultPlan] = None


def install_fault_plan(plan: Optional[FaultPlan]) -> None:
    global _active_plan
    with _plan_lock:
        _active_plan = plan


def clear_fault_plan() -> None:
    install_fault_plan(None)


def active_fault_plan() -> Optional[FaultPlan]:
    return _active_plan


@contextlib.contextmanager
def fault_plan(plan: Optional[FaultPlan] = None, **rule_kwargs):
    """Test scope: install ``plan`` (or a one-rule plan built from
    ``rule_kwargs``) for the duration of the with-block."""
    plan = plan or FaultPlan()
    if rule_kwargs:
        plan.add(**rule_kwargs)
    prev = _active_plan
    install_fault_plan(plan)
    try:
        yield plan
    finally:
        install_fault_plan(prev)


def _plan_from_env() -> Optional[FaultPlan]:
    spec = os.environ.get(ENV_FAULT, "")
    if not spec or os.environ.get("TPUDRA_TEST_HOOKS") != "1":
        return None
    plan = FaultPlan()
    for part in spec.split(";"):
        if not part.strip():
            continue
        fields = part.split(":", 3)
        if len(fields) < 2:
            raise ValueError(f"bad {ENV_FAULT} rule {part!r}")
        op, err_name = fields[0], fields[1]
        times_s = fields[2] if len(fields) > 2 and fields[2] else "1"
        path = fields[3] if len(fields) > 3 else ""
        err = getattr(errno_mod, err_name, None)
        if err is None:
            raise ValueError(f"unknown errno {err_name!r} in {ENV_FAULT}")
        times = None if times_s == "inf" else int(times_s)
        plan.add(op=op, path=path, err=err, times=times)
    return plan


def _raise_injected(op: str, path: str, rule: FaultRule) -> None:
    _count_fault(op, rule.err)
    raise OSError(
        rule.err, f"injected: {os.strerror(rule.err)}", path or None
    )


def _gate(op: str, path: str) -> None:
    """Consult the active fault plan before a real op.  The stall (if any)
    runs outside every lock; the raised OSError carries the rule's errno."""
    plan = _active_plan
    if plan is None:
        return
    rule = plan.match(op, path)
    if rule is None:
        return
    if rule.delay_s > 0:
        time.sleep(rule.delay_s)
    if rule.err is not None:
        _raise_injected(op, path, rule)


# fd → path, so fd-based ops (write/fsync/truncate) can be matched by the
# path rules of a fault plan.  Only fds opened through this seam register.
_fd_lock = lockwitness.make_lock("storage.fd_lock")
_fd_paths: dict[int, str] = {}


def _fd_path(fd: int) -> str:
    with _fd_lock:
        return _fd_paths.get(fd, "")


def open(path: str, flags: int, mode: int = 0o600) -> int:  # noqa: A001 — deliberate seam name
    _gate("open", path)
    try:
        fd = os.open(path, flags, mode)
    except OSError as e:
        _count_fault("open", e.errno)
        raise
    with _fd_lock:
        _fd_paths[fd] = path
    return fd


def close(fd: int) -> None:
    with _fd_lock:
        _fd_paths.pop(fd, None)
    os.close(fd)


def write(fd: int, data) -> int:
    path = _fd_path(fd)
    plan = _active_plan
    if plan is not None:
        rule = plan.match("write", path)
        if rule is not None:
            if rule.delay_s > 0:
                time.sleep(rule.delay_s)
            if rule.err is not None:
                if rule.partial_bytes:
                    # The mid-append shape: a real prefix lands, then the
                    # device gives up — exactly what a torn frame is.
                    with contextlib.suppress(OSError):
                        os.write(fd, bytes(data)[: rule.partial_bytes])
                _raise_injected("write", path, rule)
    try:
        return os.write(fd, data)
    except OSError as e:
        _count_fault("write", e.errno)
        raise


def fsync(fd: int) -> None:
    _gate("fsync", _fd_path(fd))
    try:
        os.fsync(fd)
    except OSError as e:
        _count_fault("fsync", e.errno)
        raise


def ftruncate(fd: int, size: int) -> None:
    _gate("truncate", _fd_path(fd))
    try:
        os.ftruncate(fd, size)
    except OSError as e:
        _count_fault("truncate", e.errno)
        raise


def replace(src: str, dst: str) -> None:
    _gate("replace", dst)
    try:
        os.replace(src, dst)
    except OSError as e:
        _count_fault("replace", e.errno)
        raise


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-completed rename/create in it is
    durable.  fsyncing the file alone persists its *contents*; the rename
    that makes the file *reachable* lives in the directory, and a crash
    between the two can lose it (the classic rename-durability gap)."""
    _gate("fsync_dir", path)
    fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    try:
        try:
            os.fsync(fd)
        except OSError as e:
            _count_fault("fsync_dir", e.errno)
            raise
    finally:
        os.close(fd)


# ------------------------------------------------------------- composed ops


def write_file(
    path: str,
    data: bytes,
    site: str = "file",
    durable: bool = False,
    mode: int = 0o644,
) -> None:
    """Write ``path`` in place through the seam (no rename).  ``durable``
    adds a file fsync.  For data whose durability is not load-bearing
    (best-effort diagnostics) or whose target cannot be renamed over.
    ``mode`` defaults to the builtin-open 0644 these helpers replaced —
    several of the files (CDI specs, daemon.env, the dnsnames config) are
    read by OTHER processes/containers, possibly as non-root."""
    fd = open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
    try:
        view = memoryview(data)
        while view:
            n = write(fd, view)
            if n <= 0:
                raise OSError(f"short write of {len(view)} byte(s) to {path}")
            view = view[n:]
        if durable:
            fsync(fd)
            metrics.STORAGE_FSYNCS_TOTAL.labels(site).inc()
    finally:
        close(fd)


def atomic_replace(
    path: str,
    data: bytes,
    site: str = "file",
    tmp_path: Optional[str] = None,
    durable: bool = True,
    mode: int = 0o644,
) -> None:
    """The atomic durable-write idiom, in one place: write a temp file,
    fsync it, rename over ``path``, fsync the parent directory — so a
    crash at any point leaves either the old complete file or the new
    complete file, reachable.  A failed tmp fsync NEVER renames over the
    good file (the fail-stop snapshot contract); the tmp is unlinked
    best-effort and the error propagates.  ``durable=False`` skips both
    fsyncs for atomic-but-rewritten-on-a-cadence data (registration
    files).  Fsyncs are counted per call site
    (``tpudra_storage_fsyncs_total{site}``) so the durability of each
    family of files is auditable from metrics alone."""
    tmp = tmp_path if tmp_path is not None else path + ".tmp"
    try:
        write_file(tmp, data, site=site, durable=durable, mode=mode)
        replace(tmp, path)
        if durable:
            fsync_dir(os.path.dirname(path) or ".")
            metrics.STORAGE_FSYNCS_TOTAL.labels(site).inc()
    except OSError:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


# Env arming happens once at import, like the crashpoint env reads: the
# subprocess crash sweeps set TPUDRA_STORAGE_FAULT before exec and the
# whole plugin process runs under the plan.
_active_plan = _plan_from_env()

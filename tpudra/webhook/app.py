"""Admission logic + HTTPS server.

The analog of cmd/webhook/main.go:115-292 and resource.go:34-152:

- ``/validate-resource-claim-parameters`` receives an AdmissionReview for a
  ResourceClaim or ResourceClaimTemplate (resource.k8s.io v1 / v1beta1 /
  v1beta2). Like the reference (resource.go:84-152, via the k8s conversion
  scheme) the object is explicitly converted to the v1 shape before
  validation: v1beta1's flat DeviceRequest fields are folded into the
  ``exactly`` nesting v1beta2/v1 use; unknown versions are denied rather
  than validated on a guessed shape.
- every opaque config entry addressed to one of our two drivers is
  strict-decoded, normalized, and validated; unknown fields, wrong kinds and
  semantic errors all become a deny with a precise message
- configs for other drivers are ignored (not our webhook's business)
"""

from __future__ import annotations

import http.server
import json
import logging
import ssl
import threading
from typing import Optional

from tpudra import COMPUTE_DOMAIN_DRIVER_NAME, TPU_DRIVER_NAME
from tpudra.api import DecodeError, decode_config

logger = logging.getLogger(__name__)

OUR_DRIVERS = (TPU_DRIVER_NAME, COMPUTE_DOMAIN_DRIVER_NAME)
WEBHOOK_PATH = "/validate-resource-claim-parameters"


SUPPORTED_VERSIONS = ("v1", "v1beta1", "v1beta2")

# ExactDeviceRequest fields that v1beta1 carried flat on DeviceRequest
# (k8s.io/api/resource/v1beta1/types.go DeviceRequest vs v1 ExactDeviceRequest).
_EXACT_REQUEST_FIELDS = (
    "deviceClassName",
    "selectors",
    "allocationMode",
    "count",
    "adminAccess",
    "tolerations",
    "capacity",
)


def convert_claim_spec_to_v1(spec: dict, version: str) -> dict:
    """Convert a ResourceClaimSpec from the given resource.k8s.io version to
    the v1 shape (the reference does this through the k8s conversion scheme,
    resource.go:108-115).

    v1 and v1beta2 share the DeviceRequest shape (name + exactly |
    firstAvailable). v1beta1 carried the exact-request fields flat on the
    request; fold them under ``exactly``. Raises ValueError on an
    unsupported version.
    """
    if version in ("v1", "v1beta2"):
        return spec
    if version != "v1beta1":
        raise ValueError(f"unsupported resource.k8s.io version {version!r}")
    out = dict(spec)
    devices = dict(spec.get("devices") or {})
    requests = []
    for req in devices.get("requests") or []:
        if not isinstance(req, dict) or "firstAvailable" in req or "exactly" in req:
            # Prioritized-list requests are already v1-shaped; a request that
            # somehow carries "exactly" is already converted.
            requests.append(req)
            continue
        exact = {k: req[k] for k in _EXACT_REQUEST_FIELDS if k in req}
        converted = {k: v for k, v in req.items() if k not in _EXACT_REQUEST_FIELDS}
        converted["exactly"] = exact
        requests.append(converted)
    if requests:
        devices["requests"] = requests
    if devices:
        out["devices"] = devices
    return out


def _claim_spec_from_object(obj: dict, version: str) -> tuple[Optional[dict], str]:
    """Extract the v1-converted ResourceClaimSpec from a claim or template
    (resource.go:84-152); returns (spec, kind)."""
    kind = obj.get("kind", "")
    if kind == "ResourceClaim":
        spec = obj.get("spec", {})
    elif kind == "ResourceClaimTemplate":
        spec = obj.get("spec", {}).get("spec", {})
    else:
        return None, kind
    return convert_claim_spec_to_v1(spec, version), kind


def _version_for_object(obj: dict, resource: Optional[dict]) -> str:
    """The resource.k8s.io version to convert from: the AdmissionReview's
    request.resource wins (what the API server actually sent, the
    reference's switch on ar.Request.Resource), falling back to the
    object's own apiVersion."""
    if resource and resource.get("group") == "resource.k8s.io":
        return resource.get("version", "")
    api_version = obj.get("apiVersion", "")
    if "/" in api_version:
        group, _, version = api_version.partition("/")
        if group == "resource.k8s.io":
            return version
    return "v1"


def validate_claim_object(obj: dict, resource: Optional[dict] = None) -> list[str]:
    """All validation errors for one claim/template object (empty = admit)."""
    version = _version_for_object(obj, resource)
    if version not in SUPPORTED_VERSIONS:
        return [f"unsupported resource.k8s.io version {version!r}"]
    spec, kind = _claim_spec_from_object(obj, version)
    if spec is None:
        return [f"unsupported object kind {kind!r}"]
    errors: list[str] = []
    entries = spec.get("devices", {}).get("config", [])
    # Request names addressable from config entries, read from the
    # *converted* v1 shape (this is why conversion runs first: the checks
    # below are written against one spec shape only).  A prioritized-list
    # subrequest is addressed as "request/subrequest"; naming the parent
    # request alone also matches.
    known_requests: set[str] = set()
    for req in spec.get("devices", {}).get("requests") or []:
        rname = req.get("name", "")
        known_requests.add(rname)
        for sub in req.get("firstAvailable") or []:
            known_requests.add(f"{rname}/{sub.get('name', '')}")
    for i, entry in enumerate(entries):
        opaque = entry.get("opaque")
        if not opaque:
            continue
        if opaque.get("driver") not in OUR_DRIVERS:
            continue
        for rname in entry.get("requests") or []:
            if rname not in known_requests:
                errors.append(
                    f"spec.devices.config[{i}].requests: no request named "
                    f"{rname!r} in this claim (have: "
                    f"{sorted(known_requests) or 'none'})"
                )
        path = f"spec.devices.config[{i}].opaque.parameters"
        params = opaque.get("parameters") or {}
        if not isinstance(params, dict):
            errors.append(f"{path}: must be an object, got {type(params).__name__}")
            continue
        try:
            config = decode_config(params, strict=True)
            config.normalize()
            config.validate()
        except (DecodeError, ValueError) as e:
            errors.append(f"{path}: {e}")
        except Exception as e:  # noqa: BLE001 — a deny beats a dropped review
            errors.append(f"{path}: internal validation error: {e}")
    return errors


def admit_review(review: dict) -> dict:
    """AdmissionReview request → AdmissionReview response
    (admitResourceClaimParameters, main.go:201-292)."""
    request = review.get("request") or {}
    uid = request.get("uid", "")
    obj = request.get("object") or {}
    errors = validate_claim_object(obj, request.get("resource"))
    response: dict = {"uid": uid, "allowed": not errors}
    if errors:
        response["status"] = {
            "code": 422,
            "message": "; ".join(errors),
        }
    return {
        "apiVersion": review.get("apiVersion", "admission.k8s.io/v1"),
        "kind": "AdmissionReview",
        "response": response,
    }


class WebhookServer:
    """HTTPS (or plain-HTTP for tests) admission endpoint."""

    def __init__(
        self,
        port: int = 0,
        cert_file: Optional[str] = None,
        key_file: Optional[str] = None,
        host: str = "0.0.0.0",
    ):
        self._host = host
        self._port = port
        self._cert = cert_file
        self._key = key_file
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> None:
        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self) -> None:  # noqa: N802 — http.server API
                if self.path != WEBHOOK_PATH:
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    review = json.loads(self.rfile.read(length))
                except json.JSONDecodeError:
                    self.send_error(400, "malformed AdmissionReview")
                    return
                try:
                    body = json.dumps(admit_review(review)).encode()
                except Exception as e:  # noqa: BLE001 — always answer the review
                    logger.exception("admission review failed")
                    body = json.dumps(
                        {
                            "apiVersion": review.get("apiVersion", "admission.k8s.io/v1"),
                            "kind": "AdmissionReview",
                            "response": {
                                "uid": (review.get("request") or {}).get("uid", ""),
                                "allowed": False,
                                "status": {"code": 500, "message": f"webhook error: {e}"},
                            },
                        }
                    ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802
                if self.path == "/healthz":
                    self.send_response(200)
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):  # noqa: D102
                logger.debug("webhook: " + fmt, *args)

        class Server(http.server.ThreadingHTTPServer):
            """TLS is wrapped per connection on the handler thread: wrapping
            the listening socket would run the handshake inside accept() on
            the single serve_forever thread, letting one stalled client
            block every admission request."""

            ssl_context: Optional[ssl.SSLContext] = None

            def finish_request(self, request, client_address):
                if self.ssl_context is not None:
                    request.settimeout(10.0)
                    request = self.ssl_context.wrap_socket(request, server_side=True)
                self.RequestHandlerClass(request, client_address, self)

        self._server = Server((self._host, self._port), Handler)
        if self._cert and self._key:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self._cert, self._key)
            self._server.ssl_context = ctx
        self._port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True, name="webhook").start()
        logger.info("webhook serving on %s:%d", self._host, self._port)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

"""Admission logic + HTTPS server.

The analog of cmd/webhook/main.go:115-292 and resource.go:34-152:

- ``/validate-resource-claim-parameters`` receives an AdmissionReview for a
  ResourceClaim or ResourceClaimTemplate (resource.k8s.io v1 / v1beta1 /
  v1beta2 — older versions are shape-compatible for the fields we touch, the
  conversion the reference does explicitly)
- every opaque config entry addressed to one of our two drivers is
  strict-decoded, normalized, and validated; unknown fields, wrong kinds and
  semantic errors all become a deny with a precise message
- configs for other drivers are ignored (not our webhook's business)
"""

from __future__ import annotations

import http.server
import json
import logging
import ssl
import threading
from typing import Optional

from tpudra import COMPUTE_DOMAIN_DRIVER_NAME, TPU_DRIVER_NAME
from tpudra.api import DecodeError, decode_config

logger = logging.getLogger(__name__)

OUR_DRIVERS = (TPU_DRIVER_NAME, COMPUTE_DOMAIN_DRIVER_NAME)
WEBHOOK_PATH = "/validate-resource-claim-parameters"


def _claim_spec_from_object(obj: dict) -> tuple[Optional[dict], str]:
    """Extract the ResourceClaimSpec from a claim or template
    (resource.go:84-152); returns (spec, kind)."""
    kind = obj.get("kind", "")
    if kind == "ResourceClaim":
        return obj.get("spec", {}), kind
    if kind == "ResourceClaimTemplate":
        return obj.get("spec", {}).get("spec", {}), kind
    return None, kind


def validate_claim_object(obj: dict) -> list[str]:
    """All validation errors for one claim/template object (empty = admit)."""
    spec, kind = _claim_spec_from_object(obj)
    if spec is None:
        return [f"unsupported object kind {kind!r}"]
    errors: list[str] = []
    entries = spec.get("devices", {}).get("config", [])
    for i, entry in enumerate(entries):
        opaque = entry.get("opaque")
        if not opaque:
            continue
        if opaque.get("driver") not in OUR_DRIVERS:
            continue
        path = f"spec.devices.config[{i}].opaque.parameters"
        params = opaque.get("parameters") or {}
        if not isinstance(params, dict):
            errors.append(f"{path}: must be an object, got {type(params).__name__}")
            continue
        try:
            config = decode_config(params, strict=True)
            config.normalize()
            config.validate()
        except (DecodeError, ValueError) as e:
            errors.append(f"{path}: {e}")
        except Exception as e:  # noqa: BLE001 — a deny beats a dropped review
            errors.append(f"{path}: internal validation error: {e}")
    return errors


def admit_review(review: dict) -> dict:
    """AdmissionReview request → AdmissionReview response
    (admitResourceClaimParameters, main.go:201-292)."""
    request = review.get("request") or {}
    uid = request.get("uid", "")
    obj = request.get("object") or {}
    errors = validate_claim_object(obj)
    response: dict = {"uid": uid, "allowed": not errors}
    if errors:
        response["status"] = {
            "code": 422,
            "message": "; ".join(errors),
        }
    return {
        "apiVersion": review.get("apiVersion", "admission.k8s.io/v1"),
        "kind": "AdmissionReview",
        "response": response,
    }


class WebhookServer:
    """HTTPS (or plain-HTTP for tests) admission endpoint."""

    def __init__(
        self,
        port: int = 0,
        cert_file: Optional[str] = None,
        key_file: Optional[str] = None,
        host: str = "0.0.0.0",
    ):
        self._host = host
        self._port = port
        self._cert = cert_file
        self._key = key_file
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> None:
        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self) -> None:  # noqa: N802 — http.server API
                if self.path != WEBHOOK_PATH:
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    review = json.loads(self.rfile.read(length))
                except json.JSONDecodeError:
                    self.send_error(400, "malformed AdmissionReview")
                    return
                try:
                    body = json.dumps(admit_review(review)).encode()
                except Exception as e:  # noqa: BLE001 — always answer the review
                    logger.exception("admission review failed")
                    body = json.dumps(
                        {
                            "apiVersion": review.get("apiVersion", "admission.k8s.io/v1"),
                            "kind": "AdmissionReview",
                            "response": {
                                "uid": (review.get("request") or {}).get("uid", ""),
                                "allowed": False,
                                "status": {"code": 500, "message": f"webhook error: {e}"},
                            },
                        }
                    ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802
                if self.path == "/healthz":
                    self.send_response(200)
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):  # noqa: D102
                logger.debug("webhook: " + fmt, *args)

        class Server(http.server.ThreadingHTTPServer):
            """TLS is wrapped per connection on the handler thread: wrapping
            the listening socket would run the handshake inside accept() on
            the single serve_forever thread, letting one stalled client
            block every admission request."""

            ssl_context: Optional[ssl.SSLContext] = None

            def finish_request(self, request, client_address):
                if self.ssl_context is not None:
                    request.settimeout(10.0)
                    request = self.ssl_context.wrap_socket(request, server_side=True)
                self.RequestHandlerClass(request, client_address, self)

        self._server = Server((self._host, self._port), Handler)
        if self._cert and self._key:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self._cert, self._key)
            self._server.ssl_context = ctx
        self._port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True, name="webhook").start()
        logger.info("webhook serving on %s:%d", self._host, self._port)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

"""Validating admission webhook.

The analog of cmd/webhook/: catches malformed opaque device configs at
``kubectl apply`` time instead of at NodePrepareResources time (where the
only signal is a pod stuck in ContainerCreating).
"""

from tpudra.webhook.app import WebhookServer, admit_review

__all__ = ["WebhookServer", "admit_review"]

"""Admission webhook binary (the cmd/webhook analog)."""

from __future__ import annotations

import argparse
import logging

from tpudra.flags import (
    add_common_flags,
    env_default,
    install_stop_handlers,
    setup_common,
)

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("tpudra-webhook")
    add_common_flags(p)
    p.add_argument("--port", type=int, default=int(env_default("PORT", "8443")))
    p.add_argument("--tls-cert", default=env_default("TLS_CERT"))
    p.add_argument("--tls-key", default=env_default("TLS_KEY"))
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_common(args)

    from tpudra.webhook import WebhookServer

    srv = WebhookServer(
        port=args.port, cert_file=args.tls_cert or None, key_file=args.tls_key or None
    )
    stop = install_stop_handlers()
    try:
        srv.start()
        logger.info("webhook up on :%d (tls=%s)", srv.port, bool(args.tls_cert))
        stop.wait()
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

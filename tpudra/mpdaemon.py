"""The multi-process control daemon (the MPS control daemon analog).

The TPU kubelet plugin stamps a per-claim Deployment running this binary
(``templates/multi-process-daemon.tmpl.yaml``, reference
mps-control-daemon.tmpl.yaml); consumer containers of the claim get
``TPUDRA_MP_PIPE_DIRECTORY`` pointing at the shared hostPath this daemon
owns.  The broker contract:

- on startup the daemon materializes the claim's sharing policy into
  ``limits.json`` in the pipe directory (chip UUIDs, active-TensorCore
  percentage, per-chip pinned-HBM limits — resolved by the plugin from the
  opaque MultiProcessConfig, tpudra/api/sharing.py normalized_limits);
- it serves a unix socket ``control.sock`` there: clients ATTACH/DETACH
  (the admission point a hardware broker would enforce limits at) and
  anyone may ask STATUS;
- the readiness probe is ``tpu-mp-control-daemon status`` — exit 0 iff the
  socket answers READY, which is what lets the plugin's AssertReady (and
  the pod's readinessProbe) gate workload prepare on the broker being up.

Subcommands: ``run`` (default) and ``status``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import socketserver
import threading

logger = logging.getLogger(__name__)

CONTROL_SOCK = "control.sock"
LIMITS_FILE = "limits.json"


def _pipe_dir(env=None) -> str:
    env = os.environ if env is None else env
    d = env.get("TPUDRA_MP_PIPE_DIRECTORY", "")
    if not d:
        raise SystemExit("TPUDRA_MP_PIPE_DIRECTORY is not set")
    return d


class ControlDaemon:
    def __init__(self, pipe_dir: str, env=None):
        env = os.environ if env is None else env
        self.pipe_dir = pipe_dir
        self.limits = {
            "chipUUIDs": [
                u for u in env.get("TPUDRA_MP_CHIP_UUIDS", "").split(",") if u
            ],
            "activeTensorCorePercentage": int(
                env.get("TPUDRA_MP_ACTIVE_TENSORCORE_PERCENTAGE", "100") or "100"
            ),
            # "uuid=limitMi;..." as rendered by the plugin.
            "pinnedHbmLimits": dict(
                kv.split("=", 1)
                for kv in env.get("TPUDRA_MP_PINNED_HBM_LIMITS", "").split(";")
                if "=" in kv
            ),
            # Platform truth vs broker behavior (VERDICT r4 #5): the plugin
            # probes whether a second process can open the chip while held
            # (DeviceLib.multiprocess_mode) and passes it through; the
            # broker's own limit enforcement is cooperative either way —
            # nothing enforces TensorCore percentages in TPU hardware, and
            # an "exclusive" platform additionally means concurrent process
            # sharing is impossible (attachment is time-multiplexed).
            "platformMode": env.get("TPUDRA_MP_PLATFORM_MODE", "") or "unknown",
            "enforcement": "cooperative",
        }
        self._clients: set[str] = set()
        self._lock = threading.Lock()
        self._server: socketserver.ThreadingUnixStreamServer | None = None

    @property
    def sock_path(self) -> str:
        return os.path.join(self.pipe_dir, CONTROL_SOCK)

    def start(self) -> None:
        os.makedirs(self.pipe_dir, exist_ok=True)
        with open(os.path.join(self.pipe_dir, LIMITS_FILE), "w") as f:
            json.dump(self.limits, f, indent=2)
        if os.path.exists(self.sock_path):
            os.unlink(self.sock_path)
        daemon = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                line = self.rfile.readline().decode(errors="replace").strip()
                verb, _, arg = line.partition(" ")
                with daemon._lock:
                    if verb == "ATTACH" and arg:
                        daemon._clients.add(arg)
                        resp = "OK " + json.dumps(daemon.limits)
                    elif verb == "DETACH" and arg:
                        daemon._clients.discard(arg)
                        resp = "OK"
                    elif verb == "STATUS":
                        resp = (
                            f"READY {len(daemon._clients)} "
                            f"platform={daemon.limits['platformMode']} "
                            f"enforcement={daemon.limits['enforcement']}"
                        )
                    else:
                        resp = f"ERR unknown verb {verb!r}"
                self.wfile.write((resp + "\n").encode())

        self._server = socketserver.ThreadingUnixStreamServer(self.sock_path, Handler)
        self._server.daemon_threads = True
        threading.Thread(
            target=self._server.serve_forever, daemon=True, name="mp-control"
        ).start()
        logger.info(
            "mp control daemon up: %d chip(s), %d%% TensorCore, socket %s",
            len(self.limits["chipUUIDs"]),
            self.limits["activeTensorCorePercentage"],
            self.sock_path,
        )

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass


def query(pipe_dir: str, line: str, timeout: float = 2.0) -> str:
    with socket.socket(socket.AF_UNIX) as s:
        s.settimeout(timeout)
        s.connect(os.path.join(pipe_dir, CONTROL_SOCK))
        s.sendall((line + "\n").encode())
        return s.makefile().readline().strip()


def status(pipe_dir: str | None = None) -> int:
    """Probe entry: exit 0 iff the broker answers READY."""
    try:
        resp = query(pipe_dir or _pipe_dir(), "STATUS")
    except OSError as e:
        print(f"NOT_READY: {e}")
        return 1
    print(resp)
    return 0 if resp.startswith("READY") else 1


def main(argv=None) -> int:
    from tpudra.flags import add_common_flags, setup_common

    p = argparse.ArgumentParser("tpu-mp-control-daemon")
    sub = p.add_subparsers(dest="command")
    run_p = sub.add_parser("run", help="run the per-claim control daemon (default)")
    add_common_flags(run_p)
    sub.add_parser("status", help="probe: exit 0 iff the broker is READY")
    args = p.parse_args(argv)

    if args.command == "status":
        return status()
    if args.command is None:
        # Bare invocation (the Deployment template's command) means `run`;
        # re-parse so the run subparser's common flags are populated.
        args = p.parse_args(["run"] if argv is None else ["run", *argv])

    setup_common(args)  # shared logging/gates, honors LOG_LEVEL/LOG_VERBOSITY
    from tpudra.flags import install_stop_handlers

    stop = install_stop_handlers()
    daemon = ControlDaemon(_pipe_dir())
    try:
        daemon.start()
        stop.wait()
    finally:
        daemon.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Runtime lock-acquisition witness (the lockdep dynamic side).

tpudra-lockgraph's static model (tpudra/analysis/lockmodel.py) derives the
lock acquisition graph from the AST; this module is its runtime
cross-check.  With ``TPUDRA_LOCK_WITNESS=1`` in the environment, the
lock-heavy modules construct *instrumented* primitives (``make_lock`` /
``make_rlock`` / ``make_condition``; ``Flock`` hooks in directly) that
maintain a per-thread held stack and append every first-seen acquisition
edge "A was held when B was acquired" to a JSONL witness log
(``TPUDRA_LOCK_WITNESS_LOG``, default ``tpudra-lock-witness.jsonl`` in the
working directory).  ``python -m tpudra.analysis --witness <log>`` then
merges the log into the static graph: witnessed cycles and edges the
static model lacks (model gaps) fail the run; static edges never
witnessed are a coverage report.

With the variable unset (every production path), the factories return the
plain ``threading`` primitives — zero wrapping, zero overhead.

Conventions shared with the static model:

- IDs are lock *classes*, not instances (every ``Informer``'s store lock
  is one node, every claim-uid flock is ``flock:claim-uid``).
- Same-ID edges are never recorded: for re-entrant locks they are
  re-entry, for families (claim-uid flocks, per-device mutexes) intra-
  family order is governed by LOCK-ORDER's ``sorted()`` check, which a
  class-collapsed witness cannot re-derive.
- ``Condition.wait`` keeps the cond on the held stack: the waiting thread
  is blocked and records nothing, and the implicit re-acquire on wake is
  not a new ordering decision.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional, Union

ENV_WITNESS = "TPUDRA_LOCK_WITNESS"
ENV_WITNESS_LOG = "TPUDRA_LOCK_WITNESS_LOG"
DEFAULT_LOG = "tpudra-lock-witness.jsonl"


def enabled() -> bool:
    return os.environ.get(ENV_WITNESS, "") not in ("", "0")


def log_path() -> str:
    return os.environ.get(ENV_WITNESS_LOG, "") or os.path.join(
        os.getcwd(), DEFAULT_LOG
    )


# ----------------------------------------------------------------- recording

_tls = threading.local()
_sink_guard = threading.Lock()
_sink = None  # opened lazily, OUTSIDE _sink_guard (no open-under-lock)
_written: set = set()


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = []
        _tls.held = held
    return held


def _emit(record: dict) -> None:
    global _sink
    if _sink is None:
        # Open before taking the guard; a racing double-open leaves one
        # extra O_APPEND handle to close, never a torn line.
        fh = open(log_path(), "a", encoding="utf-8")
        with _sink_guard:
            if _sink is None:
                _sink = fh
                fh = None
        if fh is not None:
            fh.close()
    line = json.dumps(record, sort_keys=True) + "\n"
    with _sink_guard:
        _sink.write(line)
        _sink.flush()


def note_acquire(lock_id: str) -> None:
    """Record that the current thread acquired ``lock_id``: one ``lock``
    record per first-seen ID, one ``edge`` record per first-seen (held,
    acquired) pair.  Called by the instrumented wrappers and by
    ``Flock.acquire`` — must never itself take an instrumented lock."""
    held = _held()
    thread = threading.current_thread().name
    new_records = []
    with _sink_guard:
        known = ("lock", lock_id) in _written
        if not known:
            _written.add(("lock", lock_id))
    if not known:
        new_records.append({"t": "lock", "lock": lock_id, "thread": thread})
    for holder in dict.fromkeys(held):  # de-dup, order-preserving
        if holder == lock_id:
            continue  # re-entry / intra-family: not an ordering edge
        key = ("edge", holder, lock_id)
        with _sink_guard:
            seen = key in _written
            if not seen:
                _written.add(key)
        if not seen:
            new_records.append(
                {"t": "edge", "from": holder, "to": lock_id, "thread": thread}
            )
    held.append(lock_id)
    for record in new_records:
        _emit(record)


def note_release(lock_id: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == lock_id:
            del held[i]
            return


def held_by_current_thread() -> tuple:
    """The current thread's held-ID stack (tests)."""
    return tuple(_held())


def reset_for_tests() -> None:
    """Drop the in-process dedup/sink state so a test can witness into a
    fresh log file."""
    global _sink, _written
    with _sink_guard:
        sink, _sink = _sink, None
        _written = set()
    if sink is not None:
        sink.close()


# ------------------------------------------------------------------ wrappers


class _WitnessLock:
    """threading.Lock with acquisition-edge recording."""

    _reentrant = False

    def __init__(self, lock_id: str):
        self._inner = self._make_inner()
        self.lock_id = lock_id

    @staticmethod
    def _make_inner():
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            note_acquire(self.lock_id)
        return ok

    def release(self) -> None:
        note_release(self.lock_id)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _WitnessRLock(_WitnessLock):
    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()

    def locked(self) -> bool:  # RLock has no locked(); mirror 3.12 surface
        return bool(getattr(self._inner, "_is_owned", lambda: False)())


class _WitnessCondition:
    """threading.Condition with acquisition-edge recording.  ``wait`` keeps
    the cond on the held stack (see module docstring)."""

    def __init__(self, lock_id: str):
        self._inner = threading.Condition()
        self.lock_id = lock_id

    def __enter__(self):
        self._inner.__enter__()
        note_acquire(self.lock_id)
        return self

    def __exit__(self, *exc):
        note_release(self.lock_id)
        return self._inner.__exit__(*exc)

    def acquire(self, *args):
        ok = self._inner.acquire(*args)
        if ok:
            note_acquire(self.lock_id)
        return ok

    def release(self) -> None:
        note_release(self.lock_id)
        self._inner.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._inner.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._inner.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


LockLike = Union[threading.Lock, _WitnessLock]
ConditionLike = Union[threading.Condition, _WitnessCondition]


def make_lock(lock_id: str):
    """A mutex carrying a stable witness ID.  Plain ``threading.Lock()``
    unless the witness is armed — the ID string doubles as the static
    model's name for this lock (lockmodel.py reads it off the call)."""
    return _WitnessLock(lock_id) if enabled() else threading.Lock()


def make_rlock(lock_id: str):
    return _WitnessRLock(lock_id) if enabled() else threading.RLock()


def make_condition(lock_id: str):
    return _WitnessCondition(lock_id) if enabled() else threading.Condition()


# ------------------------------------------------------------------- reading


def read_log(path: str) -> tuple[set, set]:
    """(lock IDs, edges) recorded in a witness log.  Malformed lines are
    skipped — a crashed witness process may tear its final line."""
    locks: set = set()
    edges: set = set()
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("t") == "lock" and rec.get("lock"):
                    locks.add(rec["lock"])
                elif rec.get("t") == "edge" and rec.get("from") and rec.get("to"):
                    locks.add(rec["from"])
                    locks.add(rec["to"])
                    edges.add((rec["from"], rec["to"]))
    except FileNotFoundError:
        pass
    return locks, edges

{{/* Chart name, honoring nameOverride. */}}
{{ define "tpu-dra-driver.name" }}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" -}}
{{ end }}

{{/* Fully qualified app name. */}}
{{ define "tpu-dra-driver.fullname" }}
{{- if .Values.fullnameOverride -}}
{{- .Values.fullnameOverride | trunc 63 | trimSuffix "-" -}}
{{- else -}}
{{- printf "%s-%s" .Release.Name (include "tpu-dra-driver.name" .) | trunc 63 | trimSuffix "-" -}}
{{- end -}}
{{ end }}

{{/* Install namespace, honoring namespaceOverride. */}}
{{ define "tpu-dra-driver.namespace" }}
{{- default .Release.Namespace .Values.namespaceOverride -}}
{{ end }}

{{/* Image reference; empty tag = appVersion. */}}
{{ define "tpu-dra-driver.image" }}
{{- printf "%s:%s" .Values.image.repository (default .Chart.AppVersion .Values.image.tag) -}}
{{ end }}

{{/* Common labels. */}}
{{ define "tpu-dra-driver.labels" }}
app.kubernetes.io/name: {{ include "tpu-dra-driver.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{ end }}

{{/* ServiceAccount name. */}}
{{ define "tpu-dra-driver.serviceAccountName" }}
{{- if .Values.serviceAccount.create -}}
{{- default (include "tpu-dra-driver.fullname" .) .Values.serviceAccount.name -}}
{{- else -}}
{{- default "default" .Values.serviceAccount.name -}}
{{- end -}}
{{ end }}

{{/* Comma-separated gate=bool pairs for the FEATURE_GATES env. */}}
{{ define "tpu-dra-driver.featureGates" }}
{{- range $k, $v := .Values.featureGates -}}{{ $k }}={{ $v }},{{- end -}}
{{ end }}

{{/* Webhook service DNS name (what the cert must cover). */}}
{{ define "tpu-dra-driver.webhookHost" }}
{{- printf "%s-webhook.%s.svc" (include "tpu-dra-driver.fullname" .) (include "tpu-dra-driver.namespace" .) -}}
{{ end }}

// tpu-slicewatchd — per-node slice coordination daemon.
//
// The TPU-native replacement for the closed-source nvidia-imex daemon the
// reference supervises (cmd/compute-domain-daemon/process.go, main.go:49-50):
// where IMEX brokers cross-node GPU memory export over NVLink, a TPU slice's
// ICI fabric needs no runtime broker — what the ComputeDomain machinery needs
// from this daemon is exactly the part it *did* use IMEX for:
//
//   1. peer liveness over DCN: every daemon heartbeats every other host in
//      the slice (UDP), so "the domain is formed" is an observable state;
//   2. a READY probe: a TCP status socket answering "Q\n" with "READY\n"
//      once all expected peers are alive (the nvidia-imex-ctl -q analog,
//      reference main.go:429-438);
//   3. config-by-files + reload-by-signal: peers come from a static
//      nodes.cfg of DNS names indirected through /etc/hosts; SIGHUP
//      re-resolves (the reference's SIGUSR1-to-imex dance, main.go:405).
//
// Single-threaded poll(2) event loop; no dependencies beyond POSIX.
//
// Usage:
//   tpu-slicewatchd --nodes-config nodes.cfg [--hosts /etc/hosts]
//                   --index N --expected M
//                   [--status-port 7173] [--peer-port 7174]
//                   [--heartbeat-ms 500] [--stale-ms 3000]

#include <arpa/inet.h>
#include <netinet/in.h>
#include <poll.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

namespace {

volatile sig_atomic_t g_reload = 0;
volatile sig_atomic_t g_stop = 0;

void on_sighup(int) { g_reload = 1; }
void on_term(int) { g_stop = 1; }

int64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

struct Config {
  std::string nodes_config;
  std::string hosts_path = "/etc/hosts";
  int index = 0;
  int expected = 1;
  int status_port = 7173;
  int peer_port = 7174;
  int heartbeat_ms = 500;
  int stale_ms = 3000;
  // Source-address verification rejects spoofed liveness, but drops real
  // heartbeats where the CNI SNATs pod traffic or a multi-homed sender's
  // routing picks a different egress address than the one in /etc/hosts —
  // such clusters opt out with --no-hb-source-check.
  bool hb_source_check = true;
};

struct Peer {
  std::string name;
  std::string ip;  // empty or "0.0.0.0" = not yet known
  int port = 0;    // 0 = the shared --peer-port
  int64_t last_seen_ms = 0;
};

// Parse the hosts file ourselves: the whole point of the /etc/hosts
// indirection is that membership changes land as file rewrites, and libc
// resolvers cache — reading the file on SIGHUP gives deterministic reload
// semantics (the reason the reference signals its daemon, dnsnames.go:145).
std::map<std::string, std::string> parse_hosts(const std::string& path) {
  std::map<std::string, std::string> out;
  std::ifstream f(path);
  std::string line;
  while (std::getline(f, line)) {
    auto hash = line.find('#');
    if (hash != std::string::npos) line = line.substr(0, hash);
    std::istringstream ss(line);
    std::string ip, name;
    if (!(ss >> ip)) continue;
    while (ss >> name) out[name] = ip;
  }
  return out;
}

// nodes.cfg lines are DNS names, optionally "name:port" — the port override
// exists for single-host testing, where every peer is 127.0.0.1 and only the
// port distinguishes daemons; production files carry bare names.
std::vector<std::pair<std::string, int>> parse_nodes_config(
    const std::string& path) {
  std::vector<std::pair<std::string, int>> names;
  std::ifstream f(path);
  std::string line;
  while (std::getline(f, line)) {
    while (!line.empty() && (line.back() == '\r' || line.back() == ' ')) line.pop_back();
    if (line.empty() || line[0] == '#') continue;
    auto colon = line.rfind(':');
    int port = 0;
    if (colon != std::string::npos) {
      port = atoi(line.substr(colon + 1).c_str());
      line = line.substr(0, colon);
    }
    names.emplace_back(line, port);
  }
  return names;
}

class SliceWatch {
 public:
  explicit SliceWatch(const Config& cfg) : cfg_(cfg) {}

  bool init() {
    peer_fd_ = ::socket(AF_INET, SOCK_DGRAM, 0);
    if (peer_fd_ < 0) return perr("peer socket");
    int one = 1;
    setsockopt(peer_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(cfg_.peer_port);
    if (bind(peer_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return perr("bind peer port");

    status_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (status_fd_ < 0) return perr("status socket");
    setsockopt(status_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in saddr{};
    saddr.sin_family = AF_INET;
    saddr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    saddr.sin_port = htons(cfg_.status_port);
    if (bind(status_fd_, reinterpret_cast<sockaddr*>(&saddr), sizeof(saddr)) < 0)
      return perr("bind status port");
    if (listen(status_fd_, 8) < 0) return perr("listen status");
    reload();
    return true;
  }

  void reload() {
    auto names = parse_nodes_config(cfg_.nodes_config);
    auto hosts = parse_hosts(cfg_.hosts_path);
    std::vector<Peer> next;
    for (size_t i = 0; i < names.size(); i++) {
      Peer p;
      p.name = names[i].first;
      p.port = names[i].second;
      auto it = hosts.find(p.name);
      if (it != hosts.end() && it->second != "0.0.0.0") p.ip = it->second;
      // Preserve liveness across reloads for unchanged IPs.
      if (i < peers_.size() && peers_[i].ip == p.ip)
        p.last_seen_ms = peers_[i].last_seen_ms;
      next.push_back(p);
    }
    peers_ = std::move(next);
    fprintf(stderr, "[slicewatchd] reloaded: %zu names, %d resolved\n",
            peers_.size(), resolved_count());
  }

  int resolved_count() const {
    int n = 0;
    for (const auto& p : peers_)
      if (!p.ip.empty()) n++;
    return n;
  }

  bool ready() const {
    // READY = the whole slice is formed: every one of the expected hosts is
    // resolved and recently alive.  A 1-host slice is trivially READY.
    if (cfg_.expected <= 1) return true;
    if (resolved_count() < cfg_.expected) return false;
    int64_t now = now_ms();
    int alive = 0;
    for (size_t i = 0; i < peers_.size(); i++) {
      if (peers_[i].ip.empty()) continue;
      if (static_cast<int>(i) == cfg_.index ||
          now - peers_[i].last_seen_ms <= cfg_.stale_ms)
        alive++;
    }
    return alive >= cfg_.expected;
  }

  void send_heartbeats() {
    char msg[32];
    int len = snprintf(msg, sizeof(msg), "HB %d", cfg_.index);
    for (const auto& p : peers_) {
      if (p.ip.empty()) continue;
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(p.port > 0 ? p.port : cfg_.peer_port);
      if (inet_pton(AF_INET, p.ip.c_str(), &addr.sin_addr) != 1) continue;
      sendto(peer_fd_, msg, len, 0, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    }
  }

  void receive_heartbeats() {
    char buf[64];
    for (;;) {
      sockaddr_in src{};
      socklen_t srclen = sizeof(src);
      ssize_t n = recvfrom(peer_fd_, buf, sizeof(buf) - 1, MSG_DONTWAIT,
                           reinterpret_cast<sockaddr*>(&src), &srclen);
      if (n <= 0) return;
      buf[n] = '\0';
      int idx = -1;
      if (sscanf(buf, "HB %d", &idx) != 1 || idx < 0 ||
          idx >= static_cast<int>(peers_.size()))
        continue;
      // The socket is INADDR_ANY: only count a heartbeat as liveness for
      // index N when the datagram actually came from the address we resolved
      // for N — otherwise any pod on the cluster network could spoof peer
      // liveness and flip the domain READY before the slice is formed.
      if (!cfg_.hb_source_check) {
        peers_[idx].last_seen_ms = now_ms();
        continue;
      }
      const Peer& p = peers_[idx];
      char src_ip[INET_ADDRSTRLEN] = {0};
      inet_ntop(AF_INET, &src.sin_addr, src_ip, sizeof(src_ip));
      if (p.ip.empty() || p.ip != src_ip) {
        fprintf(stderr, "[slicewatchd] dropping HB %d from %s (expect %s)\n",
                idx, src_ip, p.ip.empty() ? "<unresolved>" : p.ip.c_str());
        continue;
      }
      // Single-host test mode distinguishes daemons by port override; the
      // sender's source port is its bound --peer-port, so verify it too.
      if (p.port > 0 && ntohs(src.sin_port) != p.port) {
        fprintf(stderr, "[slicewatchd] dropping HB %d from port %d (expect %d)\n",
                idx, ntohs(src.sin_port), p.port);
        continue;
      }
      peers_[idx].last_seen_ms = now_ms();
    }
  }

  void answer_status() {
    int fd = accept(status_fd_, nullptr, nullptr);
    if (fd < 0) return;
    // Bound the read: a client that connects and stalls must not freeze the
    // single-threaded loop (heartbeats stop → peers mark us stale).
    struct timeval tv = {0, 200000};  // 200 ms
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    char buf[16];
    ssize_t n = read(fd, buf, sizeof(buf));
    (void)n;
    std::string reply;
    if (ready()) {
      reply = "READY\n";
    } else {
      char detail[96];
      snprintf(detail, sizeof(detail), "NOT_READY resolved=%d/%d\n",
               resolved_count(), cfg_.expected);
      reply = detail;
    }
    ssize_t w = write(fd, reply.data(), reply.size());
    (void)w;
    close(fd);
  }

  int run() {
    int64_t next_hb = 0;
    while (!g_stop) {
      if (g_reload) {
        g_reload = 0;
        reload();
      }
      int64_t now = now_ms();
      if (now >= next_hb) {
        send_heartbeats();
        next_hb = now + cfg_.heartbeat_ms;
      }
      struct pollfd fds[2] = {
          {peer_fd_, POLLIN, 0},
          {status_fd_, POLLIN, 0},
      };
      int timeout = static_cast<int>(next_hb - now);
      if (timeout < 0) timeout = 0;
      int rc = poll(fds, 2, timeout);
      if (rc < 0) {
        if (errno == EINTR) continue;
        return perr("poll") ? 1 : 1;
      }
      if (fds[0].revents & POLLIN) receive_heartbeats();
      if (fds[1].revents & POLLIN) answer_status();
    }
    return 0;
  }

 private:
  bool perr(const char* what) {
    fprintf(stderr, "[slicewatchd] %s: %s\n", what, strerror(errno));
    return false;
  }

  Config cfg_;
  std::vector<Peer> peers_;
  int peer_fd_ = -1;
  int status_fd_ = -1;
};

}  // namespace

int main(int argc, char** argv) {
  Config cfg;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) {
        fprintf(stderr, "missing value for %s\n", a.c_str());
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--nodes-config") cfg.nodes_config = next();
    else if (a == "--hosts") cfg.hosts_path = next();
    else if (a == "--index") cfg.index = atoi(next());
    else if (a == "--expected") cfg.expected = atoi(next());
    else if (a == "--status-port") cfg.status_port = atoi(next());
    else if (a == "--peer-port") cfg.peer_port = atoi(next());
    else if (a == "--heartbeat-ms") cfg.heartbeat_ms = atoi(next());
    else if (a == "--stale-ms") cfg.stale_ms = atoi(next());
    else if (a == "--no-hb-source-check") cfg.hb_source_check = false;
    else {
      fprintf(stderr, "unknown flag %s\n", a.c_str());
      return 2;
    }
  }
  if (cfg.nodes_config.empty()) {
    fprintf(stderr, "--nodes-config is required\n");
    return 2;
  }
  signal(SIGHUP, on_sighup);
  signal(SIGTERM, on_term);
  signal(SIGINT, on_term);

  SliceWatch sw(cfg);
  if (!sw.init()) return 1;
  fprintf(stderr,
          "[slicewatchd] up: index=%d expected=%d peer-port=%d status-port=%d\n",
          cfg.index, cfg.expected, cfg.peer_port, cfg.status_port);
  return sw.run();
}
